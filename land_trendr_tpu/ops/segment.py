"""``jax_segment_pixels`` — the batched TPU LandTrendr kernel.

This operator replaces the reference's per-pixel execution path at the
``LandTrendrMapper``/``PixelSegmenter`` plugin seam (SURVEY.md §2,
BASELINE.json north_star): instead of one Hadoop map task per pixel, whole
tiles of pixel time series live as HBM-resident ``(tile_px, year)`` arrays
and the full pipeline — despike, candidate-vertex search, anchored
piecewise-linear least squares, F-statistic model selection — runs as one
vmapped, jit-compiled XLA program with **no cross-pixel collectives**.

Semantics are defined by the CPU oracle
(:mod:`land_trendr_tpu.models.oracle`); this kernel is its fixed-shape,
branchless re-expression (SURVEY.md §7 design stance):

* dynamic vertex insertion/removal → boolean vertex masks over the static
  year axis, updated in ``lax.fori_loop``s with *fixed* trip counts and
  no-op guards;
* per-segment regressions → masked closed-form least squares driven by a
  small ``(segments, years)`` membership matrix (a tiny matmul-shaped
  contraction the TPU handles natively);
* data-dependent branches → ``jnp.where`` selects; every division is
  guarded so masked/degenerate lanes stay finite;
* argmax/argmin tie-breaking matches the oracle exactly (first index).

All math runs in the input dtype: float64 (with ``JAX_ENABLE_X64``) for
exact-parity testing against the oracle on CPU, float32 on TPU.

**Float32 tolerance contract** (SURVEY.md §7 step 2 "f32 on TPU with
documented tolerance"): in float64 the kernel matches the oracle
vertex-for-vertex.  In float32 the pipeline's argmax/argmin decisions
(spike selection, deviation insertion, angle culls) sit on knife edges for
noise-chasing candidates, and XLA fusion choices (which legally vary with
batch size and platform) can flip them by one ulp.  The historically
dominant failure mode — betainc underflow collapsing the far-tail model
selection (p ≪ 1e-38 family members all rounding to 0) — is fixed by the
log-space selection score (``_f_stat_p_and_logp``).  **Measured** over 1M
mixed-regime synthetic pixels f32-vs-f64 (``tools/parity_f32.py`` →
``PARITY_f32.json``): exact vertex agreement ≳ 99.99%, residual
disagreements are single knife-edge vertex placements, fitted
trajectories agree to ~1e-6 at p99.  ``tests/test_f32_quality.py`` gates
a ≥ 99.9% agreement floor.  Note the tail: a *disagreeing* pixel can
change model family entirely (different vertex count ⇒ rmse deltas up to
~0.07 on individual pixels in the measured run) — the contract bounds how
*often* decisions flip, not how far a flipped pixel's outputs move.
Pipelines that need bit-exact vertex parity should run the f64 path
(CPU, or TPU with x64 at a large slowdown).  The committed artifact's
``platform`` field records where it was measured; fusion-order effects
are platform-specific.  **Deliberate deferral** (VERDICT r4 weak #5):
no *reduction* of the knife-edge tail is attempted — candidate fixes
(widened compare margins at the argmax knife edges, f32x2 double-float
angle compares) would slow every pixel to move a ~1e-4 population whose
flips are already individually harmless and collectively gated; revisit
only if a use case needs sub-1e-4 flip rates without paying for f64.  **Measured on real TPU v5 lite hardware**
(round 4, ``PARITY_f32_tpu.json``, 1M px): 99.987% exact vertex
agreement vs the f64 CPU oracle, fitted-trajectory p99 delta 1.8e-6 —
the same tail class as CPU f32.  (The pre-rewrite kernel measured
48.9% on identical inputs: the TPU dynamic gather/scatter lowering this
rewrite eliminated was not merely slow but decision-flipping —
TPU_KERNEL_DIAG_r04.md §5.)

Shape/naming conventions: ``NY`` = years (static), ``NC`` =
``max_segments + 1 + vertex_count_overshoot`` candidate-vertex capacity,
``NV`` = ``max_segments + 1`` final vertex capacity, ``NM`` =
``max_segments`` model-family slots.

**The Pallas revisit trigger fired in round 4, and the Pallas kernel
exists.**  Rounds 1-3 reasoned (from CPU profiles) that a Pallas kernel
could not win; the first real TPU profile proved the opposite: this XLA
kernel is instruction-bound at ~3.4M px/s because the ``(px, NY)``
layout wastes 88/128 of every vector register and stage boundaries force
HBM round trips.  :mod:`land_trendr_tpu.ops.segment_pallas` implements
stages 1-4a in a ``(NY, BLK)`` year-major Pallas kernel (zero lane
padding, whole pipeline VMEM-resident per block) and reuses this
module's ``_select_and_assemble`` tail; it passes the f64 oracle-parity
suite bit-for-bit in interpret mode and measured 100% decision-identical
to this kernel on real-TPU f32 at 65536 px.  THIS module remains the
portable reference implementation (CPU, any backend, f64) and the
semantics anchor: any Pallas change must keep ``tests/test_pallas.py``
bit-green against it.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.utils.profiling import (
    SCOPE_ANGLE_CULL,
    SCOPE_DESPIKE,
    SCOPE_MODEL_FAMILY,
    SCOPE_MODEL_SELECT,
    SCOPE_VERTEX_SEARCH,
)

__all__ = [
    "SegOutputs",
    "segment_pixel",
    "jax_segment_pixels",
    "jax_segment_pixels_chunked",
]

_EPS_RATE = 1e-12  # must match oracle._segment_violates


# ---------------------------------------------------------------------------
# One-hot access helpers
#
# Batched dynamic gather/scatter serializes on TPU: one 40-index row gather
# at 65536 px was MEASURED at 21.7 ms against 0.17 ms for the equivalent
# one-hot where-sum contraction, and the gather-heavy round-3 kernel ran at
# 40k px/s on a chip simultaneously sustaining 15 TFLOP/s on matmuls
# (TPU_KERNEL_DIAG_r04.md §§1-3).  Every traced-index read/write in this
# kernel therefore goes through the helpers below.  Bit-exactness: the
# where-sum adds the selected element plus explicit zeros, so the result is
# identical to the gather term for term *up to the sign of zero* (a gathered
# -0.0 becomes +0.0, since -0.0 + 0.0 == +0.0; behaviourally neutral — every
# downstream compare treats them equal — and invisible to the == -based
# parity suites), and NaN-safe against garbage in never-selected slots —
# ``where`` masks before the multiply-free sum.
# ---------------------------------------------------------------------------


def _gather_oh(vec: jnp.ndarray, oh: jnp.ndarray) -> jnp.ndarray:
    """``vec[idx]`` given a precomputed one-hot ``oh = idx[..., None] == iota``."""
    if vec.dtype == jnp.bool_:
        return jnp.any(oh & vec, axis=-1)
    return jnp.sum(jnp.where(oh, vec, jnp.zeros((), vec.dtype)), axis=-1)


def _gather_1d(vec: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``vec[idx]`` for in-range integer ``idx`` (any shape), one-hot form."""
    return _gather_oh(vec, idx[..., None] == jnp.arange(vec.shape[0]))


def _fill_forward(
    vals: jnp.ndarray, valid: jnp.ndarray, *, exclusive: bool = False
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """``(filled, has)``: per slot, the value at the nearest valid slot at
    (``exclusive=False``) or strictly before (``exclusive=True``) it, and
    whether one exists; 0.0 where none.

    Log-doubling select chain — pure elementwise + static shifts, so XLA
    fuses it into O(1) passes where the equivalent (NY, NY) one-hot
    contraction pays a 40-way reduction.  Bit-exact: the result is a
    *selected* element, never an arithmetic combination.
    """
    n = vals.shape[0]
    v = jnp.where(valid, vals, jnp.zeros((), vals.dtype))
    has = valid
    if exclusive:
        v = jnp.concatenate([jnp.zeros_like(v[:1]), v[:-1]])
        has = jnp.concatenate([jnp.zeros_like(has[:1]), has[:-1]])
    sh = 1
    while sh < n:
        v = jnp.where(has, v, jnp.concatenate([jnp.zeros_like(v[:sh]), v[:-sh]]))
        has = has | jnp.concatenate([jnp.zeros_like(has[:sh]), has[:-sh]])
        sh *= 2
    return v, has


def _fill_backward(
    vals: jnp.ndarray, valid: jnp.ndarray, *, exclusive: bool = False
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Mirror of :func:`_fill_forward`: nearest valid slot at/after each slot."""
    v, has = _fill_forward(vals[::-1], valid[::-1], exclusive=exclusive)
    return v[::-1], has[::-1]


class SegOutputs(NamedTuple):
    """Per-pixel outputs; mirrors ``oracle.SegmentationResult`` field for field.

    Under :func:`jax_segment_pixels` every field gains a leading pixel axis.
    """

    n_vertices: jnp.ndarray      # () int32
    vertex_indices: jnp.ndarray  # (NV,) int32, padded -1
    vertex_years: jnp.ndarray    # (NV,)
    vertex_src_vals: jnp.ndarray # (NV,)
    vertex_fit_vals: jnp.ndarray # (NV,)
    seg_magnitude: jnp.ndarray   # (NM,)
    seg_duration: jnp.ndarray    # (NM,)
    seg_rate: jnp.ndarray        # (NM,)
    rmse: jnp.ndarray            # ()
    p_of_f: jnp.ndarray          # ()
    model_valid: jnp.ndarray     # () bool
    fitted: jnp.ndarray          # (NY,)
    despiked: jnp.ndarray        # (NY,)


# ---------------------------------------------------------------------------
# Stage 1 — despike (oracle.despike)
# ---------------------------------------------------------------------------


def _despike(
    t: jnp.ndarray, y: jnp.ndarray, mask: jnp.ndarray, n_valid: jnp.ndarray,
    params: LTParams,
) -> jnp.ndarray:
    """Iterative largest-spike dampening (oracle.despike).

    Early-exit ``while_loop`` (profile-driven, PROFILE_r03.json: despike was
    33% of kernel time as a fixed NY-trip ``fori_loop``): the oracle stops
    at the first iteration where no spike exceeds the threshold, and a
    no-op iteration leaves ``y`` unchanged so every later iteration is also
    a no-op — stopping there is exact.  Typical series carry 0–3 spikes, so
    the loop runs ~spikes+1 trips instead of NY.  Under vmap the batch runs
    until its LAST pixel converges — still far below NY in practice — and
    the oracle's ``n_valid`` cap bounds the worst case.
    """
    ny = y.shape[0]
    if params.spike_threshold >= 1.0:
        return y
    iota = jnp.arange(ny)
    # nearest-valid-neighbour reads are forward/backward fills along the
    # year axis (log-doubling select chains — see _fill_forward); the
    # filled VALUES equal y[prev]/y[next] bit-for-bit wherever a neighbour
    # exists, and `interior` masks every slot where one does not.  The body
    # keeps the oracle's exact multiply-then-divide order, so hoisting the
    # subtractions (bit-exact selected reads) cannot move a single ulp.
    tp, has_prev = _fill_forward(t, mask, exclusive=True)
    tq, has_nxt = _fill_backward(t, mask, exclusive=True)
    interior = mask & has_prev & has_nxt
    dtp = t - tp
    denom = jnp.where(interior, tq - tp, 1.0)

    def body(carry):
        it, y, _ = carry
        yp, _ = _fill_forward(y, mask, exclusive=True)
        yq, _ = _fill_backward(y, mask, exclusive=True)
        itp = yp + (yq - yp) * dtp / denom
        dev = jnp.abs(y - itp)
        crossing = jnp.abs(yq - yp)
        prop = jnp.where(dev > 0.0, jnp.maximum(0.0, 1.0 - crossing / jnp.where(dev > 0.0, dev, 1.0)), 0.0)
        prop = jnp.where(interior, prop, -1.0)
        i = jnp.argmax(prop)  # first max — matches oracle tie-break
        prop_i = jnp.max(prop)  # == prop[i] exactly (same reduction winner)
        do = (prop_i > params.spike_threshold) & (it < n_valid)
        delta = jnp.where(do, (_gather_1d(itp, i) - _gather_1d(y, i)) * prop_i, 0.0)
        return it + 1, y + jnp.where(iota == i, delta, 0.0), do

    def cond(carry):
        it, _, cont = carry
        return cont & (it < ny)

    _, y, _ = lax.while_loop(cond, body, (jnp.asarray(0), y, jnp.asarray(True)))
    return y


# ---------------------------------------------------------------------------
# Masked closed-form least squares
# ---------------------------------------------------------------------------


def _masked_ols(t, y, member):
    """OLS intercept/slope per row of a (K, NY) membership matrix.

    Mean-centred formulation — identical to ``oracle._ols`` — so float64
    results match the oracle bit-for-bit up to summation order.
    """
    m = member.astype(t.dtype)
    n = jnp.sum(m, axis=-1)
    n_safe = jnp.maximum(n, 1.0)
    tm = jnp.sum(m * t, axis=-1) / n_safe
    ym = jnp.sum(m * y, axis=-1) / n_safe
    tc = (t - tm[:, None]) * m
    stt = jnp.sum(tc * (t - tm[:, None]), axis=-1)
    sty = jnp.sum(tc * (y - ym[:, None]), axis=-1)
    ok = (n >= 2.0) & (stt > 0.0)
    slope = jnp.where(ok, sty / jnp.where(ok, stt, 1.0), 0.0)
    intercept = ym - slope * tm
    return intercept, slope


# ---------------------------------------------------------------------------
# Stage 2 — candidate vertex search + angle cull
# ---------------------------------------------------------------------------


def _vertex_positions(vmask: jnp.ndarray, size: int) -> jnp.ndarray:
    """Sorted vertex positions, padded with NY (an out-of-range sentinel).

    Rank-keyed one-hot instead of ``jnp.nonzero(size=...)`` (whose
    compaction lowers to scatter on TPU): slot ``k`` takes the year whose
    running set-bit count is ``k + 1``; empty slots take NY.
    """
    ny = vmask.shape[0]
    rank = jnp.cumsum(vmask) - 1
    oh = vmask[None, :] & (rank[None, :] == jnp.arange(size)[:, None])
    pos = jnp.sum(jnp.where(oh, jnp.arange(ny)[None, :], 0), axis=-1)
    return jnp.where(jnp.any(oh, axis=-1), pos, ny)


def _find_candidates(t, y, mask, vmask0, params: LTParams):
    """Grow the vertex mask by max-deviation insertion (oracle
    ``find_candidate_vertices``); NC-2 fixed iterations with no-op guards.

    Incremental formulation (profile-driven, PROFILE_r03.json: the full
    (NC-1, NY) membership-OLS recompute per insertion made vertex search
    the kernel's largest stage at 37.5%): per-segment OLS coefficients live
    in NY-slot caches keyed by the segment's START position.  Inserting a
    vertex at ``i`` into segment ``[lo, hi]`` refits only the two halves
    ``[lo, i]`` / ``[i, hi]``; every other segment's coefficients — the
    same ``_masked_ols`` arithmetic over the same members — are reused
    unchanged, so every deviation/argmax decision is identical to the full
    recompute (and to the oracle)."""
    ny = y.shape[0]
    nc = params.max_candidates
    iota = jnp.arange(ny)
    dtype = y.dtype

    def fit_two(los, his):
        """(2,) c0/c1 for two segments [los[k], his[k]] (masked years)."""
        member = (
            (iota[None, :] >= los[:, None])
            & (iota[None, :] <= his[:, None])
            & mask[None, :]
        )
        return _masked_ols(t, y, member)

    # initial cache: the single segment [first vertex, last vertex]
    lo0 = jnp.argmax(vmask0)
    hi0 = ny - 1 - jnp.argmax(vmask0[::-1])
    c0i, c1i = fit_two(jnp.stack([lo0, lo0]), jnp.stack([hi0, hi0]))
    zero = jnp.zeros((), dtype)
    c0v = jnp.where(iota == lo0, c0i[0], zero)
    c1v = jnp.where(iota == lo0, c1i[0], zero)

    def body(_, carry):
        vmask, c0v, c1v = carry
        # segment of year j = the one starting at the largest vertex <= j:
        # c0v/c1v[seg_start] are forward fills of the caches over the
        # vertex mask (same selected values, no (NY, NY) contraction)
        seg_start = jnp.clip(lax.cummax(jnp.where(vmask, iota, -1)), 0, ny - 1)
        c0_at, _ = _fill_forward(c0v, vmask)
        c1_at, _ = _fill_forward(c1v, vmask)
        dev = jnp.abs(y - (c0_at + c1_at * t))
        vpos = _vertex_positions(vmask, nc)
        eligible = mask & ~vmask & (iota > vpos[0]) & (iota < _last_vertex(vpos, ny))
        dev = jnp.where(eligible, dev, -1.0)
        i = jnp.argmax(dev)
        do = jnp.max(dev) >= 0.0  # == dev[i] (same reduction winner)
        # split [lo, hi] at i: refit just the two halves
        lo = _gather_1d(seg_start, i)
        hi = jnp.clip(jnp.min(jnp.where(vmask & (iota > i), iota, ny)), 0, ny - 1)
        c0n, c1n = fit_two(jnp.stack([lo, i]), jnp.stack([i, hi]))
        # .at[lo].set(·).at[i].set(·) overwrite order: i wins a collision
        c0v = jnp.where(
            do & (iota == i), c0n[1], jnp.where(do & (iota == lo), c0n[0], c0v)
        )
        c1v = jnp.where(
            do & (iota == i), c1n[1], jnp.where(do & (iota == lo), c1n[0], c1v)
        )
        vmask = vmask | ((iota == i) & do)
        return vmask, c0v, c1v

    vmask, _, _ = lax.fori_loop(0, nc - 2, body, (vmask0, c0v, c1v))
    return vmask


def _last_vertex(vpos: jnp.ndarray, ny: int) -> jnp.ndarray:
    """Largest real (non-padded) vertex position."""
    return jnp.max(jnp.where(vpos < ny, vpos, -1))


def _vertex_angles(t, y, vpos, n_verts, t_lo, t_hi, y_lo, y_hi):
    """Angle change at interior vertices on axis-scaled data (oracle
    ``_vertex_angles``); padded / endpoint slots get +inf."""
    ny = t.shape[0]
    k = vpos.shape[0]
    vpos_c = jnp.clip(vpos, 0, ny - 1)
    t_rng = jnp.where(t_hi > t_lo, t_hi - t_lo, 1.0)
    y_rng = jnp.where(y_hi > y_lo, y_hi - y_lo, 1.0)
    oh_v = vpos_c[:, None] == jnp.arange(ny)[None, :]  # (K, NY)
    xs = (_gather_oh(t, oh_v) - t_lo) / t_rng
    ys = (_gather_oh(y, oh_v) - y_lo) / y_rng
    j = jnp.arange(k)
    interior = (j >= 1) & (j < n_verts - 1)
    dx1 = jnp.where(interior, xs - jnp.roll(xs, 1), 1.0)
    dx2 = jnp.where(interior, jnp.roll(xs, -1) - xs, 1.0)
    s1 = (ys - jnp.roll(ys, 1)) / dx1
    s2 = (jnp.roll(ys, -1) - ys) / dx2
    ang = jnp.abs(jnp.arctan(s2) - jnp.arctan(s1))
    return jnp.where(interior, ang, jnp.inf)


def _remove_weakest(t, y, vmask, scale, size, keep_above):
    """Drop the min-angle interior vertex while count > keep_above (one step)."""
    ny = t.shape[0]
    t_lo, t_hi, y_lo, y_hi = scale
    vpos = _vertex_positions(vmask, size)
    n_verts = jnp.sum(vmask)
    ang = _vertex_angles(t, y, vpos, n_verts, t_lo, t_hi, y_lo, y_hi)
    j = jnp.argmin(ang)  # first min — matches oracle tie-break
    do = n_verts > keep_above
    pos = jnp.clip(_gather_1d(vpos, j), 0, ny - 1)
    return jnp.where(do & (jnp.arange(ny) == pos), False, vmask)


# ---------------------------------------------------------------------------
# Stage 3 — anchored piecewise-linear fit (oracle.fit_model)
# ---------------------------------------------------------------------------


def _clamp_slope(slope, duration, y_range, params: LTParams):
    """Recovery-rate constraints on a candidate slope (disturbance-positive)."""
    limit = -params.recovery_threshold * y_range
    clamped = jnp.maximum(slope, limit)
    if params.prevent_one_year_recovery:
        clamped = jnp.where(duration <= 1.0, 0.0, clamped)
    active = (slope < 0.0) & (y_range > 0.0)
    return jnp.where(active, clamped, slope)


def _fit_model(t, y, mask, vmask, y_range, params: LTParams):
    """Anchored fit + point-to-point fallback for one vertex set.

    Returns ``(fitted_valid, sse)`` where ``fitted_valid`` is the fitted
    value at every (valid) year position and ``sse`` sums over valid years.
    """
    ny = t.shape[0]
    nv = params.max_vertices
    iota = jnp.arange(ny)
    vpos = _vertex_positions(vmask, nv)
    n_verts = jnp.sum(vmask)
    vpos_c = jnp.clip(vpos, 0, ny - 1)
    # one (NV, NY) one-hot serves every vertex-position read in this fit:
    # tv[k] == t[vpos_c[k]], yv[k] == y[vpos_c[k]], bit-exactly
    oh_vc = vpos_c[:, None] == iota[None, :]
    tv = _gather_oh(t, oh_vc)
    yv = _gather_oh(y, oh_vc)

    # --- segment 0: OLS over closed [v0, v1] ---
    member0 = (iota >= vpos[0]) & (iota <= vpos[1]) & mask
    c0, c1 = _masked_ols(t, y, member0[None, :])
    c0, c1 = c0[0], c1[0]
    dur0 = tv[1] - tv[0]
    c1c = _clamp_slope(c1, dur0, y_range, params)
    # intercept is ym - slope*tm for both the clamped and unclamped slope
    m0 = member0.astype(t.dtype)
    n0 = jnp.maximum(jnp.sum(m0), 1.0)
    c0 = jnp.sum(m0 * y) / n0 - c1c * (jnp.sum(m0 * t) / n0)
    fitted = jnp.where(member0, c0 + c1c * t, 0.0)
    anchor_t = tv[1]
    anchor_y = c0 + c1c * anchor_t

    # --- segments 1..: slope-only regression through the anchor ---
    # Python-unrolled (NV is static and small): the fori_loop formulation
    # forced dynamic vpos[k] picks per trip; unrolled, every vertex read is
    # a static slice of tv/vpos and XLA fuses across segments.  Same ops in
    # the same order as the former loop body — bit-exact.
    for k in range(1, nv - 1):
        a, b = vpos[k], vpos[k + 1]
        active = (k + 1) < n_verts
        member = (iota > a) & (iota <= b) & mask & active
        m = member.astype(t.dtype)
        dt = (t - anchor_t) * m
        denom = jnp.sum(dt * dt)
        slope = jnp.where(denom > 0.0, jnp.sum(dt * (y - anchor_y)) / jnp.where(denom > 0.0, denom, 1.0), 0.0)
        slope = _clamp_slope(slope, tv[k + 1] - anchor_t, y_range, params)
        fitted = jnp.where(member, anchor_y + slope * (t - anchor_t), fitted)
        new_anchor_y = anchor_y + slope * (tv[k + 1] - anchor_t)
        anchor_t = jnp.where(active, tv[k + 1], anchor_t)
        anchor_y = jnp.where(active, new_anchor_y, anchor_y)

    # --- point-to-point fallback (vectorized over segments) ---
    # Per-element arithmetic is identical to the former per-segment
    # fori_loop (same gathers, same multiply/divide order), so f64 oracle
    # parity is preserved; the loop's "later segment wins at shared vertex
    # years" overwrite order is reproduced by ``seg_of`` assigning a vertex
    # year to the segment STARTING at it.
    ks = jnp.arange(nv - 1)
    a_s, b_s = vpos[:-1], vpos[1:]                  # (NV-1,) segment bounds
    active_s = (ks + 1) < n_verts
    dur_s = tv[1:] - tv[:-1]                        # == t[b_sc] - t[a_sc]
    dy_s = yv[1:] - yv[:-1]
    # oracle._segment_violates
    viol_s = (dy_s < 0.0) & (y_range > 0.0) & (dur_s > 0.0)
    if params.prevent_one_year_recovery:
        fast_s = dur_s <= 1.0
    else:
        fast_s = jnp.zeros_like(viol_s)
    viol_s = viol_s & (
        fast_s
        | (
            (-dy_s) / jnp.where(dur_s > 0.0, dur_s, 1.0)
            > params.recovery_threshold * y_range + _EPS_RATE
        )
    )
    p2p_ok = ~jnp.any(viol_s & active_s)
    rate_s = jnp.where(dur_s > 0.0, dy_s / jnp.where(dur_s > 0.0, dur_s, 1.0), 0.0)
    # the loop's overwrite order gives a shared vertex year to the segment
    # STARTING at it — except the last vertex, which only its preceding
    # segment contains; min(·, n_verts-2) reproduces that
    seg_of = jnp.clip(
        jnp.minimum(jnp.cumsum(vmask) - 1, n_verts - 2), 0, nv - 2
    )
    oh_seg = seg_of[:, None] == ks[None, :]          # (NY, NV-1)
    member_y = (
        (iota >= vpos[0])
        & (iota <= _last_vertex(vpos, ny))
        & mask
        & _gather_oh(active_s, oh_seg)
    )
    p2p0 = jnp.where((iota == vpos[0]) & mask, y, 0.0)
    # y[a_sc[seg_of]] == (y[a_sc])[seg_of] == yv[:-1][seg_of]; same for t
    p2p = jnp.where(
        member_y,
        _gather_oh(yv[:-1], oh_seg)
        + _gather_oh(rate_s, oh_seg) * (t - _gather_oh(tv[:-1], oh_seg)),
        p2p0,
    )

    # SSE over the vertex span only (oracle fit_model: "SSE comparisons use
    # only the vertex span").  In the segmentation pipeline the vertices span
    # the whole valid range so this equals a full-mask sum; FTV vertex sets
    # may start/end inside the valid range, where the distinction matters.
    span = mask & (iota >= vpos[0]) & (iota <= _last_vertex(vpos, ny))
    sse_reg = jnp.sum(jnp.where(span, (y - fitted) ** 2, 0.0))
    sse_p2p = jnp.sum(jnp.where(span, (y - p2p) ** 2, 0.0))
    use_p2p = p2p_ok & (sse_p2p < sse_reg)
    fitted = jnp.where(use_p2p, p2p, fitted)
    sse = jnp.where(use_p2p, sse_p2p, sse_reg)
    return fitted, sse


# ---------------------------------------------------------------------------
# Stage 4 — F-statistic scoring (oracle.f_stat_p_value)
# ---------------------------------------------------------------------------


def _interp_through_vertices(t, vmask, fitted, pad_t, size):
    """Full-year trajectory through the live vertices of ``vmask``.

    Padded vertex slots repeat ``(pad_t, last live vertex fit)`` so the
    extension beyond the last vertex is flat — exactly ``np.interp``'s edge
    behaviour, which the oracle relies on.  ``pad_t`` must be >= the last
    live vertex's year so ``xp`` stays non-decreasing.
    """
    ny = t.shape[0]
    vpos = _vertex_positions(vmask, size)
    k = jnp.sum(vmask)
    live = jnp.arange(size) < k
    vpos_c = jnp.clip(vpos, 0, ny - 1)
    oh_vc = vpos_c[:, None] == jnp.arange(ny)[None, :]
    vfit = _gather_oh(fitted, oh_vc)
    last_fit = _gather_1d(vfit, jnp.clip(k - 1, 0, size - 1))
    xp = jnp.where(live, _gather_oh(t, oh_vc), pad_t)
    fp = jnp.where(live, vfit, last_fit)
    # ``jnp.interp(t, xp, fp)`` replica, gather-free: reproduces
    # jax._src.numpy.lax_numpy._interp's arithmetic term for term (same
    # epsilon guard, same (delta / dx) * df association, same edge clamps);
    # searchsorted(xp, x, side='right') over the sorted xp equals the count
    # of xp entries <= x.
    i = jnp.clip(jnp.sum(xp[None, :] <= t[:, None], axis=-1), 1, size - 1)
    sj = jnp.arange(size)
    oh_i = i[:, None] == sj[None, :]
    oh_im1 = (i - 1)[:, None] == sj[None, :]
    fp_i = _gather_oh(fp, oh_i)
    fp_im1 = _gather_oh(fp, oh_im1)
    xp_i = _gather_oh(xp, oh_i)
    xp_im1 = _gather_oh(xp, oh_im1)
    df = fp_i - fp_im1
    dx = xp_i - xp_im1
    delta = t - xp_im1
    epsilon = np.spacing(np.finfo(t.dtype).eps)
    dx0 = jnp.abs(dx) <= epsilon
    f = jnp.where(dx0, fp_im1, fp_im1 + (delta / jnp.where(dx0, 1, dx)) * df)
    f = jnp.where(t < xp[0], fp[0], f)
    f = jnp.where(t > xp[-1], fp[-1], f)
    return f


def _f_stat_p(ss0, sse, n, m):
    """p-of-F with df1 = 2m-1, df2 = n-2m via the regularised incomplete beta."""
    df1 = 2.0 * m - 1.0
    df2 = n - 2.0 * m
    invalid = (df2 < 1.0) | (ss0 <= 0.0) | (sse >= ss0)
    perfect = (sse <= 0.0) & ~invalid
    df1s = jnp.maximum(df1, 1.0)
    df2s = jnp.maximum(df2, 1.0)
    sse_s = jnp.where(perfect | invalid, 1.0, sse)
    f = ((ss0 - sse_s) / df1s) / (sse_s / df2s)
    f = jnp.maximum(f, 0.0)
    x = df2s / (df2s + df1s * f)
    p = jax.scipy.special.betainc(df2s / 2.0, df1s / 2.0, x)
    return jnp.where(invalid, 1.0, jnp.where(perfect, 0.0, p))


# Sentinel log-p for a perfect (sse == 0) model: far below any series value
# (series log-p bottoms out around -2100 for the largest dof), finite so no
# inf arithmetic leaks into selects.
_LOGP_PERFECT = -1e30

_HALF_LOG_2PI = 0.9189385332046727  # 0.5 * log(2*pi)


def _lgamma_fixed(x: jnp.ndarray) -> jnp.ndarray:
    """``log Gamma(x)`` for ``x >= 0.5`` — fixed 8-step shift + Stirling.

    ``lax.lgamma`` has no Mosaic (Pallas TPU) lowering, and the fused
    Pallas tail must score models with arithmetic *identical* to this XLA
    path for the on-chip impl-identity contract — so both paths share this
    plain-arithmetic form: ``lgamma(x) = lgamma(x+8) - log(x(x+1)…(x+7))``
    with a 3-term Stirling series at ``x+8 >= 8.5`` (truncation ~2e-10;
    float32 rounding dominates at ~5e-5 abs worst-case over this
    pipeline's argument range ``x <= (NY+10)/2``).  Swapping it in for
    ``lax.lgamma`` *tightened* the measured Lentz envelope on the scoring
    grid (max rel p error 6.7e-5 -> 4.6e-5 under XLA CPU f32; gated by
    ``tests/test_f32_quality.py``).  Arguments here are the F-test's
    half-integers ``df/2 >= 0.5``, so no reflection branch is needed.
    """
    dtype = x.dtype
    prod = x
    for j in range(1, 8):
        prod = prod * (x + jnp.asarray(float(j), dtype))
    z = x + jnp.asarray(8.0, dtype)
    zi = jnp.asarray(1.0, dtype) / z
    zi2 = zi * zi
    series = zi * (
        jnp.asarray(1.0 / 12.0, dtype)
        + zi2
        * (jnp.asarray(-1.0 / 360.0, dtype) + zi2 * jnp.asarray(1.0 / 1260.0, dtype))
    )
    lg = (z - 0.5) * jnp.log(z) - z + jnp.asarray(_HALF_LOG_2PI, dtype) + series
    return lg - jnp.log(prod)


def _lentz_iters(ny: int) -> int:
    """Lentz trip count for a pipeline whose year axis has ``ny`` entries.

    The continued fraction's worst case over this pipeline's argument
    range converges in ~O(sqrt(max(a, b))) half-step pairs with
    ``max(a, b) <= (ny + 10) / 2``; 12 trips are validated for NY <= 40
    (the accuracy-envelope gate in ``tests/test_f32_quality.py``), and the
    sqrt rule keeps the envelope for longer stacks (validated on the
    extended NY = 100 grid by the same test) instead of silently
    degrading — a 100-year series gets 18 trips, not 12.  Truncation,
    not ceil: NY = 40 must map to exactly the validated 12 (2.5·√25 =
    12.5), keeping production bit-identical to every gate and artifact
    measured at the default trip count.
    """
    return max(12, int(2.5 * np.sqrt((ny + 10) / 2.0)))


def _betainc_p_and_logp_lentz(a, b, x, iters: int = 12):
    """``(p, log p)`` of the regularised incomplete beta in ONE fixed-trip pass.

    Float32 scoring speed fix (round 4, measured on TPU v5 lite at 262144
    px: ``jax.scipy.special.betainc``-based scoring 13.0 ms/step — the
    entire XLA tail cost of the Pallas pipeline — vs ~4 ms for this):
    modified-Lentz evaluation of the continued fraction with a FIXED trip
    count instead of XLA's convergence loop, emitting both the linear p
    and the log-form.  The log form comes from ``log(front) + log(cf)``
    directly — no underflow at any dof in this pipeline — which also
    retires the separate 40-term deep-tail series the selection scores
    previously needed.

    Accuracy (validated against scipy f64 over the full (a, b, x) grid
    this pipeline can produce — n in [6, 40], m in [1, 6], F in [1e-3,
    1e4]): max relative p error 4.6e-5 under XLA CPU f32 with the shared
    :func:`_lgamma_fixed` (round 5; the previous ``lax.lgamma`` form
    measured 6.7e-5 — gated by ``tests/test_f32_quality.py``), p99 9e-6;
    log-p abs error p99 1e-5 including the deep tail; converged by 12
    iterations for NY <= 40 (12 == 24 half-steps; the error floor is f32
    rounding, not truncation).  For longer year axes pass
    ``iters=_lentz_iters(ny)`` — the sqrt-of-dof rule the pipeline
    callers use; the 12-trip default is only validated to NY = 40.  That
    widens the f32 knife-edge band for model-selection ties from ~1e-7
    to ~2e-5 relative — covered by the f32 tolerance contract and gated
    by ``tests/test_f32_quality.py``.  The float64 exact path
    (:func:`_f_stat_p`) keeps ``jax.scipy.special.betainc`` untouched.
    """
    dtype = x.dtype
    one = jnp.ones((), dtype)
    tiny = jnp.asarray(1e-30, dtype)
    swap = x >= (a + 1.0) / (a + b + 2.0)
    aa = jnp.where(swap, b, a)
    bb = jnp.where(swap, a, b)
    xx = jnp.where(swap, 1.0 - x, x)
    qab = aa + bb
    qap = aa + 1.0
    qam = aa - 1.0

    def guard(z):
        return jnp.where(jnp.abs(z) < tiny, tiny, z)

    c = jnp.ones_like(xx)
    d = one / guard(1.0 - qab * xx / qap)
    h = d
    for m in range(1, iters + 1):
        m2 = 2.0 * m
        num = m * (bb - m) * xx / ((qam + m2) * (aa + m2))
        d = one / guard(1.0 + num * d)
        c = guard(1.0 + num / c)
        h = h * d * c
        num = -(aa + m) * (qab + m) * xx / ((aa + m2) * (qap + m2))
        d = one / guard(1.0 + num * d)
        c = guard(1.0 + num / c)
        h = h * d * c

    log_front = (
        aa * jnp.log(jnp.maximum(xx, tiny))
        + bb * jnp.log1p(-xx)
        + _lgamma_fixed(qab)
        - _lgamma_fixed(aa)
        - _lgamma_fixed(bb)
        - jnp.log(aa)
    )
    p_small = jnp.exp(log_front) * h
    lp_small = log_front + jnp.log(jnp.maximum(h, tiny))
    p = jnp.where(swap, 1.0 - p_small, p_small)
    lp = jnp.where(
        swap,
        jnp.log1p(-jnp.minimum(p_small, jnp.asarray(1.0 - 1e-7, dtype))),
        lp_small,
    )
    return p, lp


def _f_stat_p_and_logp(ss0, sse, n, m, iters: int = 12):
    """``(p, log-p score)`` of the F test, underflow-proof in float32.

    Float32 model-selection hardening (measured on 64K mixed-regime pixels:
    99.74% exact-vertex agreement f32-vs-f64 before this, with ~99% of the
    residual disagreement in *model-family choice*, not vertex placement):
    strong signals push p-of-F below float32's ~1e-38 floor, ``betainc``
    returns 0.0 for *several* family members at once, and the oracle's
    ratio rule ``p <= p_best / best_model_proportion`` degenerates to
    "first model whose p rounds to zero".  The selection score is therefore
    log p, computed alongside the linear p by the fixed-trip Lentz
    evaluation (:func:`_betainc_p_and_logp_lentz`) — the log form is
    underflow-proof at every dof this pipeline produces, so no separate
    deep-tail series is needed (round 4; the previous
    ``log(betainc)``+series split cost 3× as much on TPU and its betainc
    convergence loop dominated the whole scoring stage).
    """
    dtype = ss0.dtype
    df1 = 2.0 * m - 1.0
    df2 = n - 2.0 * m
    invalid = (df2 < 1.0) | (ss0 <= 0.0) | (sse >= ss0)
    perfect = (sse <= 0.0) & ~invalid
    df1s = jnp.maximum(df1, 1.0)
    df2s = jnp.maximum(df2, 1.0)
    sse_s = jnp.where(perfect | invalid, 1.0, sse)
    f = ((ss0 - sse_s) / df1s) / (sse_s / df2s)
    f = jnp.maximum(f, 0.0)
    x = df2s / (df2s + df1s * f)
    a, b = df2s / 2.0, df1s / 2.0
    p_direct, lp = _betainc_p_and_logp_lentz(a, b, x, iters=iters)
    lp = jnp.where(
        invalid, 0.0, jnp.where(perfect, jnp.asarray(_LOGP_PERFECT, dtype), lp)
    )
    p = jnp.where(invalid, 1.0, jnp.where(perfect, 0.0, p_direct))
    return p, lp


# ---------------------------------------------------------------------------
# Top-level per-pixel kernel
# ---------------------------------------------------------------------------


def segment_pixel(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    params: LTParams,
) -> SegOutputs:
    """Full LandTrendr pipeline on one pixel (fixed shapes; vmap over pixels).

    Mirrors ``oracle.segment_series`` decision for decision; see the module
    docstring for the dynamic→static mapping.
    """
    dtype = jnp.result_type(values.dtype, jnp.float32)
    t = years.astype(dtype)
    v = values.astype(dtype)
    mask = mask.astype(bool) & jnp.isfinite(v)
    v = jnp.where(mask, v, 0.0)
    ny = t.shape[0]
    nv, nc, nm = params.max_vertices, params.max_candidates, params.max_segments
    iota = jnp.arange(ny)

    n_valid = jnp.sum(mask)

    # Stage 1 — despike
    with jax.named_scope(SCOPE_DESPIKE):
        y = _despike(t, v, mask, n_valid, params)
    big = jnp.asarray(jnp.finfo(dtype).max, dtype)
    y_lo = jnp.min(jnp.where(mask, y, big))
    y_hi = jnp.max(jnp.where(mask, y, -big))
    y_range = jnp.maximum(y_hi - y_lo, 0.0)

    first_v = jnp.argmax(mask)
    last_v = ny - 1 - jnp.argmax(mask[::-1])
    t_lo, t_hi = _gather_1d(t, first_v), _gather_1d(t, last_v)
    scale = (t_lo, t_hi, y_lo, y_hi)

    # Stage 2 — candidates + cull
    with jax.named_scope(SCOPE_VERTEX_SEARCH):
        vmask0 = mask & ((iota == first_v) | (iota == last_v))
        vmask = _find_candidates(t, y, mask, vmask0, params)
    with jax.named_scope(SCOPE_ANGLE_CULL):
        vmask = lax.fori_loop(
            0,
            params.vertex_count_overshoot,
            lambda _, vm: _remove_weakest(t, y, vm, scale, nc, nv),
            vmask,
        )

    # Stage 4 — model family: record each member's vertex set + fit SSE;
    # scoring/selection live in the shared tail (_select_and_assemble)
    def model_step(vm, _):
        fitted, sse = _fit_model(t, y, mask, vm, y_range, params)
        del fitted  # only the chosen model's trajectory is needed — it is
        # recomputed after selection, so the scan stacks NY bools + 2
        # scalars per model instead of an NY-float trajectory.  The
        # alternative (stack all NM trajectories, select after scoring)
        # was MEASURED 16% slower end-to-end on CPU (scan-stack write
        # traffic outweighs one extra _fit_model); _fit_model is
        # deterministic, so the recomputation is exact.
        #
        # A second rejected variant (round 4): derive the NM vertex masks
        # first (_remove_weakest never reads the fits) and vmap _fit_model
        # over the family axis — NM-fold shorter sequential chain, and
        # still bit-exact vs the oracle.  MEASURED 23% slower end-to-end
        # on CPU (18.2k vs 23.6k px/s, 65536 px, quiet box, best of 5):
        # with vmap over pixels already saturating the machine, batching
        # the family axis only materializes (px, NM, NY) intermediates
        # that the scan formulation never holds at once.  Worth re-timing
        # on real TPU hardware if a profile shows this stage
        # latency-bound rather than bandwidth-bound.
        vm_next = _remove_weakest(t, y, vm, scale, nv, 2)
        return vm_next, (vm, sse)

    with jax.named_scope(SCOPE_MODEL_FAMILY):
        _, (vmasks, sses) = lax.scan(model_step, vmask, None, length=nm)

    return _select_and_assemble(t, values.astype(dtype), mask, y, vmasks, sses, params)


def _select_and_assemble(
    t: jnp.ndarray,
    raw: jnp.ndarray,
    mask: jnp.ndarray,
    y: jnp.ndarray,
    vmasks: jnp.ndarray,
    sses: jnp.ndarray,
    params: LTParams,
) -> SegOutputs:
    """Scoring, model selection, and output assembly for one pixel.

    Shared tail of the pipeline: consumes the despiked series ``y`` and the
    model family (``vmasks`` (NM, NY) bool, ``sses`` (NM,)) however they
    were produced — the XLA scan in :func:`segment_pixel` or the Pallas
    family kernel (:mod:`land_trendr_tpu.ops.segment_pallas`) — and is the
    single definition of everything from the F-stat scoring onward.
    ``raw`` is the uncleaned (cast) input series; ``mask`` is the cleaned
    validity mask; ``t`` the cast year axis.
    """
    dtype = t.dtype
    ny = t.shape[0]
    nv, nm = params.max_vertices, params.max_segments
    iota = jnp.arange(ny)
    exact_mode = dtype == jnp.float64

    n_valid = jnp.sum(mask)
    enough = n_valid >= params.min_observations_needed
    big = jnp.asarray(jnp.finfo(dtype).max, dtype)
    y_lo = jnp.min(jnp.where(mask, y, big))
    y_hi = jnp.max(jnp.where(mask, y, -big))
    y_range = jnp.maximum(y_hi - y_lo, 0.0)
    last_v = ny - 1 - jnp.argmax(mask[::-1])
    t_hi = _gather_1d(t, last_v)
    ss0 = jnp.sum(jnp.where(mask, (y - jnp.sum(jnp.where(mask, y, 0.0)) / jnp.maximum(n_valid, 1)) ** 2, 0.0))

    # In float64 the selection scores are the linear p values — bit-exact
    # against the oracle's ratio rule.  In float32 the scores are log p
    # (underflow-proof; see _f_stat_p_and_logp) and the ratio rule becomes
    # the equivalent ``lp <= lp_best - log(best_model_proportion)``.
    ms = jnp.sum(vmasks, axis=-1) - 1  # (NM,) segments per model
    if exact_mode:
        ps = _f_stat_p(ss0, sses, n_valid.astype(dtype), ms.astype(dtype))
        scores = ps
    else:
        ps, scores = _f_stat_p_and_logp(
            ss0, sses, n_valid.astype(dtype), ms.astype(dtype),
            iters=_lentz_iters(ny),
        )

    # Selection: most segments whose p is within best_model_proportion of best
    with jax.named_scope(SCOPE_MODEL_SELECT):
        best = jnp.min(scores)
        if exact_mode:
            qualify = scores <= best / params.best_model_proportion
        else:
            qualify = scores <= best - jnp.log(
                jnp.asarray(params.best_model_proportion, dtype)
            )
        chosen = jnp.argmax(qualify)  # first (= most segments) qualifying model
        oh_m = jnp.arange(nm) == chosen
        vmask_c = _gather_oh(vmasks.T, oh_m)  # row select, one-hot over NM
        fitted_c, sse_c = _fit_model(t, y, mask, vmask_c, y_range, params)
        p_c = _gather_oh(ps, oh_m)

    model_valid = enough & (y_range > 0.0) & (p_c <= params.p_val_threshold)

    # --- assemble outputs (flat no-fit model when not model_valid) ---
    # The oracle's insufficient-data path never despikes, so its flat model
    # statistics come from the RAW valid values; the p-threshold / constant
    # no-fit paths run after despiking and use the despiked series
    # (oracle._flat_result's despiked_valid argument).
    has_any = n_valid > 0
    n_safe = jnp.maximum(n_valid, 1)
    mean_desp = jnp.where(has_any, jnp.sum(jnp.where(mask, y, 0.0)) / n_safe, 0.0)
    mean_raw = jnp.where(
        has_any, jnp.sum(jnp.where(mask, raw, 0.0)) / n_safe, 0.0
    )
    mean = jnp.where(enough, mean_desp, mean_raw)
    flat_src = jnp.where(enough, y, raw)

    vpos = _vertex_positions(vmask_c, nv)
    k = jnp.sum(vmask_c)
    live = jnp.arange(nv) < k
    vpos_c = jnp.clip(vpos, 0, ny - 1)
    oh_vc = vpos_c[:, None] == iota[None, :]  # (NV, NY): all vertex reads
    tvc = _gather_oh(t, oh_vc)                # t[vpos_c]
    vertex_indices = jnp.where(live & model_valid, vpos_c, -1).astype(jnp.int32)
    vertex_years = jnp.where(live & model_valid, tvc, 0.0)
    vertex_src = jnp.where(live & model_valid, _gather_oh(y, oh_vc), 0.0)
    vfit = _gather_oh(fitted_c, oh_vc)
    vertex_fit = jnp.where(live & model_valid, vfit, 0.0)

    sidx = jnp.arange(nm)
    seg_live = (sidx < k - 1) & model_valid
    mag = jnp.where(seg_live, vfit[1:] - vfit[:-1], 0.0)
    dur = jnp.where(seg_live, tvc[1:] - tvc[:-1], 0.0)
    rate = jnp.where(seg_live & (dur > 0.0), mag / jnp.where(dur > 0.0, dur, 1.0), 0.0)

    fitted_full = _interp_through_vertices(
        t, vmask_c, fitted_c, t_hi, nv
    )
    fitted_full = jnp.where(model_valid, fitted_full, mean)

    rmse_fit = jnp.sqrt(sse_c / n_safe)
    rmse_flat = jnp.sqrt(
        jnp.sum(jnp.where(mask, (flat_src - mean) ** 2, 0.0)) / n_safe
    )
    rmse = jnp.where(model_valid, rmse_fit, jnp.where(has_any, rmse_flat, 0.0))

    # despiked output: valid slots get the despiked series; invalid slots keep
    # the raw input when a model fit happened, the flat mean otherwise
    # (oracle.segment_series / oracle._flat_result — which keeps raw valid
    # values on the insufficient-data path)
    despiked_fit = jnp.where(mask, y, raw)
    despiked_flat = jnp.where(mask, flat_src, mean)
    despiked = jnp.where(model_valid, despiked_fit, despiked_flat)

    return SegOutputs(
        n_vertices=jnp.where(model_valid, k, 0).astype(jnp.int32),
        vertex_indices=vertex_indices,
        vertex_years=vertex_years,
        vertex_src_vals=vertex_src,
        vertex_fit_vals=vertex_fit,
        seg_magnitude=mag,
        seg_duration=dur,
        seg_rate=rate,
        rmse=rmse,
        p_of_f=jnp.where(model_valid, p_c, 1.0),
        model_valid=model_valid,
        fitted=fitted_full,
        despiked=despiked,
    )


@functools.partial(jax.jit, static_argnames=("params", "chunk"))
def jax_segment_pixels_chunked(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    params: LTParams = LTParams(),
    chunk: int = 262144,
) -> SegOutputs:
    """:func:`jax_segment_pixels` with HBM bounded by ``chunk`` pixels.

    The kernel's transient working set is linear in the pixel axis (the
    model-family scan and vertex bookkeeping), so one huge batch can exceed
    HBM where many chunks do not — e.g. a 4M-pixel 40-year batch needs
    >16 GB transient on v5e while 16 × 256K chunks stream through
    comfortably.  ``lax.map`` runs the chunks *sequentially inside one
    compiled program*: outputs for all pixels accumulate in HBM (they are
    what the caller asked for) while per-chunk temporaries are reused.

    The pixel count must be a multiple of ``chunk`` (pad with fully-masked
    rows — :func:`land_trendr_tpu.parallel.pad_to_multiple`).  Per-pixel
    *decisions* (vertex placement, model selection, validity) are identical
    to the unchunked kernel's; float outputs are numerically identical up to
    compilation-order rounding (``lax.map`` legally re-fuses reductions, so
    fields like ``p_of_f`` may differ at the last ulp, ~1e-15 relative).
    The f32 tolerance contract in the module docstring applies unchanged.
    """
    px = values.shape[0]
    if px % chunk:
        raise ValueError(
            f"pixel count {px} not a multiple of chunk {chunk}; pad first"
        )
    v = values.reshape(px // chunk, chunk, values.shape[1])
    m = mask.reshape(px // chunk, chunk, mask.shape[1])
    out = lax.map(
        lambda vm: jax_segment_pixels(years, vm[0], vm[1], params), (v, m)
    )
    return SegOutputs(*(o.reshape(px, *o.shape[2:]) for o in out))


@functools.partial(jax.jit, static_argnames=("params",))
def jax_segment_pixels(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    params: LTParams = LTParams(),
) -> SegOutputs:
    """Segment a batch of pixel time series on device.

    Parameters
    ----------
    years : (NY,) shared year axis.
    values : (PX, NY) spectral-index series, disturbance-positive convention.
    mask : (PX, NY) bool validity mask.
    params : static LTParams — one compilation per parameter set.

    Returns
    -------
    SegOutputs with a leading PX axis on every field.
    """
    return jax.vmap(lambda v, m: segment_pixel(years, v, m, params))(values, mask)
