"""FTV — fitted-trajectory values for secondary indices (batched, on device).

Classic LandTrendr (SURVEY.md §3.1 outputs) fits *other* spectral indices to
the vertex years chosen by the segmentation index: the vertex set is fixed,
and the target series is anchored-least-squares fitted through those years.
The CPU oracle's :func:`land_trendr_tpu.models.oracle.fit_to_vertices` is the
normative semantic spec; this module is its fixed-shape vmapped re-expression
reusing the segmentation kernel's masked anchored fit.

Mapping of the oracle's dynamic steps to static shapes:

* ``np.searchsorted(valid_idx, vertex_indices)`` → ``jnp.searchsorted`` over
  a fixed-size ``nonzero(mask, size=NY, fill=NY)`` position table;
* ``sorted(set(...))`` dedup → scatter into a boolean vertex mask (duplicate
  scatters coalesce for free);
* the <2-vertices fallback → a mask of the first/last valid year selected by
  ``jnp.where``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops.segment import (
    _fit_model,
    _gather_1d,
    _interp_through_vertices,
    _vertex_positions,
)

__all__ = ["ftv_pixel", "jax_fit_to_vertices"]


def ftv_pixel(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    vertex_indices: jnp.ndarray,
    n_vertices: jnp.ndarray,
    params: LTParams,
) -> jnp.ndarray:
    """Fit one pixel's target series to an already-chosen vertex set.

    Parameters
    ----------
    years : (NY,) shared year axis.
    values : (NY,) target-index series (disturbance-positive convention).
    mask : (NY,) bool validity of the *target* series.
    vertex_indices : (NV,) stack-axis vertex indices from the segmentation
        index's :class:`~land_trendr_tpu.ops.segment.SegOutputs`, padded -1.
    n_vertices : () int — number of live entries in ``vertex_indices``.

    Returns
    -------
    (NY,) fitted trajectory over the full year axis (flat mean of the valid
    target values when there is no usable vertex set / too little data —
    oracle ``fit_to_vertices`` fallback).
    """
    dtype = jnp.result_type(values.dtype, jnp.float32)
    t = years.astype(dtype)
    v = values.astype(dtype)
    mask = mask.astype(bool) & jnp.isfinite(v)
    v = jnp.where(mask, v, 0.0)
    ny = t.shape[0]
    nv = vertex_indices.shape[0]

    iota = jnp.arange(ny)
    n_valid = jnp.sum(mask)
    n_safe = jnp.maximum(n_valid, 1)
    # gather-free forms throughout (TPU: dynamic gather/scatter serializes —
    # TPU_KERNEL_DIAG_r04.md §3; every replacement below is a selected
    # element / counted comparison, bit-identical to the indexed original):
    # rank-keyed valid-position table instead of nonzero's compaction
    valid_pos = _vertex_positions(mask, ny)

    # stack-axis vertex index → nearest valid position at/after it (oracle's
    # searchsorted + clip), then back to a full-axis index.
    # searchsorted(sorted a, v, side='left') == count of a[j] < v.
    pos = jnp.clip(
        jnp.sum(valid_pos[None, :] < vertex_indices[:, None], axis=-1),
        0,
        n_safe - 1,
    )
    full = _gather_1d(valid_pos, pos)           # (NV,) full-axis indices
    live = jnp.arange(nv) < n_vertices
    # dedup: year j is a vertex iff some live slot maps to it (the one-hot
    # any-reduce replaces the scatter-max)
    vmask = jnp.any((full[:, None] == iota[None, :]) & live[:, None], axis=0)

    # fallback to endpoints when the mapped set collapses below 2 vertices
    first_v = jnp.argmax(mask)
    last_v = ny - 1 - jnp.argmax(mask[::-1])
    endpoints = ((iota == first_v) | (iota == last_v)) & mask
    vmask = jnp.where(jnp.sum(vmask) >= 2, vmask, endpoints)

    big = jnp.asarray(jnp.finfo(dtype).max, dtype)
    y_lo = jnp.min(jnp.where(mask, v, big))
    y_hi = jnp.max(jnp.where(mask, v, -big))
    y_range = jnp.maximum(y_hi - y_lo, 0.0)

    fitted, _ = _fit_model(t, v, mask, vmask, y_range, params)
    out = _interp_through_vertices(
        t, vmask, fitted, _gather_1d(t, last_v), nv
    )

    mean = jnp.where(n_valid > 0, jnp.sum(jnp.where(mask, v, 0.0)) / n_safe, 0.0)
    ok = (n_vertices >= 2) & (n_valid >= 2)
    return jnp.where(ok, out, mean)


@functools.partial(jax.jit, static_argnames=("params",))
def jax_fit_to_vertices(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    vertex_indices: jnp.ndarray,
    n_vertices: jnp.ndarray,
    params: LTParams = LTParams(),
) -> jnp.ndarray:
    """Batched FTV: fit ``(PX, NY)`` target series to per-pixel vertex sets.

    ``vertex_indices`` is ``(PX, NV)`` int32 (padded -1) and ``n_vertices``
    ``(PX,)`` int32 — exactly the fields produced by
    :func:`~land_trendr_tpu.ops.segment.jax_segment_pixels`.
    """
    return jax.vmap(
        lambda v, m, vi, nv_: ftv_pixel(years, v, m, vi, nv_, params)
    )(values, mask, vertex_indices, n_vertices)
