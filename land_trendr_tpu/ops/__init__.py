"""ops subpackage: TPU compute kernels."""

from land_trendr_tpu.ops.segment import SegOutputs, jax_segment_pixels, segment_pixel

__all__ = ["SegOutputs", "jax_segment_pixels", "segment_pixel"]
