"""ops subpackage: TPU compute kernels."""

from land_trendr_tpu.ops.change import (
    ChangeFilter,
    select_change,
    sieve_change_rasters,
    write_change_maps,
)
from land_trendr_tpu.ops.composite import medoid_composite, medoid_indices
from land_trendr_tpu.ops.ftv import ftv_pixel, jax_fit_to_vertices
from land_trendr_tpu.ops.indices import compute_index, qa_valid_mask, scale_sr, sr_valid_mask
from land_trendr_tpu.ops.segment import SegOutputs, jax_segment_pixels, segment_pixel
from land_trendr_tpu.ops.tile import TileOutputs, process_tile_dn, process_tile_index

__all__ = [
    "TileOutputs",
    "process_tile_dn",
    "process_tile_index",
    "SegOutputs",
    "jax_segment_pixels",
    "segment_pixel",
    "jax_fit_to_vertices",
    "ftv_pixel",
    "compute_index",
    "qa_valid_mask",
    "scale_sr",
    "sr_valid_mask",
    "ChangeFilter",
    "select_change",
    "write_change_maps",
    "sieve_change_rasters",
    "medoid_composite",
    "medoid_indices",
]
