"""ctypes binding for the native raster-codec library (native/lt_native.cc).

The reference's raster layer is Python over GDAL's native C++ core
(SURVEY.md §2 L1 / §3 "Native components"); this module is the rebuild's
equivalent seam.  The GeoTIFF codec (:mod:`land_trendr_tpu.io.geotiff`)
calls :func:`decode_blocks` / :func:`encode_blocks` when the shared
library is present, getting fused inflate+unpredict (and predict+deflate)
hot loops threaded across TIFF blocks; when it isn't — or when
``LT_NO_NATIVE=1`` — the pure-NumPy path runs instead with identical
results, so the native layer is a pure acceleration, never a behaviour
fork.

Search order for the library: ``LT_NATIVE_LIB`` env var, then
``native/liblt_native.so`` relative to the repo root, then a copy next to
this file.  Build with ``make -C native``.
"""

from __future__ import annotations

import ctypes
import os
import sys
from pathlib import Path

import numpy as np

__all__ = [
    "available",
    "lib_path",
    "decode_blocks",
    "encode_blocks",
    "gather_tile",
    "write_store_zip",
    "NativeCodecError",
]

_ERR_NAMES = {
    -1: "inflate failed (corrupt deflate stream?)",
    -2: "deflate failed",
    -3: "bad argument",
    -4: "block data out of file bounds / short",
    -5: "corrupt LZW stream",
}
_ABI_VERSION = 6


class NativeCodecError(RuntimeError):
    """A native codec call returned an error code."""


def _candidates() -> list[Path]:
    out = []
    env = os.environ.get("LT_NATIVE_LIB")
    if env:
        out.append(Path(env))
    here = Path(__file__).resolve()
    out.append(here.parents[2] / "native" / "liblt_native.so")
    out.append(here.parent / "liblt_native.so")
    return out


def _load() -> tuple[ctypes.CDLL | None, str | None]:
    if os.environ.get("LT_NO_NATIVE") == "1":
        return None, None
    if sys.byteorder != "little":  # codec assumes little-endian samples
        return None, None
    for p in _candidates():
        if not p.is_file():
            continue
        try:
            lib = ctypes.CDLL(str(p))
            if lib.lt_native_abi_version() != _ABI_VERSION:
                continue
            _declare(lib)
        except (OSError, AttributeError):
            # unloadable, or a library without our symbols (wrong
            # LT_NATIVE_LIB / stale pre-ABI build) — keep probing/fall back
            continue
        return lib, str(p)
    return None, None


def _declare(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.lt_decode_blocks.restype = ctypes.c_int
    lib.lt_decode_blocks.argtypes = [
        u8p, ctypes.c_uint64, u64p, u64p, u64p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p, ctypes.c_int,
    ]
    lib.lt_encode_blocks.restype = ctypes.c_int
    lib.lt_encode_blocks.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p,
        ctypes.c_uint64, u64p, ctypes.c_int, ctypes.c_int,
    ]
    lib.lt_deflate_bound.restype = ctypes.c_uint64
    lib.lt_deflate_bound.argtypes = [ctypes.c_uint64]
    lib.lt_gather_tile.restype = ctypes.c_int
    lib.lt_gather_tile.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p,
        ctypes.c_int,
    ]
    u8pp = ctypes.POINTER(u8p)
    lib.lt_write_store_zip.restype = ctypes.c_int
    lib.lt_write_store_zip.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        u8pp, u64p, u8pp, u64p, u8pp, u64p, ctypes.c_int,
    ]


_LIB, _LIB_PATH = _load()


def available() -> bool:
    """True when the native library is loaded and usable."""
    return _LIB is not None


def lib_path() -> str | None:
    return _LIB_PATH


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _u64(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def decode_blocks(
    file_data: bytes | np.ndarray,
    offsets: np.ndarray,
    counts: np.ndarray,
    *,
    compression: int,
    predictor: int,
    rows: int,
    width: int,
    spp: int,
    dtype: np.dtype,
    block_rows: np.ndarray | None = None,
    n_threads: int | None = None,
) -> np.ndarray:
    """Decode TIFF blocks → ``(n_blocks, rows, width, spp)`` native-endian.

    ``file_data`` is the whole file image; ``offsets``/``counts`` the block
    byte ranges from the IFD.  ``block_rows`` gives each block's REAL row
    count (default: all full) — a legally-short last strip decodes its real
    rows, while a block whose payload ends short of its expected size is
    corrupt and raises, exactly like the NumPy path's ``frombuffer``.
    Raises :class:`NativeCodecError` on any per-block failure (caller falls
    back to the NumPy path).

    ``n_threads``: ``None`` (default) takes the feed subsystem's shared
    ``decode_workers`` knob (:func:`land_trendr_tpu.io.blockcache.
    decode_threads` — 0 = the codec's own auto-threading, so an
    unconfigured process behaves as before); an explicit int overrides.
    """
    assert _LIB is not None
    if n_threads is None:
        from land_trendr_tpu.io import blockcache

        n_threads = blockcache.decode_threads()
    dtype = np.dtype(dtype)
    if predictor == 2 and dtype.kind not in "iu":
        raise NativeCodecError("predictor 2 requires an integer dtype")
    buf = np.frombuffer(file_data, dtype=np.uint8)
    offs = np.ascontiguousarray(offsets, dtype=np.uint64)
    cnts = np.ascontiguousarray(counts, dtype=np.uint64)
    n = len(offs)
    if block_rows is None:
        brows = np.full(n, rows, dtype=np.uint64)
    else:
        brows = np.ascontiguousarray(block_rows, dtype=np.uint64)
        if len(brows) != n:
            raise NativeCodecError("block_rows length mismatch")
    # zeros, not empty: a short last strip legally fills only its real rows
    out = np.zeros((n, rows, width, spp), dtype=dtype)
    rc = _LIB.lt_decode_blocks(
        _u8(buf), ctypes.c_uint64(buf.size), _u64(offs), _u64(cnts),
        _u64(brows), n, compression, predictor, rows, width, spp,
        dtype.itemsize, _u8(out.view(np.uint8).reshape(-1)), n_threads,
    )
    if rc != 0:
        raise NativeCodecError(_ERR_NAMES.get(rc, f"error {rc}"))
    return out


def encode_blocks(
    blocks: np.ndarray,
    *,
    predictor: int,
    compression: int = 8,
    level: int = 6,
    n_threads: int = 0,
    in_place: bool = False,
) -> list[bytes]:
    """Encode ``(n_blocks, rows, width, spp)`` blocks → bytes list.

    ``compression`` is the TIFF tag value: 8 (deflate, default) or 5 (LZW
    — byte-identical to the Python ``_lzw_encode`` reference).  Applies
    TIFF predictor 2 first when ``predictor == 2`` — the native
    differencing mutates its input buffer, so the input is copied unless
    ``in_place=True`` (pass it when the stack is a throwaway, as the
    GeoTIFF writer does).  Without the predictor the input is never
    written to.
    """
    assert _LIB is not None
    blocks = np.ascontiguousarray(blocks)
    if predictor == 2 and blocks.dtype.kind not in "iu":
        raise NativeCodecError("predictor 2 requires an integer dtype")
    if compression not in (8, 5):
        raise NativeCodecError(f"unsupported encode compression {compression}")
    n, rows, width, spp = blocks.shape
    block_bytes = rows * width * spp * blocks.dtype.itemsize
    if compression == 8:
        bound = int(_LIB.lt_deflate_bound(ctypes.c_uint64(block_bytes)))
    else:
        bound = 2 * block_bytes + 64  # 12-bit codes for 8-bit symbols
    scratch = blocks if (in_place or predictor != 2) else blocks.copy()
    scratch = scratch.view(np.uint8).reshape(-1)
    out = np.empty(n * bound, dtype=np.uint8)
    sizes = np.zeros(n, dtype=np.uint64)
    rc = _LIB.lt_encode_blocks(
        _u8(scratch), n, compression, predictor, rows, width, spp,
        blocks.dtype.itemsize, _u8(out), ctypes.c_uint64(bound),
        _u64(sizes), level, n_threads,
    )
    if rc != 0:
        raise NativeCodecError(_ERR_NAMES.get(rc, f"error {rc}"))
    return [
        out[i * bound : i * bound + int(sizes[i])].tobytes() for i in range(n)
    ]


def gather_tile(
    cube: np.ndarray,
    y0: int,
    x0: int,
    h: int,
    w: int,
    *,
    n_threads: int = 0,
) -> np.ndarray:
    """Window a ``(NY, H, W)`` cube into the ``(h*w, NY)`` device-feed
    layout — the host feed path's transpose, threaded (SURVEY.md §7
    hard-part 4).  Identical to
    ``np.ascontiguousarray(cube[:, y0:y0+h, x0:x0+w].reshape(NY, h*w).T)``.
    """
    assert _LIB is not None
    if not cube.flags["C_CONTIGUOUS"] or cube.dtype.byteorder not in "=|<":
        # copying the whole cube to gather one window would be slower than
        # the NumPy fallback this accelerates — make the caller decide
        raise NativeCodecError("gather_tile needs a C-contiguous native-endian cube")
    ny, height, width = cube.shape
    out = np.empty((h * w, ny), dtype=cube.dtype)
    rc = _LIB.lt_gather_tile(
        _u8(cube.view(np.uint8).reshape(-1)), ny, height, width,
        y0, x0, h, w, cube.dtype.itemsize,
        _u8(out.view(np.uint8).reshape(-1)), n_threads,
    )
    if rc != 0:
        raise NativeCodecError(_ERR_NAMES.get(rc, f"error {rc}"))
    return out


def write_store_zip(
    path: str, arrays: dict[str, np.ndarray], *, n_threads: int = 0
) -> None:
    """Write ``arrays`` as a STORE-mode ``.npz`` through the native writer.

    ``np.load`` reads the result like any ``np.savez`` output; the .npy
    member headers are rendered here (tiny) and the C++ side computes
    member CRC32s threaded and streams one sequential buffered write —
    the manifest write stage without Python's ``zipfile`` byte-shuffling
    or the GIL in the hot path (HOSTPATH_r03.json: the store-mode write
    was the single most core-hungry host stage at the north-star rate).

    Raises :class:`NativeCodecError` when the library is absent or the
    payload needs zip64 (any member or the file ≥ 4 GB) — callers fall
    back to ``np.savez``/``zipfile``.
    """
    if _LIB is None:
        raise NativeCodecError("native library not loaded")
    import io as _io

    names: list[bytes] = []
    heads: list[np.ndarray] = []
    datas: list[np.ndarray] = []
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        buf = _io.BytesIO()
        np.lib.format.write_array_header_1_0(
            buf, np.lib.format.header_data_from_array_1_0(arr)
        )
        names.append(f"{name}.npy".encode("ascii"))
        # write_array_header_1_0 emits magic + version + header already
        heads.append(np.frombuffer(buf.getvalue(), dtype=np.uint8))
        datas.append(arr.view(np.uint8).reshape(-1))

    n = len(names)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    name_bufs = [np.frombuffer(b, dtype=np.uint8) for b in names]

    def ptr_array(bufs):
        return (u8p * n)(*[_u8(b) for b in bufs])

    def len_array(bufs):
        return np.array([b.size for b in bufs], dtype=np.uint64)

    name_lens, head_lens, data_lens = (
        len_array(name_bufs), len_array(heads), len_array(datas)
    )
    rc = _LIB.lt_write_store_zip(
        path.encode(), n,
        ptr_array(name_bufs), _u64(name_lens),
        ptr_array(heads), _u64(head_lens),
        ptr_array(datas), _u64(data_lens),
        n_threads,
    )
    if rc != 0:
        raise NativeCodecError(_ERR_NAMES.get(rc, f"error {rc}"))
