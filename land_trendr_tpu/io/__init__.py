"""io subpackage: host-side raster I/O (GeoTIFF codec, synthetic stacks,
decoded-block cache + shared decode pool for the feed path)."""

from land_trendr_tpu.io import blockcache
from land_trendr_tpu.io.geotiff import (
    GeoMeta,
    GeoTiffStreamWriter,
    TiffInfo,
    read_geotiff,
    read_geotiff_info,
    read_geotiff_window,
    write_geotiff,
)
from land_trendr_tpu.io.synthetic import SceneSpec, SyntheticStack, make_stack, write_stack

__all__ = [
    "blockcache",
    "GeoMeta",
    "TiffInfo",
    "GeoTiffStreamWriter",
    "read_geotiff",
    "read_geotiff_info",
    "read_geotiff_window",
    "write_geotiff",
    "SceneSpec",
    "SyntheticStack",
    "make_stack",
    "write_stack",
]
