"""io subpackage of land_trendr_tpu."""
