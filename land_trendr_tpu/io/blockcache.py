"""Decoded-block cache + shared decode pool for the GeoTIFF feed path.

On the r05 gigapixel resume run the host feed stage was the dominant
non-compute cost (GIGA_r05.json ``stage_s``: feed 18.96s of 56.9s wall):
every tile window re-decoded the compressed TIFF blocks straddling tile
boundaries — once per band, serially, under a single feed worker.  The
massively-parallel break-detection literature (arXiv:1807.01751) names
exactly this host decode/feed stage as the scaling limiter once the
fitting kernel is fast.  This module is the process-wide answer, used by
:mod:`land_trendr_tpu.io.geotiff` window reads:

* a **decoded-block LRU cache** keyed by
  ``(path, mtime_ns, size, page, block_index)`` with a configurable byte
  budget — a block revisited by an overlapping window, a
  ``LazyBandCube`` re-read, or a resume pass decodes once;
* a **shared decode thread pool**: zlib releases the GIL, so the blocks
  of one window decode concurrently (the native codec threads in C++
  instead — the same ``decode_workers`` knob governs both paths);
* **readahead**: the driver's feed pool hints the next planned tile's
  windows (:func:`prefetch_window`), so their blocks decode into the
  cache while the current tile waits on the device;
* **stats** (:func:`stats_snapshot` / :func:`stats_delta`): hits,
  misses, evictions, decode seconds, readahead effectiveness — exported
  through the run telemetry (``feed_cache`` event + ``lt_feed_*``
  Prometheus metrics) and surfaced by ``tools/obs_report.py``.

Unconfigured (the import-time default: budget 0, workers ``None``) the
module is inert and the codec behaves exactly as before — no cache, the
native path auto-threads, the NumPy path decodes serially.  The driver
configures it from ``RunConfig.feed_cache_mb`` / ``decode_workers``;
``feed_cache_mb=0`` reproduces the uncached behavior byte for byte
(cached and uncached reads are byte-identical either way — the cache
stores fully decoded, un-predicted blocks, so it is pure memoization).

A configured **persistent store**
(:class:`land_trendr_tpu.io.blockstore.BlockStore`, driven by
``RunConfig.ingest_store_mb``) adds a second tier under the RAM cache:
a RAM miss consults the store before decoding, a decoded block is
persisted alongside its RAM insert, and a store-served block is
promoted back into the RAM tier.  The ``hits``/``misses`` counters here
keep describing the RAM tier (a store hit still counts a RAM miss —
store effectiveness is the ``ingest_store`` rollup's story), and
:func:`drop_corrupt` invalidates BOTH tiers, so a poisoned block —
wherever it came from — degrades to one extra decode.

Thread-safety: one module lock guards the cache map and the counters;
entries are immutable by convention (every consumer only reads slices).
A decode task spawned by :func:`prefetch_window` runs ON the shared
pool, so window reads inside a readahead task decode serially
(:func:`decode_pool` returns ``None`` there) — submitting pool work
from a pool task and waiting on it would deadlock a saturated pool.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover - typing only
    import numpy as np

__all__ = [
    "config_snapshot",
    "configure",
    "detach_store",
    "store_bytes_snapshot",
    "cache_enabled",
    "cache_get",
    "cache_put",
    "cache_clear",
    "decode_threads",
    "decode_pool",
    "drop_corrupt",
    "fault_check",
    "fault_corrupt",
    "file_key",
    "note_decode_seconds",
    "prefetch_window",
    "set_fault_plan",
    "stats_snapshot",
    "stats_delta",
]

#: cap for ``decode_workers=0`` (auto): feed decode shares the host with
#: the feed/writer pools and the JAX dispatch thread — more than a few
#: zlib threads per window hits diminishing returns long before this
_AUTO_WORKERS_MAX = 8

_lock = threading.Lock()
_tl = threading.local()  # .readahead: True inside a prefetch pool task

# -- configuration state (module-wide: the cache is process-wide by design,
#    like the reference's GDAL block cache) --------------------------------
_budget_bytes: int = 0
_workers: int | None = None  # None = never configured (legacy behavior)
_pool: ThreadPoolExecutor | None = None
_pool_size: int = 0

# -- cache map: key -> [array, nbytes, readahead_pending] ------------------
_entries: "OrderedDict[tuple, list]" = OrderedDict()
_cache_bytes: int = 0

# -- persistent second tier (io.blockstore.BlockStore or None) -------------
_store = None

# -- counters (guarded by _lock) -------------------------------------------
_stats = {
    "hits": 0,
    "misses": 0,
    "evictions": 0,
    "inserted_bytes": 0,
    "decode_s": 0.0,
    "readahead_blocks": 0,
    "readahead_hits": 0,
    "readahead_dropped": 0,
    "corrupt_dropped": 0,
}
_inflight_prefetch = 0

# -- fault-injection hook (land_trendr_tpu.runtime.faults) -----------------
# The io layer must not import runtime/ (driver imports geotiff — a
# module-level back-import would cycle), so the active FaultPlan is
# REGISTERED here by faults.activate()/deactivate().  None = inert.
_fault_plan = None


def set_fault_plan(plan) -> None:
    """Install/clear the active fault plan for the io-layer seams
    (``feed.decode``, ``cache.corrupt``); called by ``runtime.faults``."""
    global _fault_plan
    _fault_plan = plan


def fault_check(seam: str) -> None:
    """Raising io-layer seam; no-op (one attribute read) when inert.

    Readahead tasks are invisible to the seams (like they are to the
    hit/miss counters): their errors are swallowed by design, so letting
    them consume per-seam invocation indices would both waste scheduled
    faults on a path that cannot surface them AND make the demand path's
    indices race the prefetch pool — breaking the injector's determinism
    contract."""
    plan = _fault_plan
    if plan is not None and not getattr(_tl, "readahead", False):
        plan.check(seam)


def fault_corrupt(seam: str, arr: "np.ndarray") -> "np.ndarray":
    """Corruption io-layer seam: damaged stand-in on a firing invocation
    (demand reads only — see :func:`fault_check` on readahead)."""
    plan = _fault_plan
    if plan is None or getattr(_tl, "readahead", False):
        return arr
    return plan.corrupt(seam, arr)


def drop_corrupt(key: tuple) -> None:
    """Invalidate one cache entry whose consumer found it corrupt (wrong
    shape/dtype for its slot): the entry is removed and counted, and the
    caller re-decodes from the file — a poisoned block degrades to one
    extra decode instead of failing the tile.  With a persistent store
    tier the drop propagates there too (the damaged block may have been
    served from — or promoted out of — disk)."""
    with _lock:
        global _cache_bytes
        ent = _entries.pop(key, None)
        if ent is not None:
            # count actual removals only: a concurrent reader that found
            # the same poisoned block (or an eviction racing this call)
            # must not double-count one corruption
            _cache_bytes -= ent[1]
            _stats["corrupt_dropped"] += 1
        store = _store
    if store is not None:
        store.drop(key)


def configure(
    budget_bytes: int = 0, workers: int | None = 0, store=None
) -> None:
    """Set the cache byte budget, decode worker count, and store tier.

    ``budget_bytes=0`` disables the cache (and clears it).  ``workers``:
    ``0`` = auto (``min(8, cpu)`` for the NumPy path, the native codec's
    own auto-threading), ``1`` = serial everywhere, ``N`` = that many
    threads in both paths, ``None`` = the unconfigured import-time
    default (serial NumPy, auto native — exactly the pre-cache codec).
    ``store`` is a :class:`land_trendr_tpu.io.blockstore.BlockStore` (or
    ``None`` = no persistent tier); its lifecycle — flush/close — stays
    with the caller that built it (the driver).
    Counters are NOT reset — callers diff :func:`stats_snapshot`.
    """
    global _budget_bytes, _workers, _store
    if budget_bytes < 0:
        raise ValueError(f"budget_bytes={budget_bytes} must be >= 0")
    if workers is not None and workers < 0:
        raise ValueError(f"workers={workers} must be >= 0 (or None)")
    with _lock:
        _budget_bytes = int(budget_bytes)
        _workers = workers
        _store = store
        _evict_to_budget_locked()
        if _budget_bytes == 0:
            _entries.clear()
            _reset_bytes_locked()


def config_snapshot() -> dict:
    """The current process-cache configuration, in :func:`configure`'s
    keyword shape — ``configure(**config_snapshot())`` restores it.  How
    a transient reconfigurer (the autotuner's decode probe) guarantees it
    never skews the run behind it."""
    with _lock:
        return {
            "budget_bytes": _budget_bytes,
            "workers": _workers,
            "store": _store,
        }


def _reset_bytes_locked() -> None:
    global _cache_bytes
    _cache_bytes = 0


def _evict_to_budget_locked() -> None:
    global _cache_bytes
    while _cache_bytes > _budget_bytes and _entries:
        _, (arr, nbytes, _ra) = _entries.popitem(last=False)
        _cache_bytes -= nbytes
        _stats["evictions"] += 1


def detach_store(store) -> None:
    """Drop the persistent tier iff it is still ``store`` — called by the
    run that built it when it ends, so a later configure (or nothing at
    all) cannot keep writing into a closed store.  The RAM tier persists
    process-wide as before."""
    global _store
    with _lock:
        if _store is store:
            _store = None


def store_bytes_snapshot() -> "int | None":
    """Current persistent-store occupancy in bytes — the flight
    sampler's ``store_bytes`` gauge (None without an attached store).
    The store's own snapshot runs OUTSIDE the cache lock (it takes the
    store lock; nesting the two here would add a lock-order edge)."""
    with _lock:
        store = _store
    if store is None:
        return None
    try:
        return int(store.stats_snapshot().get("bytes", 0))
    except Exception:
        return None


def occupancy_probe() -> dict:
    """The flight sampler's cache/store occupancy gauges in one place:
    ``cache_bytes`` always, ``store_bytes`` only with an attached store
    (a missing gauge is "no store", not "empty store")."""
    out = {"cache_bytes": int(stats_snapshot()["cache_bytes"])}
    store_bytes = store_bytes_snapshot()
    if store_bytes is not None:
        out["store_bytes"] = store_bytes
    return out


def cache_enabled() -> bool:
    with _lock:
        return _budget_bytes > 0 or _store is not None


def cache_get(key: tuple) -> "np.ndarray | None":
    """Cached decoded block for ``key``, or None (counts a hit/miss).

    Lookups made FROM a readahead task are invisible to the counters:
    prefetch probing its own (or a sibling hint's) blocks is not demand
    traffic — counting it would floor-inflate the hit rate and consume
    the readahead-pending flag on lookups that never served a real read.

    A RAM miss falls through to the persistent store tier when one is
    configured: a store hit still counts a RAM ``miss`` here (the
    counters describe the RAM tier; the store keeps its own), passes
    the ``store.corrupt`` fault seam, and is promoted into the RAM
    cache so revisits inside this run stay memory-speed.
    """
    demand = not getattr(_tl, "readahead", False)
    with _lock:
        ent = _entries.get(key)
        if ent is not None:
            _entries.move_to_end(key)
            if demand:
                _stats["hits"] += 1
                if ent[2]:  # first real hit on a readahead-inserted block
                    ent[2] = False
                    _stats["readahead_hits"] += 1
            return ent[0]
        if demand:
            _stats["misses"] += 1
        store = _store
    if store is None:
        return None
    arr = store.get(key, count=demand)
    if arr is None:
        return None
    if demand:
        # fault seam "store.corrupt" (demand reads only, like the cache
        # seam): a damaged stand-in here flows through the SAME
        # consumer-side shape/dtype validation as a poisoned RAM entry,
        # whose drop_corrupt then invalidates both tiers
        plan = _fault_plan
        if plan is not None:
            arr = plan.corrupt("store.corrupt", arr)
    cache_put(key, arr)
    return arr


def cache_put(key: tuple, arr: "np.ndarray") -> None:
    """Insert a decoded block (RAM tier no-op when disabled/oversized;
    a configured store tier persists it alongside — idempotently, so
    store-promoted blocks are never re-written)."""
    nbytes = int(arr.nbytes)
    readahead = bool(getattr(_tl, "readahead", False))
    with _lock:
        store = _store
        if _budget_bytes > 0 and nbytes <= _budget_bytes:
            global _cache_bytes
            old = _entries.pop(key, None)
            if old is not None:
                _cache_bytes -= old[1]
            _entries[key] = [arr, nbytes, readahead]
            _cache_bytes += nbytes
            _stats["inserted_bytes"] += nbytes
            if readahead:
                _stats["readahead_blocks"] += 1
            _evict_to_budget_locked()
    if store is not None:
        store.put(key, arr)


def cache_clear() -> None:
    """Drop every entry (budget/config unchanged; counters kept)."""
    with _lock:
        _entries.clear()
        _reset_bytes_locked()


def cache_bytes() -> int:
    with _lock:
        return _cache_bytes


def budget_bytes() -> int:
    with _lock:
        return _budget_bytes


def file_key(f, path: str) -> "tuple | None":
    """Cache identity of an open raster: ``(path, mtime_ns, size)``.

    mtime + size guard rewritten files — a regenerated scene under the
    same path must not serve the previous contents' blocks.  ``None``
    (no caching) for non-statable streams.
    """
    try:
        st = os.fstat(f.fileno())
    except (OSError, AttributeError, ValueError):
        return None
    return (path, st.st_mtime_ns, st.st_size)


def decode_threads() -> int:
    """``n_threads`` for the native codec: 0 = its own auto-threading."""
    with _lock:
        workers = _workers
    if workers is None:
        return 0
    return workers


def _effective_pool_size() -> int:
    with _lock:
        workers = _workers
    if workers is None or workers == 1:
        return 1
    if workers == 0:
        return min(_AUTO_WORKERS_MAX, os.cpu_count() or 1)
    return workers


def decode_pool() -> "ThreadPoolExecutor | None":
    """The shared pool for NumPy-path block decode, or ``None`` when the
    decode must run serially (unconfigured, ``workers=1``, or already on
    a pool thread via :func:`prefetch_window` — see the module note on
    pool-in-pool deadlock)."""
    if getattr(_tl, "readahead", False):
        return None
    size = _effective_pool_size()
    if size <= 1:
        return None
    return _get_pool(size)


def _get_pool(size: int) -> ThreadPoolExecutor:
    global _pool, _pool_size
    with _lock:
        if _pool is None or _pool_size != size:
            old = _pool
            _pool = ThreadPoolExecutor(
                max_workers=size, thread_name_prefix="lt-decode"
            )
            _pool_size = size
            if old is not None:
                old.shutdown(wait=False)
        return _pool


def note_decode_seconds(dt: float) -> None:
    """Accumulate block-decode wall seconds (summed across threads, so
    the total can exceed wall time — like the driver's stage timers)."""
    with _lock:
        _stats["decode_s"] += dt


def prefetch_window(path: str, y0: int, x0: int, h: int, w: int) -> bool:
    """Hint a future window: decode its blocks into the cache off-thread.

    Fire-and-forget — returns True when the hint was queued, False when
    readahead is off (cache disabled / serial config) or the pool is
    already saturated with hints (bounded backlog; dropped hints are
    counted, the blocks just decode on demand later).  Errors inside the
    prefetch task are swallowed: the on-demand read will surface them.
    """
    global _inflight_prefetch
    size = _effective_pool_size()
    if not cache_enabled() or size <= 1:
        return False
    with _lock:
        if _inflight_prefetch >= 2 * size:
            _stats["readahead_dropped"] += 1
            return False
        _inflight_prefetch += 1
    _get_pool(size).submit(_prefetch_task, path, y0, x0, h, w)
    return True


def _prefetch_task(path: str, y0: int, x0: int, h: int, w: int) -> None:
    global _inflight_prefetch
    from land_trendr_tpu.io.geotiff import read_geotiff_window

    _tl.readahead = True
    try:
        read_geotiff_window(path, y0, x0, h, w)
    except Exception:
        pass  # the on-demand read reports the real error with context
    finally:
        _tl.readahead = False
        with _lock:
            _inflight_prefetch -= 1


def stats_snapshot() -> dict:
    """Cumulative process-wide counters (plus current cache occupancy)."""
    with _lock:
        out = dict(_stats)
        out["cache_bytes"] = _cache_bytes
        out["budget_bytes"] = _budget_bytes
        return out


def stats_delta(base: dict) -> dict:
    """Counters accumulated since ``base`` (a prior snapshot); occupancy
    fields (``cache_bytes``/``budget_bytes``) are reported as-is, not
    differenced — they are gauges, not counters."""
    now = stats_snapshot()
    out = {}
    for k, v in now.items():
        if k in ("cache_bytes", "budget_bytes"):
            out[k] = v
        else:
            out[k] = round(v - base.get(k, 0), 6) if isinstance(v, float) else v - base.get(k, 0)
    return out
