"""Synthetic Landsat annual-stack generator (test fixture + benchmark feed).

The reference's inputs are Landsat WRS-2 scenes / ARD mosaics (SURVEY.md §1,
provenance ``[B]``); none ship with this environment, so the framework
generates physically-plausible stand-ins: a six-band surface-reflectance
annual stack over a forest scene with patchy disturbance events (abrupt NBR
loss at a per-patch year), exponential regrowth, per-year cloud masking via
Collection-2 style QA bits, and sensor noise.  The generator also returns
the ground truth (disturbance year/magnitude per pixel) so tests can score
detection, and :func:`write_stack` materialises the stack as per-year
multi-band GeoTIFFs for end-to-end driver tests.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from land_trendr_tpu.io.geotiff import GeoMeta, write_geotiff
from land_trendr_tpu.ops.indices import BANDS

__all__ = [
    "SceneSpec",
    "SyntheticStack",
    "make_stack",
    "write_stack",
    "write_stack_c2",
]

# mean healthy-forest surface reflectance per band (blue..swir2)
_FOREST_SR = {
    "blue": 0.015, "green": 0.035, "red": 0.020,
    "nir": 0.380, "swir1": 0.130, "swir2": 0.060,
}
# reflectance immediately after a stand-clearing disturbance
_DISTURBED_SR = {
    "blue": 0.045, "green": 0.070, "red": 0.085,
    "nir": 0.180, "swir1": 0.280, "swir2": 0.230,
}

_C2_SCALE = 2.75e-5
_C2_OFFSET = -0.2

_QA_CLOUD = 1 << 3
_QA_FILL = 1 << 0


@dataclasses.dataclass(frozen=True)
class SceneSpec:
    """Parameters of a synthetic scene."""

    width: int = 256
    height: int = 256
    year_start: int = 1984
    year_end: int = 2023
    disturbance_fraction: float = 0.3   # fraction of pixels disturbed
    patch_scale: int = 16               # disturbance patch size (px)
    recovery_rate: float = 0.08         # fractional recovery per year
    cloud_fraction: float = 0.08        # per-observation cloud probability
    noise: float = 0.006                # reflectance noise sigma
    seed: int = 20260729


@dataclasses.dataclass
class SyntheticStack:
    """A generated stack plus its ground truth."""

    years: np.ndarray                   # (NY,) int32
    bands: dict[str, np.ndarray]        # name → (NY, H, W) float32 reflectance
    qa: np.ndarray                      # (NY, H, W) uint16 QA_PIXEL bits
    truth_year: np.ndarray              # (H, W) int32, -1 where undisturbed
    truth_magnitude: np.ndarray         # (H, W) float32 NBR-loss magnitude

    def dn(self, name: str) -> np.ndarray:
        """Band as Collection-2 scaled int16 DNs (what real files carry).

        Saturates at the int16 limits the way real C2 products do for
        over-bright targets (clouds can exceed the representable range).
        """
        sr = self.bands[name]
        dn = np.round((sr - _C2_OFFSET) / _C2_SCALE)
        return np.clip(dn, -32768, 32767).astype(np.int16)

    def dn_year(self, name: str, i: int) -> np.ndarray:
        """One year's ``(H, W)`` slice of :meth:`dn` — same arithmetic on
        the slice only, so writers stay O(H·W) in both extra time and
        memory per file instead of converting the whole cube per year
        (O(NY²) time) or holding all band cubes at once (≈+50% peak)."""
        sr = self.bands[name][i]
        dn = np.round((sr - _C2_OFFSET) / _C2_SCALE)
        return np.clip(dn, -32768, 32767).astype(np.int16)


def make_stack(spec: SceneSpec = SceneSpec()) -> SyntheticStack:
    rng = np.random.default_rng(spec.seed)
    years = np.arange(spec.year_start, spec.year_end + 1, dtype=np.int32)
    ny = len(years)
    h, w = spec.height, spec.width

    # --- patchy disturbance map: threshold smoothed noise ------------------
    gh = max(2, h // spec.patch_scale)
    gw = max(2, w // spec.patch_scale)
    field = rng.normal(size=(gh, gw))
    # bilinear upsample to (h, w)
    yi = np.linspace(0, gh - 1, h)
    xi = np.linspace(0, gw - 1, w)
    y0 = np.floor(yi).astype(int)
    x0 = np.floor(xi).astype(int)
    y1 = np.minimum(y0 + 1, gh - 1)
    x1 = np.minimum(x0 + 1, gw - 1)
    fy = (yi - y0)[:, None]
    fx = (xi - x0)[None, :]
    smooth = (
        field[np.ix_(y0, x0)] * (1 - fy) * (1 - fx)
        + field[np.ix_(y1, x0)] * fy * (1 - fx)
        + field[np.ix_(y0, x1)] * (1 - fy) * fx
        + field[np.ix_(y1, x1)] * fy * fx
    )
    thresh = np.quantile(smooth, 1.0 - spec.disturbance_fraction)
    disturbed = smooth > thresh

    # per-patch disturbance year: reuse the coarse grid so patches share one;
    # keep events away from the series edges when the span allows it
    lo = min(spec.year_start + 5, spec.year_end)
    hi = max(spec.year_end - 5, lo + 1)
    d_year_grid = rng.integers(lo, hi, size=(gh, gw))
    d_year = d_year_grid[np.ix_(np.round(yi).astype(int), np.round(xi).astype(int))]
    truth_year = np.where(disturbed, d_year, -1).astype(np.int32)

    severity = rng.uniform(0.5, 1.0, size=(h, w)).astype(np.float32)
    severity = np.where(disturbed, severity, 0.0)

    # --- per-band trajectories --------------------------------------------
    t = years[:, None, None].astype(np.float32)           # (NY,1,1)
    since = np.clip(t - truth_year[None], 0.0, None)       # years since event
    active = (truth_year[None] >= 0) & (t >= truth_year[None])
    recovery = np.exp(-spec.recovery_rate * since, dtype=np.float32)
    blend = np.where(active, severity[None] * recovery, 0.0).astype(np.float32)

    bands: dict[str, np.ndarray] = {}
    for name in BANDS:
        base = _FOREST_SR[name]
        post = _DISTURBED_SR[name]
        series = base + (post - base) * blend
        series = series + rng.normal(0.0, spec.noise, size=series.shape)
        bands[name] = series.astype(np.float32)

    nbr = lambda b: (b["nir"] - b["swir2"]) / (b["nir"] + b["swir2"])  # noqa: E731
    pre = {k: np.full((h, w), _FOREST_SR[k], dtype=np.float32) for k in BANDS}
    post = {
        k: (_FOREST_SR[k] + (_DISTURBED_SR[k] - _FOREST_SR[k]) * severity)
        for k in BANDS
    }
    truth_mag = np.where(disturbed, nbr(pre) - nbr(post), 0.0).astype(np.float32)

    # --- clouds ------------------------------------------------------------
    qa = np.zeros((ny, h, w), dtype=np.uint16)
    cloudy = rng.random(size=(ny, h, w)) < spec.cloud_fraction
    qa[cloudy] |= _QA_CLOUD
    for name in BANDS:  # clouds read bright and cold
        bands[name] = np.where(
            cloudy, rng.uniform(0.4, 0.9, size=(ny, h, w)).astype(np.float32),
            bands[name],
        )

    # --- fill margins -------------------------------------------------------
    # Real ARD tiles have nodata margins where the scene footprint shifts
    # year to year; emulate with a small per-year left/right fill strip so
    # QA fill-bit rejection is exercised end to end.
    margin = rng.integers(0, max(2, w // 32), size=ny)
    cols = np.arange(w)
    fill = (cols[None, None, :] < margin[:, None, None]) | (
        cols[None, None, :] >= w - margin[:, None, None]
    )
    fill = np.broadcast_to(fill, (ny, h, w))
    qa[fill] |= _QA_FILL
    for name in BANDS:
        bands[name] = np.where(fill, np.float32(_C2_OFFSET), bands[name])

    return SyntheticStack(
        years=years,
        bands=bands,
        qa=qa,
        truth_year=truth_year,
        truth_magnitude=truth_mag,
    )


def write_stack(
    out_dir: str,
    stack: SyntheticStack,
    compress: str = "deflate",
    tile: int | None = 256,
) -> list[str]:
    """Write one multi-band GeoTIFF per year (6 SR bands int16 + QA uint16).

    Layout mirrors a per-year Landsat composite directory:
    ``{out_dir}/LT_{year}.tif`` with bands in :data:`BANDS` order followed by
    QA_PIXEL.  Returns the file paths in year order.
    """
    os.makedirs(out_dir, exist_ok=True)
    geo = GeoMeta(
        pixel_scale=(30.0, 30.0, 0.0),
        tiepoint=(0.0, 0.0, 0.0, 500000.0, 5000000.0, 0.0),
    )
    paths = []
    for i, year in enumerate(stack.years):
        sr = np.stack([stack.dn_year(b, i) for b in BANDS])     # (6, H, W) i16
        qa = stack.qa[i].astype(np.int16)                        # QA bits fit
        img = np.concatenate([sr, qa[None]], axis=0)
        path = os.path.join(out_dir, f"LT_{int(year)}.tif")
        write_geotiff(path, img, geo=geo, compress=compress, tile=tile)
        paths.append(path)
    return paths


#: canonical band name → C2 SR band number, by sensor generation (inverse
#: of runtime.stack's ingest tables)
_C2_NUM_TM = {"blue": 1, "green": 2, "red": 3, "nir": 4, "swir1": 5, "swir2": 7}
_C2_NUM_OLI = {"blue": 2, "green": 3, "red": 4, "nir": 5, "swir1": 6, "swir2": 7}


def write_stack_c2(
    out_dir: str,
    stack: SyntheticStack,
    compress: str = "deflate",
    tile: int | None = 256,
) -> list[str]:
    """Write the USGS Collection-2 Level-2 per-band layout.

    One single-band GeoTIFF per SR band plus ``QA_PIXEL`` per year, named
    with real product ids (``LT05_L2SP_045030_YYYYMMDD_..._SR_B5.TIF``) —
    the layout :func:`land_trendr_tpu.runtime.load_stack_dir_c2` ingests.
    Years before 2013 use the LT05 sensor prefix and TM band numbering,
    2013+ use LC08/OLI numbering, so fixtures exercise the mixed-sensor
    mapping a real 1984– archive has.  Returns file paths, year-major.
    """
    os.makedirs(out_dir, exist_ok=True)
    geo = GeoMeta(
        pixel_scale=(30.0, 30.0, 0.0),
        tiepoint=(0.0, 0.0, 0.0, 500000.0, 5000000.0, 0.0),
    )
    paths = []
    for i, year in enumerate(stack.years):
        year = int(year)
        sensor, nums = (
            ("LC08", _C2_NUM_OLI) if year >= 2013 else ("LT05", _C2_NUM_TM)
        )
        date = f"{year}0715"
        stem = f"{sensor}_L2SP_045030_{date}_{date}_02_T1"
        for b in BANDS:
            path = os.path.join(out_dir, f"{stem}_SR_B{nums[b]}.TIF")
            write_geotiff(
                path, stack.dn_year(b, i), geo=geo, compress=compress, tile=tile
            )
            paths.append(path)
        path = os.path.join(out_dir, f"{stem}_QA_PIXEL.TIF")
        write_geotiff(
            path,
            stack.qa[i].astype(np.uint16),
            geo=geo,
            compress=compress,
            tile=tile,
        )
        paths.append(path)
    return paths
