"""Persistent decoded-block store: ingest once, serve many.

The PR-2 decoded-block cache (:mod:`land_trendr_tpu.io.blockcache`) dies
with the process — the second run over the same stacks pays full TIFF
inflate again, and the service-mode workload ROADMAP item 1 describes
(many requests over the same scene archive) pays it per request.  This
module spills decoded blocks to a **memory-mapped on-disk column store**
under the run's workdir, keyed by the SAME
``(path, mtime_ns, size, page, block_index)`` fingerprint the in-memory
cache uses, so a warm rerun skips TIFF decode entirely (the TorchGeo
tutorial's "ingest once, serve many" pattern, arXiv:2603.02386).

Layout — append-only **segments** with sidecar indexes::

    <root>/seg-<pid>-<n>.bin    raw concatenated block bytes
    <root>/seg-<pid>-<n>.json   {"entries": [{key, off, nbytes, dtype,
                                 shape}, ...], "bytes": N}

* Blocks buffer in memory and flush as one segment once
  ``segment_bytes`` accumulate (or at :meth:`BlockStore.flush`); both
  files are written **tmp + atomic rename**, data before index — the
  index is the commit point, so a crash mid-flush leaves at most an
  orphaned ``.bin`` that a later open garbage-collects (once STALE:
  fresh orphans/tmps in a shared directory may be a live sibling
  process mid-commit).  Concurrent processes sharing a store directory
  (a pod's shared workdir) write disjoint pid-named segments; a sibling
  evicting a segment this process has indexed degrades to one whole-
  segment drop and re-decode on the next read of it.
* Reads are **zero-copy**: a hit is a read-only NumPy view into the
  segment's ``mmap`` — no inflate, no unpredict, no allocation beyond
  the view (the stored array IS the fully decoded block the in-memory
  cache would hold).
* The **byte budget** bounds on-disk bytes: whole oldest segments are
  evicted (files deleted, live entries dropped) — eviction is coarse by
  design; the store is a spill tier, not an LRU.
* **Stale generations**: a key whose ``(path, page, block)`` matches a
  stored entry but whose ``(mtime_ns, size)`` differs means the file was
  rewritten — the stale entry is dropped (``stale_dropped``) and the
  caller re-decodes, exactly like the in-memory cache's mtime guard.
* **Corruption** reuses the PR-5 ``drop_corrupt`` contract: a segment
  whose data file is missing/short at open, or an entry whose bytes no
  longer fit its segment, is dropped and counted (``corrupt_dropped``)
  and the block re-decodes from the TIFF; consumer-side shape/dtype
  validation (``io/geotiff.py``) catches value-level damage the same
  way it does for poisoned cache entries — via
  :func:`blockcache.drop_corrupt`, which forwards the drop here.  The
  ``store.corrupt`` fault seam (:mod:`land_trendr_tpu.runtime.faults`)
  exercises that path deterministically.

Thread-safety: one instance lock guards the index maps, the pending
buffer, the counters, and the mmap table; returned views are immutable
by convention (every consumer only reads slices) — the same contract as
the in-memory cache.  The store never imports ``runtime/``; fault hooks
arrive through :mod:`blockcache`'s registered plan like every io seam.
"""

from __future__ import annotations

import glob
import json
import mmap
import os
import re
import threading
import time
from typing import TYPE_CHECKING

import numpy as np

if TYPE_CHECKING:  # pragma: no cover - typing only
    pass

__all__ = ["BlockStore"]

#: flush threshold: blocks buffer in memory until a segment's worth
#: accumulated (big enough to amortize the rename/fsync, small enough
#: that a crash loses little ingest work)
_SEGMENT_BYTES = 16 << 20

#: orphan/tmp files younger than this are left alone at open: in a
#: shared store directory (pod processes) a fresh sibling-owned ``.bin``
#: may be mid-commit (data renamed, index not yet) and a fresh ``.tmp``
#: mid-write — only stale leftovers are crash debris safe to collect
_GC_STALE_S = 60.0

_SEG_RE = re.compile(r"seg-(\d+)-(\d+)\.json$")


def _key_list(key: tuple) -> list:
    """JSON form of a block key (tuples don't survive JSON round trips)."""
    return list(key)


class BlockStore:
    """One persistent block-store directory (see module docstring)."""

    def __init__(
        self,
        root: str,
        budget_bytes: int,
        segment_bytes: int = _SEGMENT_BYTES,
    ) -> None:
        if budget_bytes <= 0:
            raise ValueError(f"budget_bytes={budget_bytes} must be > 0")
        self.root = root
        self.budget_bytes = int(budget_bytes)
        self.segment_bytes = int(segment_bytes)
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        # committed entries: key -> (seg_name, off, nbytes, dtype, shape)
        self._index: dict[tuple, tuple] = {}
        # generation guard: (path, page, block) -> full key
        self._by_block: dict[tuple, tuple] = {}
        # seg_name -> {"bytes": int, "keys": set, "mtime": float}
        self._segments: dict[str, dict] = {}
        self._mmaps: dict[str, mmap.mmap] = {}
        # pending (unflushed) blocks: key -> np.ndarray; _flushing holds
        # the batch a flush has detached and is writing OUTSIDE the lock
        # (still served by get(), still idempotence-checked by put())
        self._pending: dict[tuple, np.ndarray] = {}
        self._flushing: dict[tuple, np.ndarray] = {}
        self._pending_bytes = 0
        self._flush_lock = threading.Lock()  # one segment write at a time
        self._closed = False
        self._seq = 0
        self._stats = {
            "hits": 0,
            "misses": 0,
            "put_blocks": 0,
            "put_bytes": 0,
            "stale_dropped": 0,
            "corrupt_dropped": 0,
            "evicted_segments": 0,
        }
        self._load()

    # -- open / recovery ---------------------------------------------------
    def _load(self) -> None:
        """Index every committed segment; GC orphans and corrupt pairs."""
        with self._lock:
            sidecars = sorted(
                glob.glob(os.path.join(self.root, "seg-*-*.json")),
                key=lambda p: (os.path.getmtime(p), p),
            )
            indexed_bins = set()
            for sc in sidecars:
                name = os.path.basename(sc)[: -len(".json")]
                bin_path = os.path.join(self.root, name + ".bin")
                try:
                    with open(sc) as f:
                        meta = json.load(f)
                    entries = meta["entries"]
                    nbytes = int(meta["bytes"])
                    if os.path.getsize(bin_path) < nbytes:
                        raise ValueError("short segment data file")
                except (OSError, ValueError, KeyError, json.JSONDecodeError):
                    # torn flush / bit rot: the segment is unusable as a
                    # whole — drop both files, count it, move on (the
                    # blocks just re-decode on demand)
                    self._stats["corrupt_dropped"] += 1
                    for p in (sc, bin_path):
                        try:
                            os.unlink(p)
                        except OSError:
                            pass
                    continue
                keys = set()
                for e in entries:
                    key = tuple(e["key"])
                    self._index[key] = (
                        name,
                        int(e["off"]),
                        int(e["nbytes"]),
                        str(e["dtype"]),
                        tuple(e["shape"]),
                    )
                    self._by_block[self._block_id(key)] = key
                    keys.add(key)
                self._segments[name] = {
                    "bytes": nbytes,
                    "keys": keys,
                    "mtime": os.path.getmtime(sc),
                }
                indexed_bins.add(bin_path)
                m = _SEG_RE.search(sc)
                if m and int(m.group(1)) == os.getpid():
                    self._seq = max(self._seq, int(m.group(2)) + 1)
            # orphans: a .bin with no committed index (crash between the
            # data rename and the index rename), or leftover tmp files.
            # STALE ones only: in a shared store dir a sibling process's
            # fresh .bin may be mid-commit and its fresh .tmp mid-write —
            # unlinking those would destroy its in-flight ingest
            now = time.time()
            for pattern in ("seg-*-*.bin", "*.tmp"):
                for p in glob.glob(os.path.join(self.root, pattern)):
                    if p in indexed_bins:
                        continue
                    try:
                        if now - os.path.getmtime(p) > _GC_STALE_S:
                            os.unlink(p)
                    except OSError:
                        pass
            self._evict_to_budget_locked()

    @staticmethod
    def _block_id(key: tuple) -> tuple:
        """(path, page, block): the generation-blind block identity."""
        return (key[0], key[3], key[4])

    # -- read path ---------------------------------------------------------
    def get(self, key: tuple, count: bool = True) -> "np.ndarray | None":
        """The stored decoded block for ``key``, or ``None``.

        A hit is a read-only mmap-backed view (pending blocks return the
        buffered array).  A generation mismatch — same ``(path, page,
        block)``, different ``(mtime_ns, size)`` — drops the stale entry
        so a rewritten file can never serve its predecessor's bytes.
        ``count=False`` makes the lookup invisible to the hit/miss
        counters (readahead probing, like the in-memory cache's).

        A COLD segment's ``open``/``mmap`` runs OUTSIDE the instance
        lock (LT007: the PR-6 flush bug's read-path twin — a cold open
        on a tiered filesystem stalled every concurrent ``get``/``put``
        behind disk latency), then registers under the lock and retries
        the lookup; a segment evicted during the unlocked window simply
        misses, exactly as if the eviction had won the race under one
        big lock.
        """
        while True:
            with self._lock:
                arr = self._pending.get(key)
                if arr is None:
                    arr = self._flushing.get(key)
                if arr is not None:
                    if count:
                        self._stats["hits"] += 1
                    return arr
                ent = self._index.get(key)
                if ent is None:
                    stale = self._by_block.get(self._block_id(key))
                    if stale is not None and stale != key:
                        self._drop_locked(stale, "stale_dropped")
                    if count:
                        self._stats["misses"] += 1
                    return None
                name, off, nbytes, dtype, shape = ent
                mm = self._mmaps.get(name)
                if mm is not None:
                    return self._read_view_locked(
                        key, mm, off, nbytes, dtype, shape, count
                    )
            # cold segment: open + map with the lock RELEASED, then loop
            # to re-validate — the entry may be gone by the time the map
            # is ready (sibling eviction), in which case the next pass
            # resolves it like any other miss
            try:
                with open(
                    os.path.join(self.root, name + ".bin"), "rb"
                ) as f:
                    mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
            except OSError:
                # unopenable segment (deleted by a sibling's eviction,
                # bit rot): EVERY entry of it is gone — drop the whole
                # segment once instead of paying a failed open (and a
                # corruption count) per sibling entry
                with self._lock:
                    if name in self._segments:
                        self._drop_segment_locked(name)
                        self._stats["corrupt_dropped"] += 1
                    if count:
                        self._stats["misses"] += 1
                return None
            registered = closed = False
            with self._lock:
                if self._closed:
                    # close() tore the mmap table down while we were in
                    # the unlocked open: registering now would leak a map
                    # nothing ever closes — refuse and miss
                    closed = True
                    if count:
                        self._stats["misses"] += 1
                elif name in self._segments and name not in self._mmaps:
                    self._mmaps[name] = mm
                    registered = True
            if not registered:
                # lost the race (another reader mapped it, or the
                # segment was dropped meanwhile): this map is surplus
                mm.close()
                if closed:
                    return None

    def _read_view_locked(
        self, key, mm, off, nbytes, dtype, shape, count: bool
    ) -> "np.ndarray | None":
        """Zero-copy view over an already-mapped segment (lock held)."""
        try:
            if off + nbytes > len(mm):
                raise ValueError("entry outside segment")
            arr = np.frombuffer(
                mm, dtype=np.dtype(dtype), count=int(
                    nbytes // np.dtype(dtype).itemsize
                ), offset=off,
            ).reshape(shape)
        except ValueError:
            # entry-level inconsistency: drop just it — the caller
            # re-decodes
            self._drop_locked(key, "corrupt_dropped")
            if count:
                self._stats["misses"] += 1
            return None
        if count:
            self._stats["hits"] += 1
        return arr

    # -- write path --------------------------------------------------------
    def put(self, key: tuple, arr: "np.ndarray") -> None:
        """Persist one decoded block (idempotent; no-op when oversized).

        A stale generation of the same block is dropped first; the block
        buffers in the pending segment and commits at the next flush.
        """
        nbytes = int(arr.nbytes)
        if nbytes > self.budget_bytes:
            return
        flush_now = False
        with self._lock:
            if (
                key in self._pending
                or key in self._flushing
                or key in self._index
            ):
                return
            stale = self._by_block.get(self._block_id(key))
            if stale is not None and stale != key:
                self._drop_locked(stale, "stale_dropped")
            self._pending[key] = np.ascontiguousarray(arr)
            self._by_block[self._block_id(key)] = key
            self._pending_bytes += nbytes
            self._stats["put_blocks"] += 1
            self._stats["put_bytes"] += nbytes
            flush_now = self._pending_bytes >= self.segment_bytes
        if flush_now:
            self.flush()

    def flush(self) -> None:
        """Commit the pending blocks as one segment (tmp + rename, data
        before index — the index rename is the commit point).

        The multi-MiB disk write runs OUTSIDE the instance lock (decode
        threads' get/put must not stall behind a segment rollover): the
        batch is detached into ``_flushing`` — still served by reads,
        still idempotence-checked by puts — written, then committed
        under the lock.  A key dropped mid-flush (corruption, stale
        generation) is simply not indexed; its bytes stay as dead space.
        """
        with self._flush_lock:
            with self._lock:
                if not self._pending:
                    return
                self._flushing = self._pending
                self._pending = {}
                self._pending_bytes = 0
                name = f"seg-{os.getpid()}-{self._seq:06d}"
                self._seq += 1
                batch = list(self._flushing.items())

            entries = []
            off = 0
            chunks = []
            for key, arr in batch:
                raw = arr.tobytes()
                chunks.append(raw)
                entries.append(
                    {
                        "key": _key_list(key),
                        "off": off,
                        "nbytes": len(raw),
                        "dtype": np.dtype(arr.dtype).name,
                        "shape": list(arr.shape),
                    }
                )
                off += len(raw)
            bin_path = os.path.join(self.root, name + ".bin")
            sc_path = os.path.join(self.root, name + ".json")
            try:
                tmp = bin_path + ".tmp"
                with open(tmp, "wb") as f:
                    for raw in chunks:
                        f.write(raw)
                os.replace(tmp, bin_path)
                tmp = sc_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"entries": entries, "bytes": off}, f)
                os.replace(tmp, sc_path)
            except OSError:
                # a failed flush (full disk) degrades to "not persisted":
                # the blocks re-decode next run — never fail the read
                # path for it
                with self._lock:
                    for key, _arr in batch:
                        if self._by_block.get(self._block_id(key)) == key:
                            del self._by_block[self._block_id(key)]
                    self._flushing = {}
                for p in (bin_path, sc_path, bin_path + ".tmp", sc_path + ".tmp"):
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
                return
            with self._lock:
                keys = set()
                for e in entries:
                    key = tuple(e["key"])
                    if key not in self._flushing:
                        continue  # dropped mid-flush: leave unindexed
                    self._index[key] = (
                        name, e["off"], e["nbytes"], e["dtype"],
                        tuple(e["shape"]),
                    )
                    keys.add(key)
                self._segments[name] = {
                    "bytes": off,
                    "keys": keys,
                    "mtime": os.path.getmtime(sc_path),
                }
                self._flushing = {}
                self._evict_to_budget_locked()

    # -- drop / evict ------------------------------------------------------
    def drop(self, key: tuple, corrupt: bool = True) -> None:
        """Invalidate one entry (the ``drop_corrupt`` forward from the
        in-memory cache: a consumer found the served block damaged)."""
        with self._lock:
            self._drop_locked(key, "corrupt_dropped" if corrupt else None)

    def _drop_locked(self, key: tuple, stat: "str | None") -> None:
        dropped = False
        if self._pending.pop(key, None) is not None:
            dropped = True
        if self._flushing.pop(key, None) is not None:
            dropped = True  # the in-flight flush will skip indexing it
        ent = self._index.pop(key, None)
        if ent is not None:
            seg = self._segments.get(ent[0])
            if seg is not None:
                seg["keys"].discard(key)
            dropped = True
        if dropped:
            bid = self._block_id(key)
            if self._by_block.get(bid) == key:
                del self._by_block[bid]
            if stat is not None:
                self._stats[stat] += 1

    def _drop_segment_locked(self, name: str) -> None:
        """Forget one whole segment: index entries, mmap, files."""
        seg = self._segments.pop(name, None)
        if seg is not None:
            for key in seg["keys"]:
                self._index.pop(key, None)
                bid = self._block_id(key)
                if self._by_block.get(bid) == key:
                    del self._by_block[bid]
        mm = self._mmaps.pop(name, None)
        if mm is not None:
            try:
                mm.close()
            except BufferError:
                pass  # live views pin it; freed with the last view
        for suffix in (".bin", ".json"):
            try:
                os.unlink(os.path.join(self.root, name + suffix))
            except OSError:
                pass

    def _evict_to_budget_locked(self) -> None:
        while (
            sum(s["bytes"] for s in self._segments.values())
            > self.budget_bytes
            and self._segments
        ):
            name = min(
                self._segments, key=lambda n: (self._segments[n]["mtime"], n)
            )
            self._drop_segment_locked(name)
            self._stats["evicted_segments"] += 1

    # -- lifecycle / stats -------------------------------------------------
    def close(self) -> None:
        """Flush pending blocks and release the mmaps (views stay valid —
        they hold their own buffer references).  Marks the store closed
        so a reader mid-cold-open cannot register a fresh mmap into the
        torn-down table (it misses instead); index/stats reads keep
        working on a closed store."""
        self.flush()
        with self._lock:
            self._closed = True
            for mm in self._mmaps.values():
                try:
                    mm.close()
                except BufferError:
                    pass
            self._mmaps.clear()

    def stats_snapshot(self) -> dict:
        """Cumulative counters plus current occupancy gauges."""
        with self._lock:
            out = dict(self._stats)
            out["bytes"] = (
                sum(s["bytes"] for s in self._segments.values())
                + self._pending_bytes
                + sum(a.nbytes for a in self._flushing.values())
            )
            out["budget_bytes"] = self.budget_bytes
            out["segments"] = len(self._segments)
            return out

    def stats_delta(self, base: dict) -> dict:
        """Counters accumulated since ``base``; occupancy gauges
        (``bytes``/``budget_bytes``/``segments``) are reported as-is."""
        now = self.stats_snapshot()
        out = {}
        for k, v in now.items():
            if k in ("bytes", "budget_bytes", "segments"):
                out[k] = v
            else:
                out[k] = v - base.get(k, 0)
        return out
