"""Minimal self-contained GeoTIFF codec (NumPy + zlib; no GDAL).

The reference's raster layer reads Landsat GeoTIFF stacks and writes segment
rasters through GDAL's Python bindings (SURVEY.md §2 layer L1, provenance
``[B]`` behaviour / ``[K]`` library).  GDAL is not available in this
environment (SURVEY.md §7 hard-part 5), so the framework vendors the small
slice of TIFF 6.0 + GeoTIFF it actually needs:

* classic TIFF **and BigTIFF**, little- or big-endian, **read**: stripped
  or tiled layout, uncompressed / Deflate (zlib) / raw-deflate / LZW,
  horizontal-differencing predictor, chunky or planar multi-band,
  u/int 8/16/32, float32/64;
* **write**: tiled (default) or stripped, Deflate, LZW, or uncompressed,
  optional horizontal predictor, any of the dtypes above, chunky band
  layout;
  classic by default, switching to BigTIFF automatically when the encoded
  file would overflow 4 GB addressing (CONUS ARD mosaic products,
  SURVEY.md §7 hard-part 5);
* GeoTIFF georeferencing carried as an opaque-but-typed :class:`GeoMeta`
  (pixel scale + tiepoint + the raw GeoKey directory blocks), round-tripped
  losslessly so outputs inherit the input grid.

This is host-side I/O: arrays land in NumPy and are fed to the TPU pipeline
by the runtime driver.
"""

from __future__ import annotations

import dataclasses
import mmap
import struct
import time
import zlib
from typing import BinaryIO, Mapping

import numpy as np

from land_trendr_tpu.io import blockcache, native

__all__ = ["GeoMeta", "TiffInfo", "read_geotiff", "write_geotiff"]

# -- TIFF tag ids -----------------------------------------------------------
_T_NEW_SUBFILE_TYPE = 254
_T_IMAGE_WIDTH = 256
_T_IMAGE_LENGTH = 257
_T_BITS_PER_SAMPLE = 258
_T_COMPRESSION = 259
_T_PHOTOMETRIC = 262
_T_STRIP_OFFSETS = 273
_T_SAMPLES_PER_PIXEL = 277
_T_ROWS_PER_STRIP = 278
_T_STRIP_BYTE_COUNTS = 279
_T_PLANAR_CONFIG = 284
_T_PREDICTOR = 317
_T_TILE_WIDTH = 322
_T_TILE_LENGTH = 323
_T_TILE_OFFSETS = 324
_T_TILE_BYTE_COUNTS = 325
_T_SAMPLE_FORMAT = 339
_T_MODEL_PIXEL_SCALE = 33550
_T_MODEL_TIEPOINT = 33922
_T_GEO_KEY_DIRECTORY = 34735
_T_GEO_DOUBLE_PARAMS = 34736
_T_GEO_ASCII_PARAMS = 34737
_T_GDAL_NODATA = 42113

_COMP_NONE = 1
_COMP_LZW = 5
_COMP_DEFLATE_ADOBE = 8
_COMP_DEFLATE_OLD = 32946

# TIFF field types → (struct char, byte size)
_FIELD_TYPES = {
    1: ("B", 1),   # BYTE
    2: ("s", 1),   # ASCII
    3: ("H", 2),   # SHORT
    4: ("I", 4),   # LONG
    5: ("II", 8),  # RATIONAL (2×LONG)
    6: ("b", 1),   # SBYTE
    8: ("h", 2),   # SSHORT
    9: ("i", 4),   # SLONG
    11: ("f", 4),  # FLOAT
    12: ("d", 8),  # DOUBLE
    13: ("I", 4),  # IFD
    16: ("Q", 8),  # LONG8 (BigTIFF)
    17: ("q", 8),  # SLONG8 (BigTIFF)
}

# (sample_format, bits) → numpy dtype char
_DTYPES = {
    (1, 8): "u1", (1, 16): "u2", (1, 32): "u4",
    (2, 8): "i1", (2, 16): "i2", (2, 32): "i4",
    (3, 32): "f4", (3, 64): "f8",
}
_DTYPE_TO_FORMAT = {np.dtype(v): k for k, v in _DTYPES.items()}


@dataclasses.dataclass(frozen=True)
class GeoMeta:
    """Georeferencing sidecar, round-tripped verbatim between files.

    ``pixel_scale`` is the GeoTIFF ModelPixelScale ``(sx, sy, sz)``;
    ``tiepoint`` the first ModelTiepoint ``(i, j, k, x, y, z)``; the three
    ``geo_*`` fields carry the GeoKey directory blocks untouched (the
    framework never interprets projection parameters — it only preserves
    them, which is all the segment-raster writer needs).
    """

    pixel_scale: tuple[float, ...] | None = None
    tiepoint: tuple[float, ...] | None = None
    geo_key_directory: tuple[int, ...] | None = None
    geo_double_params: tuple[float, ...] | None = None
    geo_ascii_params: str | None = None
    nodata: float | None = None

    def geotransform(self) -> tuple[float, float, float, float, float, float] | None:
        """GDAL-style (x0, dx, 0, y0, 0, -dy) affine, when defined."""
        if not self.pixel_scale or not self.tiepoint:
            return None
        sx, sy = self.pixel_scale[0], self.pixel_scale[1]
        i, j, _k, x, y, _z = self.tiepoint[:6]
        return (x - i * sx, sx, 0.0, y + j * sy, 0.0, -sy)


@dataclasses.dataclass(frozen=True)
class TiffInfo:
    """Shape/layout facts about a decoded file (useful for tests/tools)."""

    width: int
    height: int
    bands: int
    dtype: np.dtype
    tiled: bool
    compression: int
    big: bool = False
    #: block geometry (TileLength/TileWidth, or RowsPerStrip/width) — the
    #: natural window-read granularity; set by read_geotiff_info
    block_rows: int | None = None
    block_cols: int | None = None
    #: reduced-resolution (overview/mask) pages in the IFD chain; set by
    #: read_geotiff_info so rewriting tools can reproduce the pyramid
    overview_pages: int = 0

    def compression_name(self) -> str:
        return {1: "none", 5: "lzw", 8: "deflate", 32946: "deflate"}.get(
            self.compression, "deflate"
        )


def _read_ifd(
    f: BinaryIO, bo: str, off: int, big: bool = False
) -> tuple[dict[int, tuple], int]:
    """Parse one IFD; ``big`` selects BigTIFF layout (u64 entry count,
    20-byte entries with 8-byte inline values, u64 value offsets).

    Returns ``(tags, next_ifd_offset)`` — 0 when this is the last IFD, so
    multi-page files (e.g. pre-stacked per-year series written one band
    per page) can be walked instead of silently truncated to page 1.
    """
    f.seek(0, 2)
    file_size = f.tell()
    if not (0 <= off < file_size):
        raise ValueError(
            f"corrupt TIFF: IFD offset {off} outside file (size {file_size})"
        )
    f.seek(off)

    def read_exact(n: int) -> bytes:
        buf = f.read(n)
        if len(buf) != n:
            raise ValueError(
                f"corrupt TIFF: truncated at offset {f.tell()} "
                f"(wanted {n} bytes, got {len(buf)})"
            )
        return buf

    if big:
        (n,) = struct.unpack(bo + "Q", read_exact(8))
        # the on-disk u64 count is untrusted: a truncated/corrupt file must
        # fail parsing, not attempt an exabyte read (classic TIFF's u16
        # field caps itself; mirror that bound here)
        if n > 0xFFFF:
            raise ValueError(f"corrupt BigTIFF IFD: implausible entry count {n}")
        esz, inline, ptr_fmt = 20, 8, "Q"
        head_fmt = bo + "HHQ"
    else:
        (n,) = struct.unpack(bo + "H", read_exact(2))
        esz, inline, ptr_fmt = 12, 4, "I"
        head_fmt = bo + "HHI"
    entries: dict[int, tuple] = {}
    raw = read_exact(n * esz)
    for k in range(n):
        tag, ftype, count = struct.unpack(head_fmt, raw[k * esz : k * esz + esz - inline])
        if ftype not in _FIELD_TYPES:
            continue
        ch, sz = _FIELD_TYPES[ftype]  # sz already totals both LONGs for RATIONAL
        total = sz * count
        # the on-disk count is untrusted: an out-of-line payload can never be
        # larger than the file itself, so a corrupt huge count must fail
        # parsing here, not drive f.read() into a multi-TB allocation
        if total > file_size:
            raise ValueError(
                f"corrupt TIFF IFD: tag {tag} payload {total} bytes exceeds "
                f"file size {file_size}"
            )
        val_off = k * esz + (esz - inline)
        if total <= inline:
            payload = raw[val_off : val_off + total]
        else:
            (ptr,) = struct.unpack(bo + ptr_fmt, raw[val_off : val_off + inline])
            if ptr + total > file_size:
                raise ValueError(
                    f"corrupt TIFF: tag {tag} payload at {ptr} runs past "
                    f"file size {file_size}"
                )
            here = f.tell()
            f.seek(ptr)
            payload = read_exact(total)
            f.seek(here)
        if ftype == 2:
            entries[tag] = (payload.rstrip(b"\0").decode("ascii", "replace"),)
        elif ftype == 5:
            vals = struct.unpack(bo + "I" * (2 * count), payload)
            entries[tag] = tuple(
                vals[i] / vals[i + 1] if vals[i + 1] else 0.0
                for i in range(0, 2 * count, 2)
            )
        else:
            entries[tag] = struct.unpack(bo + ch * count, payload)
    f.seek(off + (8 if big else 2) + n * esz)
    ptr_sz = 8 if big else 4
    raw_next = f.read(ptr_sz)
    next_off = (
        struct.unpack(bo + ("Q" if big else "I"), raw_next)[0]
        if len(raw_next) == ptr_sz
        else 0
    )
    # untrusted trailer: a garbage pointer must fail the codec's ValueError
    # taxonomy here, not as a struct.error/KeyError while parsing junk
    if next_off and not (8 <= next_off < file_size):
        raise ValueError(
            f"corrupt TIFF: next-IFD offset {next_off} outside file "
            f"(size {file_size})"
        )
    return entries, next_off


def _lzw_decode(data: bytes) -> bytes:
    """TIFF 6.0 LZW (compression 5): MSB-first bit packing, ClearCode=256,
    EOI=257, 9→12-bit codes with the spec's "early change" width bumps.

    Pure-Python behavioural reference for ``lt_native.cc::lzw_decode`` (the
    threaded fast path); real Landsat C2 distribution files commonly ship
    LZW-compressed, which GDAL handled for the reference for free
    (SURVEY.md §2 L1).
    """
    CLEAR, EOI = 256, 257
    out = bytearray()
    table: list[bytes] = []
    code_bits = 9
    next_code = 258
    prev: bytes | None = None
    bitpos = 0
    total_bits = len(data) * 8

    def read_code() -> int:
        nonlocal bitpos
        if bitpos + code_bits > total_bits:
            return EOI
        byte0 = bitpos >> 3
        chunk = int.from_bytes(data[byte0 : byte0 + 4].ljust(4, b"\0"), "big")
        val = (chunk >> (32 - code_bits - (bitpos & 7))) & ((1 << code_bits) - 1)
        bitpos += code_bits
        return val

    while True:
        code = read_code()
        if code == EOI:
            break
        if code == CLEAR:
            table = [bytes([i]) for i in range(256)] + [b"", b""]
            code_bits = 9
            next_code = 258
            code = read_code()
            while code == CLEAR:  # libtiff tolerates consecutive Clear codes
                code = read_code()
            if code == EOI:
                break
            if code >= 256:
                raise ValueError("corrupt LZW: literal must follow clear")
            entry = table[code]
            out += entry
            prev = entry
            continue
        if prev is None or next_code >= 4096:
            raise ValueError("corrupt LZW: missing clear code")
        if code < next_code:
            entry = table[code]
        elif code == next_code:
            entry = prev + prev[:1]  # KwKwK
        else:
            raise ValueError("corrupt LZW: code beyond table")
        out += entry
        table.append(prev + entry[:1])
        next_code += 1
        if next_code == (1 << code_bits) - 1 and code_bits < 12:
            code_bits += 1
        prev = entry
    return bytes(out)


def _lzw_encode(data: bytes) -> bytes:
    """TIFF 6.0 LZW encoder: MSB-first packing, ClearCode first, 9→12-bit
    codes with the spec's "early change" width bumps, Clear + reset when
    the table fills (code 4094, libtiff's limit).

    Inverse of :func:`_lzw_decode`; outputs are validated round-trip
    against both our decoder and Pillow's (tests/test_geotiff.py), which
    pins the width-bump timing empirically.  The dictionary is
    ``(prefix_code << 8 | byte) → code`` so each input byte is one dict
    probe — O(n) overall.
    """
    CLEAR, EOI = 256, 257
    out = bytearray()
    buf = 0
    nbits = 0
    code_bits = 9

    def emit(code: int) -> None:
        nonlocal buf, nbits
        buf = (buf << code_bits) | code
        nbits += code_bits
        while nbits >= 8:
            nbits -= 8
            out.append((buf >> nbits) & 0xFF)
        buf &= (1 << nbits) - 1  # drop drained bits: keep buf a small int

    table: dict[int, int] = {}
    next_code = 258
    emit(CLEAR)
    prev = -1
    for b in data:
        if prev < 0:
            prev = b
            continue
        key = (prev << 8) | b
        code = table.get(key)
        if code is not None:
            prev = code
            continue
        emit(prev)
        table[key] = next_code
        next_code += 1
        prev = b
        # the decoder's table lags one add behind the encoder's, and its
        # "early change" bump fires at (1<<bits)-1 — so the encoder bumps
        # at (1<<bits): both sides widen before the same emitted code
        if next_code == (1 << code_bits) and code_bits < 12:
            code_bits += 1
        elif next_code >= 4094:  # table full: clear and restart
            emit(CLEAR)
            table.clear()
            next_code = 258
            code_bits = 9
    if prev >= 0:
        emit(prev)
        # the decoder's add for this final code catches its count up to
        # ours and can trigger its early-change bump — EOI must be written
        # at the width the decoder will read it with
        if next_code == (1 << code_bits) - 1 and code_bits < 12:
            code_bits += 1
    emit(EOI)
    if nbits:
        out.append((buf << (8 - nbits)) & 0xFF)
    return bytes(out)


def _decompress(buf: bytes, compression: int) -> bytes:
    if compression == _COMP_NONE:
        return buf
    if compression in (_COMP_DEFLATE_ADOBE, _COMP_DEFLATE_OLD):
        try:
            return zlib.decompress(buf)
        except zlib.error:
            try:
                return zlib.decompress(buf, -15)  # raw deflate stream
            except zlib.error as e:
                # keep the corrupt-file ValueError taxonomy — zlib.error
                # must not escape to callers
                raise ValueError(f"corrupt deflate block: {e}") from e
    if compression == _COMP_LZW:
        return _lzw_decode(buf)
    raise ValueError(f"unsupported TIFF compression {compression}")


def _tag1(path: str, tags: dict[int, tuple], tag: int, default=None):
    """First value of a tag; missing → ``default`` (or ValueError when
    required), present-but-empty (count=0) → ValueError."""
    vals = tags.get(tag)
    if vals is None:
        if default is None:
            raise ValueError(f"{path}: corrupt TIFF IFD (missing tag {tag})")
        return default
    if not vals:
        raise ValueError(f"{path}: corrupt TIFF IFD (empty tag {tag})")
    return vals[0]


def _unpredict(block: np.ndarray, predictor: int) -> np.ndarray:
    """Undo horizontal differencing in place along the row axis."""
    if predictor == 2:
        np.cumsum(block, axis=-2, dtype=block.dtype, out=block)
    return block


def _walk_full_pages(
    f: BinaryIO, path: str
) -> tuple[str, bool, list[dict[int, tuple]]]:
    """Parse the header and walk the IFD chain (tags only — no block data);
    returns ``(byte_order, big, full_resolution_page_tags)``.  Overview and
    mask pages (NewSubfileType reduced/mask bits) are skipped, as COGs and
    gdaladdo expect."""
    hdr = f.read(16)
    if len(hdr) < 8:
        raise ValueError(f"{path}: not a TIFF (truncated header)")
    if hdr[:2] == b"II":
        bo = "<"
    elif hdr[:2] == b"MM":
        bo = ">"
    else:
        raise ValueError(f"{path}: not a TIFF (bad byte-order mark)")
    (magic,) = struct.unpack(bo + "H", hdr[2:4])
    if magic == 42:
        big = False
        (ifd_off,) = struct.unpack(bo + "I", hdr[4:8])
    elif magic == 43:
        big = True
        if len(hdr) < 16:
            raise ValueError(f"{path}: not a BigTIFF (truncated header)")
        offsize, pad = struct.unpack(bo + "HH", hdr[4:8])
        if offsize != 8 or pad != 0:
            raise ValueError(
                f"{path}: BigTIFF with offset size {offsize} (only 8 supported)"
            )
        (ifd_off,) = struct.unpack(bo + "Q", hdr[8:16])
    else:
        raise ValueError(f"{path}: not a TIFF (magic={magic})")

    page_tags: list[dict[int, tuple]] = []
    seen: set[int] = set()
    n_reduced = 0
    off = ifd_off
    while off:
        if off in seen:
            raise ValueError(f"{path}: cyclic IFD chain at offset {off}")
        seen.add(off)
        tags, off = _read_ifd(f, bo, off, big)
        subtype = _tag1(path, tags, _T_NEW_SUBFILE_TYPE, 0)
        if subtype & 0x5:  # reduced-resolution overview (1) / mask (4)
            n_reduced += 1
            continue
        page_tags.append(tags)
    if not page_tags:
        raise ValueError(f"{path}: no full-resolution pages in IFD chain")
    return bo, big, page_tags, n_reduced


def _pages_geometry(
    path: str, page_tags: list[dict[int, tuple]]
) -> tuple[int, int, tuple, int]:
    """Validate the full-resolution pages agree in size/format (stacking
    mismatched pages would silently cast/truncate) and that each carries a
    complete block layout; returns ``(width, height, dtype_key,
    total_samples_per_pixel)``."""

    def geometry(tags):
        w = _tag1(path, tags, _T_IMAGE_WIDTH)
        h = _tag1(path, tags, _T_IMAGE_LENGTH)
        if _T_TILE_OFFSETS in tags:
            # tiled layout needs its companion tags too
            for req in (_T_TILE_WIDTH, _T_TILE_LENGTH, _T_TILE_BYTE_COUNTS):
                _tag1(path, tags, req)
        elif _T_STRIP_OFFSETS in tags:
            _tag1(path, tags, _T_STRIP_BYTE_COUNTS)
        else:
            raise ValueError(
                f"{path}: corrupt TIFF IFD (no strip or tile offsets)"
            )
        spp = _tag1(path, tags, _T_SAMPLES_PER_PIXEL, 1)
        if spp < 1:
            raise ValueError(f"{path}: corrupt TIFF IFD (SamplesPerPixel={spp})")
        bits = _tag1(path, tags, _T_BITS_PER_SAMPLE, 1)
        fmt = _tag1(path, tags, _T_SAMPLE_FORMAT, 1)
        return w, h, spp, (fmt, bits)

    w0, h0, _, key0 = geometry(page_tags[0])
    total_spp = 0
    for k, tags in enumerate(page_tags):
        w, h, spp, key = geometry(tags)
        if (w, h, key) != (w0, h0, key0):
            raise ValueError(
                f"{path}: page {k} is {h}×{w}/format{key}, page 0 is "
                f"{h0}×{w0}/format{key0} — refusing to stack "
                "mismatched pages"
            )
        total_spp += spp
    if key0 not in _DTYPES:
        raise ValueError(f"{path}: unsupported sample format/bits {key0}")
    return w0, h0, key0, total_spp


def read_geotiff(path: str) -> tuple[np.ndarray, GeoMeta, TiffInfo]:
    """Decode a GeoTIFF into ``(array, geo, info)``.

    ``array`` is ``(height, width)`` for single-band files and
    ``(bands, height, width)`` otherwise, in the file's native dtype.

    Multi-page files (an IFD chain) are read page by page into ONE
    allocation and stacked along the band axis — the layout some
    pre-stacked per-year products use (one band per page).  Overview and
    mask pages (NewSubfileType reduced-resolution/mask bits — what COGs
    and gdaladdo produce) are skipped, so Cloud-Optimized GeoTIFFs read
    as their full-resolution image.  Full-resolution pages must agree in
    size and dtype; a mismatch raises instead of silently truncating to
    page 1.
    """
    with open(path, "rb") as f:
        bo, big, page_tags, _ = _walk_full_pages(f, path)
        w0, h0, key0, total_spp = _pages_geometry(path, page_tags)
        # untrusted dimensions: deflate/LZW top out near ~1032:1, so a
        # decoded size beyond file_size × 64Ki (or an absolute 1 TiB) can
        # only come from corrupt width/height tags — fail before np.zeros
        # attempts a garbage-driven multi-TB allocation
        f.seek(0, 2)
        fsize = f.tell()
        decoded = total_spp * h0 * w0 * np.dtype(_DTYPES[key0]).itemsize
        if decoded > min((fsize + 4096) * 65536, 2**40):
            raise ValueError(
                f"{path}: corrupt TIFF dimensions {total_spp}×{h0}×{w0} "
                f"({decoded} decoded bytes from a {fsize}-byte file)"
            )
        out = np.zeros((total_spp, h0, w0), dtype=np.dtype(_DTYPES[key0]))

        geo: GeoMeta | None = None
        info: TiffInfo | None = None
        band0 = 0
        for tags in page_tags:
            spp = _tag1(path, tags, _T_SAMPLES_PER_PIXEL, 1)
            g, inf = _decode_ifd(f, path, bo, big, tags, out[band0 : band0 + spp])
            band0 += spp
            if geo is None:
                geo, info = g, inf
        assert info is not None
        info = dataclasses.replace(info, bands=total_spp)
        arr = out[0] if total_spp == 1 else out
        return arr, geo, info


def read_geotiff_info(path: str) -> tuple[GeoMeta, TiffInfo]:
    """Header-only inspection (the ``gdalinfo`` seam): geo + shape/layout
    facts from the IFD chain alone.  No block data is read or decoded, so
    this is O(tags) even on a multi-GB mosaic — the cheap first step of
    any windowed-read workflow."""
    with open(path, "rb") as f:
        bo, big, page_tags, n_reduced = _walk_full_pages(f, path)
        width, height, key, total_spp = _pages_geometry(path, page_tags)
        tags = page_tags[0]
        tiled = _T_TILE_OFFSETS in tags
        if tiled:
            block_rows = _tag1(path, tags, _T_TILE_LENGTH)
            block_cols = _tag1(path, tags, _T_TILE_WIDTH)
        else:
            block_rows = min(
                _tag1(path, tags, _T_ROWS_PER_STRIP, height), height
            )
            block_cols = width
        info = TiffInfo(
            width=width,
            height=height,
            bands=total_spp,
            dtype=np.dtype(_DTYPES[key]),
            tiled=tiled,
            compression=_tag1(path, tags, _T_COMPRESSION, _COMP_NONE),
            big=big,
            block_rows=block_rows,
            block_cols=block_cols,
            overview_pages=n_reduced,
        )
        return _page_geo(tags), info


def read_geotiff_window(
    path: str, y0: int, x0: int, h: int, w: int
) -> np.ndarray:
    """Random-access window read: decode ONLY the blocks intersecting
    ``(y0, x0, h, w)`` of every full-resolution page — I/O and decode cost
    scale with the window, not the raster (GDAL's ReadAsArray-with-window
    seam; the piece that lets change maps and inspection tooling run over
    CONUS-scale mosaics in bounded memory).

    Returns ``(h, w)`` for single-band files, ``(bands, h, w)`` otherwise
    (multi-page band stacking as in :func:`read_geotiff`).  Georeferencing
    is the FULL raster's — offset by ``(y0, x0)`` pixels when a window
    transform is needed (``GeoMeta.geotransform``)."""
    # fault seam "feed.decode" (runtime.faults): the windowed feed path's
    # decode errors — a transient NFS read, a torn block — surface here
    blockcache.fault_check("feed.decode")
    with open(path, "rb") as f:
        bo, big, page_tags, _ = _walk_full_pages(f, path)
        width, height, key, total_spp = _pages_geometry(path, page_tags)
        # bounds BEFORE allocation: a typo'd window must fail with this
        # error, not a garbage-driven MemoryError from np.zeros
        if y0 < 0 or x0 < 0 or h < 1 or w < 1 or y0 + h > height or x0 + w > width:
            raise ValueError(
                f"{path}: window {(y0, x0, h, w)} outside raster "
                f"{(height, width)}"
            )
        spps = [_tag1(path, t, _T_SAMPLES_PER_PIXEL, 1) for t in page_tags]
        out = np.zeros((total_spp, h, w), dtype=np.dtype(_DTYPES[key]))
        # decoded-block cache identity (None = caching off): window reads
        # are the revisit-heavy path — tile edges, LazyBandCube re-reads,
        # resume passes — so only they populate/consult the cache
        fkey = blockcache.file_key(f, path) if blockcache.cache_enabled() else None
        band0 = 0
        for page, (tags, spp) in enumerate(zip(page_tags, spps)):
            _decode_ifd(
                f, path, bo, big, tags, out[band0 : band0 + spp],
                window=(y0, x0, h, w), page=page, fkey=fkey,
            )
            band0 += spp
    return out[0] if total_spp == 1 else out


def _decode_ifd(
    f: BinaryIO,
    path: str,
    bo: str,
    big: bool,
    tags: dict[int, tuple],
    out: np.ndarray,
    window: tuple[int, int, int, int] | None = None,
    page: int = 0,
    fkey: tuple | None = None,
) -> tuple[GeoMeta, TiffInfo]:
    """Decode one IFD's raster into the preallocated ``(spp, H, W)`` view
    ``out`` (native byte order); returns the page's geo/info.

    ``window=(y0, x0, h, w)`` decodes ONLY the blocks intersecting that
    region into an ``(spp, h, w)`` view — the random-access read path
    (GDAL ReadAsArray-with-window equivalent): I/O and decode cost scale
    with the window, not the raster.

    ``fkey`` (a :func:`blockcache.file_key` identity) + ``page`` enable
    the decoded-block cache for this page's blocks; ``None`` decodes
    uncached.  Cached and uncached reads are byte-identical."""
    width = _tag1(path, tags, _T_IMAGE_WIDTH)
    height = _tag1(path, tags, _T_IMAGE_LENGTH)
    spp = _tag1(path, tags, _T_SAMPLES_PER_PIXEL, 1)
    bits = tags.get(_T_BITS_PER_SAMPLE, (1,) * spp)
    if len(set(bits)) != 1:
        raise ValueError(f"{path}: mixed BitsPerSample {bits}")
    fmt = _tag1(path, tags, _T_SAMPLE_FORMAT, 1)
    key = (fmt, bits[0])
    if key not in _DTYPES:
        raise ValueError(f"{path}: unsupported sample format/bits {key}")
    dtype = np.dtype(bo + _DTYPES[key])
    compression = _tag1(path, tags, _T_COMPRESSION, _COMP_NONE)
    predictor = _tag1(path, tags, _T_PREDICTOR, 1)
    planar = _tag1(path, tags, _T_PLANAR_CONFIG, 1)
    tiled = _T_TILE_OFFSETS in tags

    planes = spp if planar == 2 else 1
    chunk_spp = 1 if planar == 2 else spp
    if window is None:
        window = (0, 0, height, width)
    wy, wx, wh, ww = window
    if wy < 0 or wx < 0 or wh < 1 or ww < 1 or wy + wh > height or wx + ww > width:
        raise ValueError(
            f"{path}: window {window} outside raster {(height, width)}"
        )
    if out.shape != (spp, wh, ww):
        raise ValueError(
            f"{path}: output view {out.shape} != window shape {(spp, wh, ww)}"
        )
    if tiled:
        tw = _tag1(path, tags, _T_TILE_WIDTH)
        th = _tag1(path, tags, _T_TILE_LENGTH)
        if tw < 1 or th < 1:
            raise ValueError(f"{path}: corrupt tile size {th}×{tw}")
        offsets = tags[_T_TILE_OFFSETS]
        counts = tags[_T_TILE_BYTE_COUNTS]
        blk_rows, blk_w = th, tw
        tiles_x = (width + tw - 1) // tw
        tiles_y = (height + th - 1) // th
        n_blocks = planes * tiles_x * tiles_y
        # blocks intersecting the window, with their grid coordinates —
        # the unit the decode below pays for
        coords: list[tuple] = [
            (p, ty, tx)
            for p in range(planes)
            for ty in range(wy // th, (wy + wh - 1) // th + 1)
            for tx in range(wx // tw, (wx + ww - 1) // tw + 1)
        ]
        sel = [p * tiles_y * tiles_x + ty * tiles_x + tx for p, ty, tx in coords]
    else:
        rps = _tag1(path, tags, _T_ROWS_PER_STRIP, height)
        if rps < 1:
            raise ValueError(f"{path}: corrupt RowsPerStrip {rps}")
        offsets = tags[_T_STRIP_OFFSETS]
        counts = tags[_T_STRIP_BYTE_COUNTS]
        # clamp: RowsPerStrip may legally exceed height (e.g. 2^32-1 =
        # "everything in one strip"); the buffer needs only real rows
        blk_rows, blk_w = min(rps, height), width
        strips = (height + rps - 1) // rps
        n_blocks = planes * strips
        coords = [
            (p, s)
            for p in range(planes)
            for s in range(wy // rps, (wy + wh - 1) // rps + 1)
        ]
        sel = [p * strips + s for p, s in coords]

    # untrusted block tables AND block geometry: the layout dictates how
    # many blocks the decode loops index, every selected block must lie
    # inside the file, and the block SLOT allocation (len(sel) × blk_rows
    # × blk_w — which corrupt TileWidth/TileLength tags can inflate far
    # beyond the image size) must pass the same plausibility budget as the
    # page — otherwise the native fast path np.zeros's from garbage
    # dimensions and dies with MemoryError instead of a clean parse error
    f.seek(0, 2)
    fsize = f.tell()
    if len(offsets) < n_blocks or len(counts) < n_blocks:
        raise ValueError(
            f"{path}: corrupt block table ({len(offsets)} offsets / "
            f"{len(counts)} counts for {n_blocks} blocks)"
        )
    sel_offsets = [offsets[i] for i in sel]
    sel_counts = [counts[i] for i in sel]
    slot_bytes = (
        len(sel) * blk_rows * blk_w * chunk_spp * dtype.itemsize
    )
    if slot_bytes > min((fsize + 4096) * 65536, 2**40):
        raise ValueError(
            f"{path}: corrupt block geometry ({len(sel)} blocks × "
            f"{blk_rows}×{blk_w}×{chunk_spp} = {slot_bytes} decoded bytes "
            f"from a {fsize}-byte file)"
        )
    for o, c in zip(sel_offsets, sel_counts):
        if o < 0 or c < 0 or o + c > fsize:
            raise ValueError(
                f"{path}: corrupt block table entry ({o}+{c} vs file "
                f"size {fsize})"
            )

    # Block decode, in three layers (land_trendr_tpu.io.blockcache):
    # (1) the decoded-block cache resolves revisited blocks instantly;
    # (2) cache misses run the native fast path when eligible — fused
    # inflate+unpredict across the missing blocks, threaded in C++
    # (native/lt_native.cc) under the shared decode_workers knob; (3) any
    # remainder (native absent, unsupported layout, or a NativeCodecError
    # fallback) decodes on the NumPy reference path, fanned over the
    # shared thread pool (zlib releases the GIL).  All three produce
    # byte-identical blocks — cache and pool are acceleration only.
    if tiled:
        rows_of = [blk_rows] * len(sel)  # file tiles are full-size
    else:
        # a legally-short last strip decodes only its real rows
        rows_of = [min(rps, height - s * rps) for _, s in coords]
    use_cache = fkey is not None and blockcache.cache_enabled()

    def _decode_one(pos: int, raw: bytes) -> np.ndarray:
        data = _decompress(raw, compression)
        b = np.frombuffer(
            data, dtype=dtype, count=rows_of[pos] * blk_w * chunk_spp
        )
        b = b.reshape(rows_of[pos], blk_w, chunk_spp).astype(
            dtype.newbyteorder("="), copy=True
        )
        return _unpredict(b, predictor)

    def _decode_at(pos: int) -> np.ndarray:
        """Serial reference path, one block straight from the file — the
        placement loop calls this lazily so a full-file read without the
        native lib holds ONE compressed + one decoded block beyond the
        output array, exactly as before the cache existed."""
        t0 = time.perf_counter()
        f.seek(sel_offsets[pos])
        b = _decode_one(pos, f.read(sel_counts[pos]))
        blockcache.note_decode_seconds(time.perf_counter() - t0)
        if use_cache:
            blockcache.cache_put((*fkey, page, sel[pos]), b)
        return b

    blocks: list[np.ndarray | None] = [None] * len(sel)
    if use_cache:
        native_dt = dtype.newbyteorder("=")
        for pos, bidx in enumerate(sel):
            b = blockcache.cache_get((*fkey, page, bidx))
            if b is not None:
                # fault seam "cache.corrupt" + the validation that makes a
                # poisoned entry survivable: a cached block that no longer
                # matches its slot's shape/dtype (bit rot, a corrupting
                # bug, an injected fault) is invalidated and re-decoded
                # from the file instead of failing the tile
                b = blockcache.fault_corrupt("cache.corrupt", b)
                if (
                    b.shape != (rows_of[pos], blk_w, chunk_spp)
                    or b.dtype != native_dt
                ):
                    blockcache.drop_corrupt((*fkey, page, bidx))
                    b = None
            blocks[pos] = b
    miss = [pos for pos, b in enumerate(blocks) if b is None]

    t_dec = time.perf_counter()
    if (
        miss
        and native.available()
        and bo == "<"
        # predictor 2 is integer differencing; float files tagged with
        # it (nonstandard) must keep NumPy's float-cumsum semantics
        and (predictor == 1 or (predictor == 2 and dtype.kind in "iu"))
    ):
        # mmap keeps peak host memory at the decoded array, not whole-file
        # bytes + decoded array, for scene-scale rasters
        try:
            buf = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (ValueError, OSError):  # empty file / non-mmappable stream
            f.seek(0)
            buf = f.read()
        try:
            nat_blocks = native.decode_blocks(
                buf,
                np.asarray([sel_offsets[p] for p in miss], dtype=np.uint64),
                np.asarray([sel_counts[p] for p in miss], dtype=np.uint64),
                compression=compression,
                predictor=predictor,
                rows=blk_rows,
                width=blk_w,
                spp=chunk_spp,
                dtype=dtype.newbyteorder("="),
                block_rows=np.asarray(
                    [rows_of[p] for p in miss], dtype=np.uint64
                ),
            )
        except native.NativeCodecError:
            nat_blocks = None
        finally:
            if isinstance(buf, mmap.mmap):
                try:
                    buf.close()
                except BufferError:
                    # a propagating exception's traceback can still pin
                    # the frombuffer view; don't mask it — the mmap is
                    # freed with the object
                    pass
        if nat_blocks is not None:
            for j, pos in enumerate(miss):
                b = nat_blocks[j][: rows_of[pos]]
                if use_cache:
                    # a copy, not the slice: caching the view would pin
                    # the whole (n_miss, rows, w, spp) batch in memory
                    b = b.copy()
                    blockcache.cache_put((*fkey, page, sel[pos]), b)
                blocks[pos] = b
            miss = []

    if miss:
        pool = blockcache.decode_pool() if len(miss) > 1 else None
        if pool is not None:
            # NumPy parallel path: raw bytes read serially up front (one
            # shared file handle), decompress+unpredict fanned over the
            # shared pool — transient memory is the misses' compressed
            # bytes, which a window read bounds to the window
            raws = []
            for pos in miss:
                f.seek(sel_offsets[pos])
                raws.append(f.read(sel_counts[pos]))
            for pos, b in zip(miss, pool.map(_decode_one, miss, raws)):
                if use_cache:
                    blockcache.cache_put((*fkey, page, sel[pos]), b)
                blocks[pos] = b
        # else: remaining misses stay None and decode lazily, one at a
        # time, inside the placement loop (_decode_at) — the pre-cache
        # serial memory profile
    blockcache.note_decode_seconds(time.perf_counter() - t_dec)

    for pos, coord in enumerate(coords):
        block = blocks[pos]
        if block is None:
            block = _decode_at(pos)
        if tiled:
            p, ty, tx = coord
            by, bx = ty * th, tx * tw
            bh = min(th, height - by)
            bw = min(tw, width - bx)
        else:
            p, s = coord
            by, bx = s * rps, 0
            bh = min(rps, height - by)
            bw = width
        # block ∩ window, placed window-relative (full reads: the whole block)
        ys, xs = max(wy, by), max(wx, bx)
        ye, xe = min(wy + wh, by + bh), min(wx + ww, bx + bw)
        sub = block[ys - by : ye - by, xs - bx : xe - bx]
        if planar == 2:
            out[p, ys - wy : ye - wy, xs - wx : xe - wx] = sub[..., 0]
        else:
            out[:, ys - wy : ye - wy, xs - wx : xe - wx] = np.moveaxis(
                sub, -1, 0
            )

    return _page_geo(tags), TiffInfo(
        width=width,
        height=height,
        bands=spp,
        dtype=np.dtype(_DTYPES[key]),
        tiled=tiled,
        compression=compression,
        big=big,
    )


def _page_geo(tags: dict[int, tuple]) -> GeoMeta:
    nodata = None
    if _T_GDAL_NODATA in tags:
        try:
            nodata = float(tags[_T_GDAL_NODATA][0])
        except (TypeError, ValueError):
            nodata = None
    return GeoMeta(
        pixel_scale=tags.get(_T_MODEL_PIXEL_SCALE),
        tiepoint=tags.get(_T_MODEL_TIEPOINT),
        geo_key_directory=tags.get(_T_GEO_KEY_DIRECTORY),
        geo_double_params=tags.get(_T_GEO_DOUBLE_PARAMS),
        geo_ascii_params=tags.get(_T_GEO_ASCII_PARAMS, (None,))[0],
        nodata=nodata,
    )


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------


class _ClassicOverflow(Exception):
    """Encoded file does not fit classic TIFF's u32 addressing."""


def _resolve_compress(compress: str | None) -> int:
    if compress == "deflate":
        return _COMP_DEFLATE_ADOBE
    if compress == "lzw":
        return _COMP_LZW
    if compress in (None, "none"):
        return _COMP_NONE
    raise ValueError(f"unsupported compression {compress!r}")


def _predict(block: np.ndarray) -> np.ndarray:
    """Apply horizontal differencing along the row axis (predictor 2)."""
    out = block.copy()
    out[..., 1:, :] = block[..., 1:, :] - block[..., :-1, :]
    return out


class _IfdBuilder:
    """Accumulates IFD entries + out-of-line payloads for a little-endian
    file; ``big=True`` emits the BigTIFF layout (u64 count, 20-byte entries,
    8-byte inline values, u64 offsets)."""

    def __init__(self, big: bool = False) -> None:
        self.big = big
        self.entries: list[tuple[int, int, int, bytes]] = []  # tag,type,count,payload

    def add(self, tag: int, ftype: int, values) -> None:
        ch, sz = _FIELD_TYPES[ftype]
        if ftype == 2:
            payload = values.encode("ascii") + b"\0"
            count = len(payload)
        else:
            vals = tuple(values)
            count = len(vals)
            try:
                payload = struct.pack("<" + ch * count, *vals)
            except struct.error as e:
                raise ValueError(
                    f"TIFF tag {tag}: value out of range for field type "
                    f"{ftype}: {e}"
                ) from e
        self.entries.append((tag, ftype, count, payload))

    def serialize(self, ifd_offset: int, next_off: int = 0) -> bytes:
        """Serialized IFD at ``ifd_offset`` whose next-IFD pointer is
        ``next_off`` (0 = end of chain).  The output LENGTH depends only on
        the entries, never on the offsets — multi-page layout relies on
        measuring with dummy offsets first."""
        self.entries.sort(key=lambda e: e[0])
        n = len(self.entries)
        if self.big:
            esz, inline, ptr_fmt = 20, 8, "Q"
            body = struct.pack("<Q", n)
            head_fmt = "<HHQ"
        else:
            esz, inline, ptr_fmt = 12, 4, "I"
            body = struct.pack("<H", n)
            head_fmt = "<HHI"
        overflow_off = ifd_offset + len(body) + n * esz + struct.calcsize("<" + ptr_fmt)
        overflow = b""
        for tag, ftype, count, payload in self.entries:
            body += struct.pack(head_fmt, tag, ftype, count)
            if len(payload) <= inline:
                body += payload.ljust(inline, b"\0")
            else:
                body += struct.pack("<" + ptr_fmt, overflow_off + len(overflow))
                # TIFF 6.0: value offsets must be even — pad odd payloads
                overflow += payload + b"\0" * (len(payload) & 1)
        body += struct.pack("<" + ptr_fmt, next_off)
        return body + overflow


def _page_ifd(
    big: bool,
    is_overview: bool,
    pw: int,
    ph: int,
    spp: int,
    bits: int,
    fmt: int,
    comp_id: int,
    use_pred: bool,
    tile: int | None,
    offsets,
    counts,
    geo: "GeoMeta | None",
    extra_ascii_tags: Mapping[int, str] | None,
    ifd_off: int,
    next_off: int,
) -> bytes:
    """Serialize one page's IFD (shared by the one-shot and streaming
    writers).  Geo/extra tags belong to the full-resolution page only —
    pass ``geo=None`` / ``extra_ascii_tags=None`` for overview pages.
    Raises :class:`_ClassicOverflow` when a classic-layout pointer
    overflows u32 (a 4 GB problem, not a tag-value problem)."""
    ifd = _IfdBuilder(big)
    if is_overview:
        ifd.add(_T_NEW_SUBFILE_TYPE, 4, (1,))  # reduced-resolution page
    ifd.add(_T_IMAGE_WIDTH, 4, (pw,))
    ifd.add(_T_IMAGE_LENGTH, 4, (ph,))
    ifd.add(_T_BITS_PER_SAMPLE, 3, (bits,) * spp)
    ifd.add(_T_COMPRESSION, 3, (comp_id,))
    ifd.add(_T_PHOTOMETRIC, 3, (1,))  # BlackIsZero
    ifd.add(_T_SAMPLES_PER_PIXEL, 3, (spp,))
    ifd.add(_T_PLANAR_CONFIG, 3, (1,))
    ifd.add(_T_SAMPLE_FORMAT, 3, (fmt,) * spp)
    if use_pred:
        ifd.add(_T_PREDICTOR, 3, (2,))
    off_type = 16 if big else 4  # LONG8 under BigTIFF
    if tile:
        ifd.add(_T_TILE_WIDTH, 3, (int(tile),))
        ifd.add(_T_TILE_LENGTH, 3, (int(tile),))
        ifd.add(_T_TILE_OFFSETS, off_type, offsets)
        ifd.add(_T_TILE_BYTE_COUNTS, off_type, counts)
    else:
        ifd.add(_T_ROWS_PER_STRIP, 3, (64,))
        ifd.add(_T_STRIP_OFFSETS, off_type, offsets)
        ifd.add(_T_STRIP_BYTE_COUNTS, off_type, counts)
    if geo is not None:
        if geo.pixel_scale:
            ifd.add(_T_MODEL_PIXEL_SCALE, 12, geo.pixel_scale)
        if geo.tiepoint:
            ifd.add(_T_MODEL_TIEPOINT, 12, geo.tiepoint)
        if geo.geo_key_directory:
            ifd.add(_T_GEO_KEY_DIRECTORY, 3, geo.geo_key_directory)
        if geo.geo_double_params:
            ifd.add(_T_GEO_DOUBLE_PARAMS, 12, geo.geo_double_params)
        if geo.geo_ascii_params:
            ifd.add(_T_GEO_ASCII_PARAMS, 2, geo.geo_ascii_params)
        if geo.nodata is not None:
            ifd.add(_T_GDAL_NODATA, 2, ("%g" % geo.nodata))
    for tag, text in (extra_ascii_tags or {}).items():
        ifd.add(tag, 2, text)
    try:
        return ifd.serialize(ifd_off, next_off)
    except struct.error as e:
        if big:
            raise  # not a 4 GB problem: bad tag values
        # an out-of-line payload pointer overflowed classic's u32
        raise _ClassicOverflow(str(e)) from e


def _overview_pyramid(
    chunky: np.ndarray, levels: int, resampling: str
) -> list[np.ndarray]:
    """2×-decimated ``(H, W, S)`` reductions of ``chunky``, each level
    built from the previous one.  ``"nearest"`` subsamples (safe for
    categorical products — year-of-detection, counts, masks — and GDAL's
    own default); ``"average"`` box-means 2×2 (odd edges replicate),
    rounding back into integer dtypes."""
    if resampling not in ("nearest", "average"):
        raise ValueError(f"resampling={resampling!r} not 'nearest'|'average'")
    out: list[np.ndarray] = []
    cur = chunky
    for _ in range(levels):
        h, w = cur.shape[:2]
        if min(h, w) < 2:
            break
        if resampling == "nearest":
            cur = np.ascontiguousarray(cur[::2, ::2])
        else:
            if h & 1:
                cur = np.concatenate([cur, cur[-1:]], axis=0)
            if w & 1:
                cur = np.concatenate([cur, cur[:, -1:]], axis=1)
            acc = (
                cur[0::2, 0::2].astype(np.float64)
                + cur[1::2, 0::2]
                + cur[0::2, 1::2]
                + cur[1::2, 1::2]
            ) / 4.0
            if chunky.dtype.kind in "iu":
                acc = np.rint(acc)
            cur = np.ascontiguousarray(acc.astype(chunky.dtype))
        out.append(cur)
    return out


def write_geotiff(
    path: str,
    array: np.ndarray,
    geo: GeoMeta | None = None,
    compress: str = "deflate",
    tile: int | None = 256,
    predictor: bool = True,
    extra_ascii_tags: Mapping[int, str] | None = None,
    bigtiff: bool | str = "auto",
    overviews: int | str = 0,
    resampling: str = "nearest",
) -> None:
    """Encode ``array`` (``(H, W)`` or ``(bands, H, W)``) as a GeoTIFF.

    Always little-endian, chunky band layout; ``tile=None`` writes one strip
    per 64 rows instead of tiles.  ``compress`` is ``"deflate"`` (default),
    ``"lzw"``, or ``"none"``.  ``predictor`` enables horizontal
    differencing for integer dtypes under deflate/LZW (better compression
    on smooth rasters; ignored for floats and uncompressed files).

    ``bigtiff``: ``"auto"`` (default) switches to the BigTIFF layout (u64
    offsets) exactly when the encoded file would overflow classic TIFF's
    4 GB addressing — e.g. the CONUS ARD mosaic products of the scale-out
    config (SURVEY.md §7 hard-part 5); ``True``/``False`` force the choice
    (forcing ``False`` on an oversized file raises).

    ``overviews`` appends that many 2×-decimated reduced-resolution pages
    (``"auto"``: until the smaller dimension drops under 256) to the IFD
    chain, each tagged ``NewSubfileType=1`` — the ``gdaladdo``-style
    pyramid GIS viewers expect on large rasters.  ``resampling`` picks the
    decimation (``"nearest"`` default — safe for categorical products;
    ``"average"`` for continuous ones).  :func:`read_geotiff` skips
    overview pages, so round-trips are unaffected.
    """
    arr = np.asarray(array)
    if arr.ndim == 2:
        arr = arr[None]
    if arr.ndim != 3:
        raise ValueError(f"array must be (H, W) or (bands, H, W); got {arr.shape}")
    if arr.dtype.newbyteorder("=") not in _DTYPE_TO_FORMAT:
        raise ValueError(f"unsupported dtype {arr.dtype}")
    arr = arr.astype(arr.dtype.newbyteorder("<"), copy=False)
    spp, height, width = arr.shape
    fmt, bits = _DTYPE_TO_FORMAT[arr.dtype.newbyteorder("=")]
    comp_id = _resolve_compress(compress)
    use_pred = bool(predictor) and comp_id != _COMP_NONE and fmt in (1, 2)

    chunky = np.moveaxis(arr, 0, -1)  # (H, W, S)

    if overviews == "auto":
        # halve any level whose smaller dimension is still >= 256, so the
        # last overview's smaller dimension drops under 256
        n_levels = 0
        d = min(height, width)
        while d >= 256:
            n_levels += 1
            d //= 2
    else:
        n_levels = int(overviews)
        if n_levels < 0:
            raise ValueError(f"overviews={overviews!r} must be >= 0 or 'auto'")
    pages = [chunky] + (
        _overview_pyramid(chunky, n_levels, resampling) if n_levels else []
    )
    page_shapes = [p.shape[:2] for p in pages]

    def gen_blocks(page: np.ndarray):
        ph, pw = page.shape[:2]
        if tile:
            tw = th = int(tile)
            for ty in range((ph + th - 1) // th):
                for tx in range((pw + tw - 1) // tw):
                    full = np.zeros((th, tw, spp), dtype=arr.dtype)
                    y0, x0 = ty * th, tx * tw
                    h = min(th, ph - y0)
                    w = min(tw, pw - x0)
                    full[:h, :w] = page[y0 : y0 + h, x0 : x0 + w]
                    yield full
        else:
            for y0 in range(0, ph, 64):
                yield np.ascontiguousarray(page[y0 : y0 + 64])

    page_blocks = [_encode_all(gen_blocks(p), comp_id, use_pred) for p in pages]
    # only shapes are needed past this point — drop the raw overview arrays
    # so a CONUS-scale 'auto' write doesn't hold ~1/3 extra uncompressed
    # raster through layout() and the write loop
    del pages

    def _build_ifd(
        big: bool, page_i: int, ifd_off: int, next_off: int, offsets, counts
    ) -> bytes:
        ph, pw = page_shapes[page_i]
        return _page_ifd(
            big,
            page_i > 0,
            pw,
            ph,
            spp,
            bits,
            fmt,
            comp_id,
            use_pred,
            tile,
            offsets,
            counts,
            geo if page_i == 0 else None,  # georeferencing: full page only
            extra_ascii_tags if page_i == 0 else None,
            ifd_off,
            next_off,
        )

    def layout(big: bool):
        """Exact file layout for one format choice: per-page block
        offsets/counts and the serialized IFD chain (all out-of-line
        payloads included), so the 4 GB decision below is based on real
        sizes, not a heuristic bound.  IFD blob LENGTHS are offset-
        independent (_IfdBuilder.serialize), so pass 1 measures with dummy
        offsets and pass 2 re-serializes at the true positions."""
        data_off = 16 if big else 8  # blocks start right after the header
        pos = data_off
        page_offs = []
        for blocks in page_blocks:
            offsets: list[int] = []
            counts: list[int] = []
            for b in blocks:
                offsets.append(pos)
                counts.append(len(b))
                pos += len(b) + (len(b) & 1)  # keep offsets word-aligned
            page_offs.append((offsets, counts))
        # classic-u32 bounds are checked EXPLICITLY here and at serialize
        # time only — a struct.error from tag *values* (e.g. an out-of-range
        # geo key SHORT) is a genuine input error in both layouts and
        # propagates as-is instead of masquerading as "file too big"
        if not big and pos > 2**32 - 1:
            raise _ClassicOverflow(f"block data ends at {pos} bytes")
        sizes = [
            len(_build_ifd(big, i, 0, 0, *page_offs[i]))
            for i in range(len(page_shapes))
        ]
        ifd_positions = []
        cur = pos
        for s in sizes:
            ifd_positions.append(cur)
            cur += s
        if not big and cur > 2**32 - 1:
            raise _ClassicOverflow(f"file ends at {cur} bytes")
        ifd_blobs = []
        for i in range(len(page_shapes)):
            nxt = ifd_positions[i + 1] if i + 1 < len(page_shapes) else 0
            blob = _build_ifd(big, i, ifd_positions[i], nxt, *page_offs[i])
            assert len(blob) == sizes[i]
            ifd_blobs.append(blob)
        return ifd_positions[0], ifd_blobs

    if bigtiff == "auto":
        try:
            big = False
            ifd0_off, ifd_blobs = layout(False)
        except _ClassicOverflow:
            big = True
            ifd0_off, ifd_blobs = layout(True)
    else:
        big = bool(bigtiff)
        try:
            ifd0_off, ifd_blobs = layout(big)
        except _ClassicOverflow as e:
            raise ValueError(
                f"{path}: encoded size exceeds classic TIFF's 4 GB addressing "
                f"({e}); use bigtiff=True (or the default bigtiff='auto')"
            ) from e

    with open(path, "wb") as f:
        if big:
            f.write(struct.pack("<2sHHHQ", b"II", 43, 8, 0, ifd0_off))
        else:
            f.write(struct.pack("<2sHI", b"II", 42, ifd0_off))
        for blocks in page_blocks:
            for b in blocks:
                f.write(b)
                if len(b) & 1:
                    f.write(b"\0")
        for blob in ifd_blobs:
            f.write(blob)


class _StreamLevel:
    """Per-page bookkeeping for :class:`GeoTiffStreamWriter`: grid shape,
    partially-filled block buffers, and the offset/count tables the IFD
    needs at close."""

    __slots__ = ("ph", "pw", "nby", "nbx", "partial", "filled", "offsets", "counts")

    def __init__(self, ph: int, pw: int, tile: int) -> None:
        self.ph, self.pw = ph, pw
        self.nby = (ph + tile - 1) // tile
        self.nbx = (pw + tile - 1) // tile
        self.partial: dict[int, np.ndarray] = {}  # block idx -> (t, t, spp) buf
        self.filled: dict[int, int] = {}  # block idx -> real pixels covered
        self.offsets: list[int] = [0] * (self.nby * self.nbx)
        self.counts: list[int] = [0] * (self.nby * self.nbx)

    def real_area(self, idx: int, tile: int) -> int:
        ty, tx = divmod(idx, self.nbx)
        return min(tile, self.ph - ty * tile) * min(tile, self.pw - tx * tile)


class GeoTiffStreamWriter:
    """Incremental tiled GeoTIFF writer: windows in, blocks out, IFD at close.

    The one-shot :func:`write_geotiff` needs the whole ``(bands, H, W)``
    mosaic in host memory — fine at WRS-2 scene scale, impossible at the
    CONUS ARD mosaic scale of BASELINE configs[4] (one float32 band at
    ~9e9 px is ~36 GB).  This writer bounds host memory by O(open blocks):
    callers push non-overlapping ``(h, w, bands)`` windows in any order;
    every 256×256 block whose real coverage completes is compressed and
    appended to the file immediately (native-batched), and ``close()``
    writes the IFD chain at EOF and patches the header's first-IFD
    pointer — a layout every TIFF reader follows (offsets are explicit;
    nothing requires IFDs to precede data).

    Overviews build incrementally: each window cascades a nearest-
    decimated copy (global-parity aligned, so the result is pixel-
    identical to :func:`write_geotiff`'s ``resampling="nearest"`` pyramid)
    into the next level's block grid.  ``"average"`` resampling would need
    neighbor rows across window boundaries, so it stays a one-shot-writer
    feature.

    Memory: completed blocks leave immediately; a partial block lives
    until its real area is covered.  Row-major windows whose size is a
    multiple of 256 complete every block they touch on arrival (zero
    buffering); unaligned windows buffer at most one block-row per level.

    BigTIFF choice: the exact-layout probe of the one-shot writer needs
    every block encoded up front, which streaming exists to avoid — so
    ``bigtiff="auto"`` here picks classic only when a *worst-case* encoded
    bound (incompressible data through the chosen codec, plus IFD tables)
    fits u32 addressing with margin.  The bound errs toward BigTIFF; both
    layouts round-trip through :func:`read_geotiff`.
    """

    def __init__(
        self,
        path: str,
        height: int,
        width: int,
        bands: int,
        dtype,
        geo: GeoMeta | None = None,
        compress: str = "deflate",
        tile: int = 256,
        predictor: bool = True,
        extra_ascii_tags: Mapping[int, str] | None = None,
        bigtiff: bool | str = "auto",
        overviews: int | str = 0,
        resampling: str = "nearest",
        allow_partial: bool = False,
        compress_level: int = 6,
    ) -> None:
        dt = np.dtype(dtype)
        if dt.newbyteorder("=") not in _DTYPE_TO_FORMAT:
            raise ValueError(f"unsupported dtype {dt}")
        if not tile or int(tile) <= 0:
            raise ValueError("GeoTiffStreamWriter is tiled-only (tile >= 1)")
        if resampling != "nearest":
            raise ValueError(
                "streaming overviews are nearest-only (average needs "
                "cross-window neighbor rows); use write_geotiff for average"
            )
        if not -1 <= int(compress_level) <= 9:
            # eager like every other constructor check: zlib rejects
            # out-of-range levels only at the first flush, after a partial
            # file is already on disk
            raise ValueError(f"compress_level={compress_level} not in [-1, 9]")
        self.path = path
        self.height, self.width, self.spp = int(height), int(width), int(bands)
        self.dtype = dt.newbyteorder("<")
        self.fmt, self.bits = _DTYPE_TO_FORMAT[dt.newbyteorder("=")]
        self.comp_id = _resolve_compress(compress)
        #: zlib effort for deflate output (GDAL's ZLEVEL equivalent): 1 is
        #: ~3-4x faster for ~15% larger files — the right trade when the
        #: writer is the pipeline's CPU bottleneck (e.g. scene synthesis
        #: or manifest-heavy gigapixel runs).  Ignored for none/LZW.
        self.compress_level = int(compress_level)
        self.tile = int(tile)
        self.use_pred = bool(predictor) and self.comp_id != _COMP_NONE and self.fmt in (1, 2)
        self.geo = geo
        self.extra_ascii_tags = extra_ascii_tags
        self.allow_partial = allow_partial

        if overviews == "auto":
            n_levels, d = 0, min(self.height, self.width)
            while d >= 256:
                n_levels += 1
                d //= 2
        else:
            n_levels = int(overviews)
            if n_levels < 0:
                raise ValueError(f"overviews={overviews!r} must be >= 0 or 'auto'")
        self.levels: list[_StreamLevel] = [
            _StreamLevel(self.height, self.width, self.tile)
        ]
        ph, pw = self.height, self.width
        for _ in range(n_levels):
            if min(ph, pw) < 2:  # matches _overview_pyramid's stop rule
                break
            ph, pw = (ph + 1) // 2, (pw + 1) // 2
            self.levels.append(_StreamLevel(ph, pw, self.tile))

        self.big = self._pick_layout(bigtiff)
        self._pending: list[tuple[int, int, np.ndarray]] = []  # (level, idx, buf)
        self._closed = False
        self._f: BinaryIO = open(path, "wb")
        if self.big:
            self._f.write(struct.pack("<2sHHHQ", b"II", 43, 8, 0, 0))
            self._pos = 16
        else:
            self._f.write(struct.pack("<2sHI", b"II", 42, 0))
            self._pos = 8

    # -- layout ------------------------------------------------------------

    def _pick_layout(self, bigtiff: bool | str) -> bool:
        if bigtiff != "auto":
            return bool(bigtiff)
        t = self.tile
        n_blocks = sum(lv.nby * lv.nbx for lv in self.levels)
        raw_block = t * t * self.spp * self.dtype.itemsize
        if self.comp_id == _COMP_DEFLATE_ADOBE:
            # zlib worst case: stored blocks, ~5 bytes / 16 KB + header
            worst_block = raw_block + raw_block // 1000 + 64
        elif self.comp_id == _COMP_LZW:
            # 12-bit codes for 8-bit-novel data: 1.5x + table resets
            worst_block = raw_block * 3 // 2 + 64
        else:
            worst_block = raw_block + 1  # odd-length pad
        ifd_bound = 4096 + 16 * n_blocks + 2 * len(self.levels) * 512
        end = 16 + n_blocks * worst_block + ifd_bound
        return end > 2**32 - 2**20

    # -- write path --------------------------------------------------------

    def write(self, y0: int, x0: int, window: np.ndarray) -> None:
        """Scatter one non-overlapping ``(h, w)`` / ``(h, w, bands)`` window
        (top-left at ``(y0, x0)``) into the block grids of every level."""
        if self._closed:
            raise ValueError("writer is closed")
        win = np.asarray(window)
        if win.ndim == 2:
            win = win[..., None]
        if win.ndim != 3 or win.shape[2] != self.spp:
            raise ValueError(
                f"window must be (h, w) or (h, w, {self.spp}); got {win.shape}"
            )
        win = win.astype(self.dtype, copy=False)
        for lvl_i, lvl in enumerate(self.levels):
            h, w = win.shape[:2]
            if h == 0 or w == 0:
                break
            if y0 + h > lvl.ph or x0 + w > lvl.pw or y0 < 0 or x0 < 0:
                raise ValueError(
                    f"window {win.shape} at ({y0},{x0}) exceeds level {lvl_i} "
                    f"extent ({lvl.ph},{lvl.pw})"
                )
            self._scatter(lvl_i, y0, x0, win)
            if lvl_i + 1 == len(self.levels):
                break
            # nearest cascade, global-parity aligned: level L+1 row r is
            # global level-L row 2r, so keep local rows where (y0+i) is even
            sy, sx = y0 & 1, x0 & 1
            win = win[sy::2, sx::2]
            y0, x0 = (y0 + sy) // 2, (x0 + sx) // 2
        self._flush_pending()

    def _scatter(self, lvl_i: int, y0: int, x0: int, win: np.ndarray) -> None:
        lvl = self.levels[lvl_i]
        t = self.tile
        h, w = win.shape[:2]
        for ty in range(y0 // t, (y0 + h - 1) // t + 1):
            for tx in range(x0 // t, (x0 + w - 1) // t + 1):
                idx = ty * lvl.nbx + tx
                by, bx = ty * t, tx * t
                ys, xs = max(y0, by), max(x0, bx)
                ye, xe = min(y0 + h, by + t), min(x0 + w, bx + t)
                buf = lvl.partial.get(idx)
                if buf is None:
                    if lvl.counts[idx] or lvl.filled.get(idx):
                        raise ValueError(
                            f"level {lvl_i} block {idx} written twice "
                            "(windows must not overlap)"
                        )
                    buf = np.zeros((t, t, self.spp), dtype=self.dtype)
                    lvl.partial[idx] = buf
                buf[ys - by : ye - by, xs - bx : xe - bx] = win[
                    ys - y0 : ye - y0, xs - x0 : xe - x0
                ]
                filled = lvl.filled.get(idx, 0) + (ye - ys) * (xe - xs)
                lvl.filled[idx] = filled
                if filled == lvl.real_area(idx, t):
                    self._pending.append((lvl_i, idx, buf))
                    del lvl.partial[idx]

    def _flush_pending(self, force: bool = False) -> None:
        if not self._pending or (len(self._pending) < _ENCODE_CHUNK and not force):
            return
        blobs = _encode_all(
            (buf for _, _, buf in self._pending), self.comp_id, self.use_pred,
            self.compress_level,
        )
        for (lvl_i, idx, _), blob in zip(self._pending, blobs):
            lvl = self.levels[lvl_i]
            lvl.offsets[idx] = self._pos
            lvl.counts[idx] = len(blob)
            self._f.write(blob)
            self._pos += len(blob)
            if len(blob) & 1:  # keep offsets word-aligned
                self._f.write(b"\0")
                self._pos += 1
        self._pending.clear()

    # -- close -------------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        try:
            # incomplete = partially-touched AND never-touched blocks alike
            incomplete = [
                (i, idx)
                for i, lvl in enumerate(self.levels)
                for idx in range(lvl.nby * lvl.nbx)
                if lvl.filled.get(idx, 0) != lvl.real_area(idx, self.tile)
            ]
            if incomplete and not self.allow_partial:
                raise ValueError(
                    f"{len(incomplete)} block(s) not fully covered at close "
                    f"(first few: {incomplete[:5]}); pass allow_partial=True "
                    "to zero-fill"
                )
            for lvl_i, idx in incomplete:
                lvl = self.levels[lvl_i]
                buf = lvl.partial.pop(
                    idx, None
                )  # never-touched blocks become all-zero
                if buf is None:
                    buf = np.zeros((self.tile, self.tile, self.spp), self.dtype)
                self._pending.append((lvl_i, idx, buf))
            self._flush_pending(force=True)

            def build(ifd_positions: list[int]) -> list[bytes]:
                blobs = []
                for i, lvl in enumerate(self.levels):
                    nxt = (
                        ifd_positions[i + 1] if i + 1 < len(self.levels) else 0
                    )
                    blobs.append(
                        _page_ifd(
                            self.big,
                            i > 0,
                            lvl.pw,
                            lvl.ph,
                            self.spp,
                            self.bits,
                            self.fmt,
                            self.comp_id,
                            self.use_pred,
                            self.tile,
                            lvl.offsets,
                            lvl.counts,
                            self.geo if i == 0 else None,
                            self.extra_ascii_tags if i == 0 else None,
                            ifd_positions[i],
                            nxt,
                        )
                    )
                return blobs

            # IFD blob lengths are offset-independent: measure, place, re-emit
            sizes = [len(b) for b in build([0] * len(self.levels))]
            positions, cur = [], self._pos
            for s in sizes:
                positions.append(cur)
                cur += s
            if not self.big and cur > 2**32 - 1:
                raise ValueError(
                    f"{self.path}: streamed file ends at {cur} bytes, past "
                    "classic TIFF addressing — the bigtiff='auto' bound "
                    "should have chosen BigTIFF; force bigtiff=True"
                )
            for blob in build(positions):
                self._f.write(blob)
            self._f.seek(8 if self.big else 4)
            ptr = struct.pack("<Q" if self.big else "<I", positions[0])
            self._f.write(ptr)
        finally:
            self._closed = True
            self._f.close()

    def abort(self) -> None:
        """Release the file handle WITHOUT completeness checks or IFD
        emission — for error paths that must not mask an in-flight
        exception (the half-written file is left for the caller to
        unlink)."""
        if not self._closed:
            self._closed = True
            self._f.close()

    def __enter__(self) -> "GeoTiffStreamWriter":
        return self

    def __exit__(self, exc_type, *_) -> None:
        if exc_type is None:
            self.close()
        else:
            self.abort()


def _encode_block(
    block: np.ndarray, comp_id: int, use_pred: bool, level: int = 6
) -> bytes:
    if use_pred:
        block = _predict(block)
    raw = block.tobytes()
    if comp_id == _COMP_NONE:
        return raw
    if comp_id == _COMP_LZW:
        return _lzw_encode(raw)
    return zlib.compress(raw, level)


#: blocks per native-encode batch: bounds transient memory to CHUNK blocks
#: (e.g. 16 × 256²×spp samples) while amortising the ctypes call + thread
#: spawn over enough independent work to keep the pool busy.
_ENCODE_CHUNK = 16


def _encode_all(
    block_iter, comp_id: int, use_pred: bool, level: int = 6
) -> list[bytes]:
    """Encode a stream of blocks, in chunks through the native library when
    possible, else per-block NumPy.

    Blocks are consumed lazily — peak transient memory is one chunk, not
    the whole raster.  Equal-shape runs batch together (always true for the
    tiled layout; the strip layout's short last strip flushes a chunk).
    Both paths produce byte-identical output: same zlib level, same
    predictor arithmetic, same LZW code stream — the native path is
    acceleration only.
    """
    if not (native.available() and comp_id in (_COMP_DEFLATE_ADOBE, _COMP_LZW)):
        return [_encode_block(b, comp_id, use_pred, level) for b in block_iter]

    out: list[bytes] = []
    chunk: list[np.ndarray] = []

    def flush() -> None:
        if not chunk:
            return
        if use_pred and chunk[0].dtype.itemsize == 8:
            out.extend(_encode_block(b, comp_id, use_pred, level) for b in chunk)
        else:
            try:
                out.extend(
                    native.encode_blocks(
                        np.stack(chunk),  # fresh stack → safe to mutate
                        predictor=2 if use_pred else 1,
                        compression=comp_id,
                        level=level,
                        in_place=True,
                    )
                )
            except native.NativeCodecError:
                out.extend(
                    _encode_block(b, comp_id, use_pred, level) for b in chunk
                )
        chunk.clear()

    for b in block_iter:
        if chunk and b.shape != chunk[0].shape:
            flush()
        chunk.append(b)
        if len(chunk) >= _ENCODE_CHUNK:
            flush()
    flush()
    return out
