"""Completed-tile manifest: the framework's checkpoint/resume mechanism.

The reference gets fault tolerance for free from Hadoop — failed map tasks
are retried by the framework, and a restarted job recomputes everything
(SURVEY.md §5 "Failure detection" / "Checkpoint/resume").  The TPU-native
equivalent is deliberately simple because tiles are independent work units:
each finished tile is persisted as one ``.npz`` plus an append-only JSONL
manifest record; resume = skip every tile already in the manifest whose
artifact exists and matches the run fingerprint.  A crashed run therefore
loses at most the tile in flight.

The fingerprint ties a manifest to (stack shape, year span, parameters,
index selection, tile size) so stale workdirs from a different run are
rejected instead of silently mixed in.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
import uuid
import zipfile
from typing import Iterator

import numpy as np

from land_trendr_tpu.io import native
from land_trendr_tpu.runtime import faults

__all__ = ["TileManifest", "run_fingerprint"]

#: valid tile-artifact compression choices (see :meth:`TileManifest.record`)
ARTIFACT_COMPRESS = ("none", "deflate")


def _write_npz(path: str, arrays: dict[str, np.ndarray], compress: str) -> None:
    """Write an ``.npz`` with an explicit speed/size trade.

    ``np.savez_compressed`` hardwires zlib level 6, which measured at
    ~18 MB/s on this class of payload — 2.8 s per 512² tile, the single
    largest host stage of a scene run (SCENE_r03.json ``write_s``) and far
    below what a TPU-rate pipeline can tolerate.  ``"none"`` stores the
    members raw (~340 MB/s, np.load reads either transparently);
    ``"deflate"`` uses zlib level 1 (~2.3× faster than level 6, within a
    few % of its size on real segmentation outputs) for runs where the
    workdir lives on constrained storage.

    The ``"none"`` path routes through the native store-zip writer when
    the library is built: threaded CRC32 + one sequential buffered C
    write that never touches the GIL mid-payload, so several
    ``RunConfig.write_workers`` threads can be inside their artifacts
    simultaneously on multi-core hosts (Python's zipfile re-acquires the
    GIL between every chunked write/CRC call).  Single-core throughput is
    ~parity with ``np.savez`` — the point is pool scaling, not one
    thread.  Falls back to ``np.savez`` (identical readers) when the
    library is absent or the artifact would need zip64.
    """
    if compress == "none":
        if native.available():
            try:
                native.write_store_zip(path, arrays)
                return
            except native.NativeCodecError:
                pass  # zip64-scale artifact or transient failure
        np.savez(path, **arrays)
        return
    with zipfile.ZipFile(
        path, "w", zipfile.ZIP_DEFLATED, compresslevel=1
    ) as z:
        for name, arr in arrays.items():
            # stream straight into the zip member — no full serialized copy
            with z.open(f"{name}.npy", "w", force_zip64=True) as member:
                np.lib.format.write_array(
                    member, np.asanyarray(arr), allow_pickle=False
                )


def run_fingerprint(payload: dict) -> str:
    """Stable short hash of the run-defining configuration."""
    blob = json.dumps(payload, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


@dataclasses.dataclass
class TileManifest:
    """Append-only JSONL manifest of completed tiles in a work directory.

    ``context`` carries execution facts that must not be MIXED across a
    resume (e.g. ``{"mesh_devices": 8}`` — partitioning legally flips rare
    f32 knife-edge decisions) but that post-hoc consumers like raster
    assembly don't know and don't need: when ``context`` is None the
    header's context is not checked.

    ``telemetry`` (optional — anything with a ``write_done(tile_id,
    nbytes, record_s, meta)`` hook, in practice
    :class:`land_trendr_tpu.obs.Telemetry`) is notified once per
    :meth:`record`, AFTER the artifact and manifest line are durable: the
    ``write_done`` event stream is therefore a faithful durability log —
    an event present means the tile survives a crash.
    """

    workdir: str
    fingerprint: str
    context: dict | None = None
    telemetry: "object | None" = None
    #: torn/malformed manifest lines skipped by the last tolerant scan
    #: (:meth:`open` resume pass / :meth:`iter_records`).  A reader
    #: racing a concurrent append — the elastic lease queue, a pod
    #: sibling's done record, an ENOSPC half-line — sees at most a torn
    #: tail; skip-and-count (the blockstore GC's posture) instead of
    #: dying in ``json.loads``: a lost done record at worst recomputes
    #: an idempotent tile, while a crashed scan loses the whole run.
    skipped_lines: int = dataclasses.field(default=0, init=False)
    #: pod-wide run correlation ID, agreed through the shared manifest
    #: header: exactly ONE process of a pod run writes the header
    #: (exclusive create) and stamps a fresh id; every other process —
    #: and every resume — reads the SAME id back at :meth:`open`.  The
    #: driver passes it to ``run_start`` so all N per-host event streams
    #: of one pod run carry one ``run_id`` (the span model's correlation
    #: contract).  A resume shares its predecessor's id by design: it is
    #: the same logical run over the same workdir, and pod-trace assembly
    #: folds each stream's LAST scope anyway.  ``None`` until ``open()``
    #: (or when resuming a pre-run_id manifest — callers fall back to a
    #: per-process id).
    run_id: "str | None" = None

    @property
    def path(self) -> str:
        return os.path.join(self.workdir, "manifest.jsonl")

    def tile_path(self, tile_id: int) -> str:
        return os.path.join(self.workdir, f"tile_{tile_id:05d}.npz")

    def open(self, resume: bool) -> set[int]:
        """Prepare the workdir; return tile ids that can be skipped.

        With ``resume=False`` any existing manifest is discarded.  With
        ``resume=True`` the existing manifest must carry the same
        fingerprint (else ValueError — the workdir belongs to a different
        run) and only records whose ``.npz`` artifact is readable count as
        done.
        """
        os.makedirs(self.workdir, exist_ok=True)
        # sweep temp artifacts orphaned by a crash mid-write — but only
        # STALE ones: in a shared pod workdir a peer process may be inside
        # record() right now, and deleting its live tmp would abort its
        # os.replace.  10 minutes is far beyond any tile write.
        now = time.time()
        for n in os.listdir(self.workdir):
            if n.endswith(".tmp.npz"):
                p = os.path.join(self.workdir, n)
                try:
                    if now - os.path.getmtime(p) > 600:
                        os.remove(p)
                except OSError:
                    pass  # a peer finished (replaced) or swept it first
        if not os.path.exists(self.path):
            # multiple processes of one pod run share a workdir; exclusive
            # create means exactly one writes the header and the rest fall
            # through to validate it like any resume
            try:
                self._write_header(exclusive=True)
                return set()
            except FileExistsError:
                pass
        if not resume:
            # inherently single-process (or externally coordinated): two
            # processes discarding concurrently would race the rewrite
            os.remove(self.path)
            self._write_header()
            return set()

        done: set[int] = set()
        header_seen = False
        any_record = False
        deadline: "float | None" = None
        while True:
            done.clear()
            header_seen = False
            any_record = False
            for rec in self._iter_tolerant():
                any_record = True
                self._fold_open_record(rec, done)
                if rec.get("kind") == "header":
                    header_seen = True
            if header_seen or any_record:
                break
            # the shared-workdir creation window: a pod sibling holds the
            # exclusive create and is inside its buffered header write —
            # an EMPTY manifest (or one whose only line is the header
            # still mid-flush, visible as a torn fragment) is a peer
            # mid-write, not a damaged workdir.  Wait it out boundedly
            # before judging.  Parseable records without a header never
            # retry: appends only happen after an open() that saw the
            # header, so that state is real damage.
            if deadline is None:
                deadline = time.time() + 2.0
            elif time.time() > deadline:
                break
            time.sleep(0.02)
        if not header_seen:
            # the fingerprint guard must not be skippable by corruption:
            # a manifest whose header line cannot be read is a foreign /
            # damaged workdir, not an empty done set
            raise ValueError(
                f"manifest {self.path} has no readable header "
                f"({self.skipped_lines} torn/malformed line(s) skipped); "
                "pass resume=False to discard the workdir"
            )
        return done

    def _fold_open_record(self, rec: dict, done: "set[int]") -> None:
        """One record of the :meth:`open` resume scan: validate a header,
        count an artifact-verified tile as done, ignore the rest."""
        if rec.get("kind") == "header":
            if rec.get("fingerprint") != self.fingerprint:
                raise ValueError(
                    f"workdir {self.workdir} belongs to a different "
                    f"run (manifest fingerprint {rec.get('fingerprint')} "
                    f"!= {self.fingerprint}); pass resume=False to "
                    "discard it"
                )
            # the pod-wide correlation id the header's writer
            # stamped (None on pre-run_id manifests — the driver
            # falls back to a per-process id)
            self.run_id = rec.get("run_id")
            # headers written before context existed were all
            # single-device runs — treat a missing key as that
            stored = rec.get("context", {"mesh_devices": 1})
            if self.context is not None and stored != self.context:
                raise ValueError(
                    f"workdir {self.workdir} was produced under a "
                    f"different execution context "
                    f"({stored} != {self.context}); "
                    "pass resume=False to discard it"
                )
            return
        if rec.get("kind") != "tile":
            return
        try:
            tid = int(rec["tile_id"])
        except (KeyError, TypeError, ValueError):
            self.skipped_lines += 1  # parsed JSON, broken record
            return
        if self._artifact_readable(tid):
            done.add(tid)

    def _artifact_readable(self, tile_id: int) -> bool:
        """True when the tile's ``.npz`` exists and its zip directory
        parses with at least one member.

        The crash-safety leg of resume: ``record`` is atomic (tmp +
        rename), but an OS crash can still leave a renamed artifact with
        torn data blocks — and a truncated zip loses its END-of-file
        central directory, exactly what this opens.  An unreadable
        artifact counts as not-done (the tile recomputes) instead of
        crashing the resumed run at assembly, hours later.
        """
        try:
            with np.load(self.tile_path(tile_id)) as z:
                return len(z.files) > 0
        except Exception:
            return False

    def _write_header(self, exclusive: bool = False) -> None:
        self.run_id = uuid.uuid4().hex[:12]
        hdr = {
            "kind": "header",
            "fingerprint": self.fingerprint,
            "run_id": self.run_id,
        }
        if self.context is not None:
            hdr["context"] = self.context
        with open(self.path, "x" if exclusive else "w") as f:
            f.write(json.dumps(hdr) + "\n")

    def record(
        self,
        tile_id: int,
        arrays: dict[str, np.ndarray],
        meta: dict,
        compress: str = "none",
    ) -> None:
        """Persist one finished tile: artifact first, then the manifest line
        (so a crash between the two leaves a recoverable, not corrupt, state).

        ``compress`` is one of :data:`ARTIFACT_COMPRESS`; it is a pure
        speed/size trade — ``np.load`` reads either form, so a resumed run
        may freely mix compressions (the fingerprint does not include it).
        """
        if compress not in ARTIFACT_COMPRESS:
            raise ValueError(
                f"compress={compress!r} not one of {ARTIFACT_COMPRESS}"
            )
        # fault seam "manifest.record": the persist path's ENOSPC / I/O
        # errors surface here, BEFORE the artifact — the atomic-write
        # contract means a failed record leaves no partial final artifact
        faults.check("manifest.record")
        t0 = time.perf_counter()
        # note: np.savez appends ".npz" unless the name already ends with it;
        # the pid keeps concurrent pod processes' tmp files distinct
        tmp = f"{self.tile_path(tile_id)}.{os.getpid()}.tmp.npz"
        _write_npz(tmp, arrays, compress)
        os.replace(tmp, self.tile_path(tile_id))
        with open(self.path, "a") as f:
            f.write(json.dumps({"kind": "tile", "tile_id": tile_id, **meta}) + "\n")
        if faults.fired("manifest.torn"):
            # behavioral seam: simulate an OS crash after the manifest
            # line landed but before the artifact's data blocks were
            # durable — the one torn state tmp+rename cannot prevent.
            # open(resume=True)'s readability check must then treat the
            # recorded tile as not-done.
            with open(self.tile_path(tile_id), "r+b") as tf:
                tf.truncate(max(1, os.path.getsize(self.tile_path(tile_id)) // 2))
            raise OSError(
                f"injected torn artifact write for tile {tile_id}"
            )
        if self.telemetry is not None:
            self.telemetry.write_done(
                tile_id,
                os.path.getsize(self.tile_path(tile_id)),
                time.perf_counter() - t0,
                meta,
            )

    def record_clock_anchor(
        self,
        run_id: str,
        host: str,
        process_index: int,
        anchor_wall: float,
        anchor_mono: float,
    ) -> None:
        """Append this process's run-scope clock anchor to the shared
        manifest (``kind="clock_anchor"``) — the manifest-side copy of
        the ``run_start`` anchor pair, so pod-trace assembly can align a
        host whose ``events.p<i>.jsonl`` was lost/truncated (the
        manifest lives on the shared filesystem and survives the host).
        Append-only like every record; :meth:`open` ignores the kind, so
        resumes and assembly are unaffected."""
        with open(self.path, "a") as f:
            f.write(
                json.dumps(
                    {
                        "kind": "clock_anchor",
                        "run_id": run_id,
                        "host": host,
                        "process_index": int(process_index),
                        "pid": os.getpid(),
                        "anchor_wall": anchor_wall,
                        "anchor_mono": anchor_mono,
                    }
                )
                + "\n"
            )

    def record_failed(self, tile_id: int, attempts: int, error: str) -> None:
        """Append a quarantine record for a tile that exhausted its retry
        budget (``--quarantine-tiles``): the run continues without it, and
        the record is post-mortem evidence — :meth:`open` only counts
        ``kind == "tile"`` records as done, so a resumed run re-attempts
        every quarantined tile automatically."""
        with open(self.path, "a") as f:
            f.write(
                json.dumps(
                    {
                        "kind": "tile_failed",
                        "tile_id": tile_id,
                        "attempts": attempts,
                        "error": str(error)[:500],
                        "t_wall": time.time(),
                    }
                )
                + "\n"
            )

    def load_tile(self, tile_id: int) -> dict[str, np.ndarray]:
        with np.load(self.tile_path(tile_id)) as z:
            return {k: z[k] for k in z.files}

    def _iter_tolerant(self) -> Iterator[dict]:
        """Parsed manifest records, torn/malformed lines skipped.

        Resets then counts into :attr:`skipped_lines`.  In a shared pod
        workdir a reader legitimately races concurrent appenders (lease
        claims, sibling done records): the in-flight append shows up as
        a torn trailing line, and an ENOSPC half-line buried by later
        appends shows up as one unparseable mid-file line.  Both are
        skipped and counted, like the blockstore GC's tolerant scan —
        never a crashed reader.
        """
        self.skipped_lines = 0
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    self.skipped_lines += 1
                    continue
                if isinstance(rec, dict):
                    yield rec
                else:
                    self.skipped_lines += 1

    def iter_records(self) -> Iterator[dict]:
        """Every readable manifest record; a torn tail (a concurrent
        appender mid-write) or malformed line is skipped and counted in
        :attr:`skipped_lines` instead of raising."""
        return self._iter_tolerant()
