"""Packed async host→device upload: one transfer per tile, overlapped.

`SCENE_TPU_r05.json` measured the surviving half of the host path: with
the device→host side packed (PR 3, ``runtime/fetch.py``), feed (43.6 s)
+ dispatch (53.1 s) together now exceed device compute (87.7 s), and the
dispatch stage was a synchronous per-array ``jax.device_put`` loop — one
latency-bound transfer per band plus QA per tile.  This module is the
upload mirror of the fetch subsystem, closing the pattern the
massively-parallel break-detection literature names (Gieseke et al.,
arXiv:1807.01751: continent-scale time-series runs dominated by data
movement, not fitting).

Three pieces, each the inverse of its fetch twin:

* **Host-side pack** (:func:`pack_inputs`): every fed array — the
  selected DN bands and QA, all ``(feed_px, NY)`` and 2-byte on real C2
  stacks — is memcpy'd into ONE contiguous little-endian ``uint32`` word
  buffer (each entry word-aligned), so a tile costs one
  ``jax.device_put`` instead of ``len(bands)+1`` latency-bound ones.
* **Async overlap**: ``device_put`` of the packed buffer is issued as
  soon as the tile's feed completes; the driver keeps up to
  ``RunConfig.upload_depth`` packed tiles in flight, so tile ``i+1``'s
  upload crosses the link while tile ``i`` computes.
  :meth:`PackedUpload.arrays` blocks only on transfers that have not
  landed (the ``upload.wait`` fault seam + the run's ``upload`` wait_s
  counter live there).
* **Device-side unpack** (:func:`unpack_inputs`): one tiny jitted
  program bitcasts the landed words back into the per-band device
  arrays the tile program consumes — compiled once per run (every tile
  shares the padded pixel count).

The contract mirrors the fetch plan's: packed and per-array runs produce
**byte-identical artifacts** (``tests/test_upload.py`` pins the matrix),
because the packed wire format is a pure reinterpretation of the same
fed bytes.  ``upload_packed="auto"`` resolves to packed only where a
transfer is a real wire: on a CPU backend ``device_put`` is (near)
zero-copy and packing is pure overhead, and a sharded mesh places
per-array ``NamedSharding`` inputs, so both keep the per-array path.

Upload errors surfacing through the async wait re-enter the driver's
shared ``_retry_ladder`` (the retained host inputs ride the pending
queue for exactly that), and repeated consecutive failures demote the
run to the per-array sync path — mirroring ``TileFetcher.demote``.

This module is, with ``runtime/fetch.py``, a blessed LT002 host-sync
module: the one ``block_until_ready`` here IS the upload path's
sanctioned wait point.
"""

from __future__ import annotations

import functools
import sys
import threading
import time
import warnings
from typing import TYPE_CHECKING, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from land_trendr_tpu.runtime import faults

if TYPE_CHECKING:  # pragma: no cover - type-only import (cycle with driver)
    from land_trendr_tpu.runtime.driver import RunConfig

# unpack_inputs donates its word buffer (see its docstring); on backends
# where donation is unusable (CPU shares host memory) JAX warns once per
# compile.  Expected and not actionable wherever this module is used, so
# the one message-targeted filter installs at import — NOT per call: the
# filter list is process-global and arrays() runs once per tile on the
# driver's hot path.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

__all__ = [
    "UploadPlan",
    "UploadEntry",
    "TileUploader",
    "build_plan",
    "pack_inputs",
    "plan_wire_bytes",
    "resolve_packed",
    "unpack_inputs",
]


class UploadEntry(NamedTuple):
    """One fed array's place in the packed wire format.

    ``name`` is the band name (``"qa"`` for the QA plane); ``dtype`` the
    host/device dtype whose raw bytes cross the link (uploads are
    lossless reinterpretation — there is no f16 narrowing on the input
    side, DNs are already 2-byte integers).
    """

    name: str
    dtype: str


class UploadPlan(NamedTuple):
    """Hashable (jit-static) description of one run's tile upload."""

    entries: tuple[UploadEntry, ...]
    px: int  # PADDED feed pixel count every tile shares
    ny: int


def build_plan(dn: dict, qa: np.ndarray) -> UploadPlan:
    """The run's upload plan, from the first fed tile's (shared) arrays.

    Entry order is the feed dict's deterministic band order with QA
    last — the device unpack re-emits the same structure, so both paths
    hand ``process_tile_dn`` identical inputs.
    """
    entries = [UploadEntry(k, np.dtype(v.dtype).name) for k, v in dn.items()]
    entries.append(UploadEntry("qa", np.dtype(qa.dtype).name))
    px, ny = (int(s) for s in qa.shape)
    return UploadPlan(entries=tuple(entries), px=px, ny=ny)


@functools.lru_cache(maxsize=None)
def _layout(plan: UploadPlan) -> tuple[tuple[tuple[int, int], ...], int]:
    """Per-entry ``(word_offset, word_count)`` and the total wire words.

    Every entry starts on a word boundary (odd 2-byte tails are
    zero-padded to the next word), so the device unpack is a static
    slice + bitcast at a known offset.
    """
    offs: list[tuple[int, int]] = []
    off_w = 0
    for e in plan.entries:
        nbytes = plan.px * plan.ny * np.dtype(e.dtype).itemsize
        nw = (nbytes + 3) // 4
        offs.append((off_w, nw))
        off_w += nw
    return tuple(offs), off_w


def plan_wire_bytes(plan: UploadPlan) -> int:
    """Bytes one packed tile transfer moves (word padding included)."""
    return _layout(plan)[1] * 4


def pack_inputs(dn: dict, qa: np.ndarray, plan: UploadPlan) -> np.ndarray:
    """Host-side pack: every planned array → one ``uint32`` buffer.

    Pure memcpy (one per entry) into a preallocated word buffer — no
    dtype conversion, no predictor, nothing lossy: the packed words are
    the fed arrays' raw little-endian bytes, so the device unpack is a
    bit-exact inverse.
    """
    offs, total_w = _layout(plan)
    buf = np.zeros(total_w, dtype=np.uint32)  # zero word padding
    u8 = buf.view(np.uint8)
    for e, (off_w, _nw) in zip(plan.entries, offs):
        a = qa if e.name == "qa" else dn[e.name]
        raw = np.ascontiguousarray(a).view(np.uint8).reshape(-1)
        u8[off_w * 4 : off_w * 4 + raw.size] = raw
    return buf


def _from_words(words: jnp.ndarray, dtype: str, n: int) -> jnp.ndarray:
    """Reinterpret a word slice as ``n`` elements of ``dtype`` — the
    inverse of the host pack's byte copy (little-endian both sides)."""
    it = np.dtype(dtype).itemsize
    if it == 4:
        return jax.lax.bitcast_convert_type(words, dtype)[:n]
    if it == 8:
        pairs = words.reshape(-1, 2)
        return jax.lax.bitcast_convert_type(pairs, dtype)[:n]
    # sub-word dtypes gain a trailing (4 // itemsize) group dim
    return jax.lax.bitcast_convert_type(words, dtype).reshape(-1)[:n]


@functools.partial(jax.jit, static_argnames=("plan",), donate_argnames=("words",))
def unpack_inputs(
    words: jnp.ndarray, plan: UploadPlan
) -> tuple[dict[str, jnp.ndarray], jnp.ndarray]:
    """One device program: the landed words → per-band arrays + QA.

    Compiles once per run — every tile, edge tiles included, shares the
    padded feed pixel count.  XLA fuses the bitcasts/slices, so the
    unpack is effectively free next to the transfer it replaces.

    The packed word buffer is **donated** (SNIPPETS.md [2]'s
    ``donate_argnames`` dispatch-path pattern): it is dead the moment
    the unpack reads it — each tile packs a fresh buffer, and the retry
    ladder re-dispatches from the retained HOST inputs, never from the
    device words — so XLA may alias its HBM into the outputs instead of
    holding packed + unpacked copies live per in-flight tile.  The
    outputs are a bit-exact reinterpretation either way (the
    ``tests/test_upload.py`` parity matrix pins it), and on backends
    where donation is unusable (CPU shares host memory) XLA just keeps
    the copy — behavior, not bytes, is what the hint changes.
    """
    offs, _total = _layout(plan)
    n = plan.px * plan.ny
    dn: dict[str, jnp.ndarray] = {}
    qa = None
    for e, (off_w, nw) in zip(plan.entries, offs):
        a = _from_words(words[off_w : off_w + nw], e.dtype, n)
        a = a.reshape(plan.px, plan.ny)
        if e.name == "qa":
            qa = a
        else:
            dn[e.name] = a
    assert qa is not None  # build_plan always appends the QA entry
    return dn, qa


def resolve_packed(upload_packed: "bool | str") -> bool:
    """Resolve ``RunConfig.upload_packed`` ("auto"/True/False) to a bool.

    "auto" packs only where a transfer is a real wire: on the CPU
    backend ``device_put`` shares host memory, so the pack would be a
    pure extra memcpy.  The wire format is little-endian (the device
    side of every supported backend); a big-endian HOST cannot produce
    it, so auto falls back and an explicit ``True`` raises.  Mesh runs
    are resolved by the driver (per-array ``NamedSharding`` placement
    cannot consume one packed buffer).
    """
    if upload_packed == "auto":
        return jax.default_backend() != "cpu" and sys.byteorder == "little"
    if upload_packed and sys.byteorder != "little":
        raise ValueError(
            "upload_packed=True needs a little-endian host (the packed "
            "wire format is the device's LE byte order); use "
            "upload_packed=False"
        )
    return bool(upload_packed)


class _Stats:
    """Thread-safe upload counters (mirrors ``fetch._Stats``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.tiles = 0
        self.transfers = 0
        self.bytes = 0
        self.pack_s = 0.0
        self.wait_s = 0.0
        self.unpack_s = 0.0
        self.backlog_max = 0

    def add(self, **deltas) -> None:
        with self._lock:
            for k, v in deltas.items():
                setattr(self, k, getattr(self, k) + v)

    def note_backlog(self, depth: int) -> None:
        with self._lock:
            if depth > self.backlog_max:
                self.backlog_max = depth


class PackedUpload:
    """One tile's in-flight packed host→device transfer.

    ``arrays`` is called on the driver loop right before dispatch: it
    waits out the remainder of the transfer (short by then — the buffer
    has been crossing the link while earlier tiles computed), then runs
    the jitted unpack.  A device error surfacing through the wait
    propagates to the caller, where the retry ladder re-dispatches from
    the retained host inputs on the per-array path.
    """

    packed = True

    def __init__(self, uploader: "TileUploader", words) -> None:
        self._uploader = uploader
        self._words = words

    def arrays(self) -> tuple[dict, "jnp.ndarray"]:
        faults.check("upload.wait")
        stats = self._uploader.stats
        t0 = time.perf_counter()
        # the upload path's ONE sanctioned host-blocks-on-device point:
        # landing is awaited here so link errors surface at a named seam
        # (and wait_s measures true un-overlapped upload time)
        jax.block_until_ready(self._words)
        t1 = time.perf_counter()
        dn, qa = unpack_inputs(self._words, plan=self._uploader.plan)
        # the donated buffer is consumed: drop the handle so no later
        # path can touch a deleted array
        self._words = None
        stats.add(
            wait_s=t1 - t0, unpack_s=time.perf_counter() - t1, tiles=1
        )
        return dn, qa


class SyncUpload:
    """The per-array fallback: the pre-packing path, byte for byte.

    No transfer is issued here — the host arrays flow into the dispatch
    exactly as before this subsystem existed (implicit per-array
    ``device_put`` at the jit call, or the mesh's explicit
    ``NamedSharding`` placement loop).  Transfers/bytes are counted at
    construction: that per-array wire traffic is what the dispatch
    pays.
    """

    packed = False

    def __init__(self, uploader: "TileUploader", dn: dict, qa) -> None:
        self._dn = dn
        self._qa = qa
        uploader.stats.add(
            transfers=len(dn) + 1,
            bytes=sum(a.nbytes for a in dn.values()) + qa.nbytes,
        )
        self._uploader = uploader

    def arrays(self) -> tuple[dict, np.ndarray]:
        self._uploader.stats.add(tiles=1)
        return self._dn, self._qa


class TileUploader:
    """Per-run upload strategy: plan once, then one handle per tile."""

    def __init__(self, cfg: "RunConfig", packed: bool) -> None:
        self.cfg = cfg
        self.packed = packed
        self.demoted = False
        self.plan: UploadPlan | None = None
        self.stats = _Stats()

    def demote(self) -> None:
        """Graceful degradation: drop to the per-array sync path for the
        REST of the run (the driver calls this after repeated upload
        failures — a sick link must not keep eating every subsequent
        tile's retry budget).  Artifacts are byte-identical either way
        (the wire format is a pure reinterpretation), so demotion is
        safe mid-run; in-flight packed handles still resolve normally.
        """
        self.packed = False
        self.demoted = True

    def start(self, dn: dict, qa: np.ndarray) -> "PackedUpload | SyncUpload":
        """Issue one tile's upload; packed transfers begin crossing NOW."""
        if self.plan is None:
            self.plan = build_plan(dn, qa)
        if not self.packed:
            return SyncUpload(self, dn, qa)
        t0 = time.perf_counter()
        words = jax.device_put(pack_inputs(dn, qa, plan=self.plan))
        self.stats.add(
            pack_s=time.perf_counter() - t0,
            transfers=1,
            bytes=plan_wire_bytes(self.plan),
        )
        return PackedUpload(self, words)

    def note_backlog(self, depth: int) -> None:
        self.stats.note_backlog(depth)

    def summary(self) -> dict:
        """Run-scoped counters for the run summary / ``upload`` event."""
        s = self.stats
        with s._lock:
            return {
                "packed": self.packed,
                "demoted": self.demoted,
                "tiles": s.tiles,
                "transfers": s.transfers,
                "bytes": s.bytes,
                "pack_s": round(s.pack_s, 6),
                "wait_s": round(s.wait_s, 6),
                "unpack_s": round(s.unpack_s, 6),
                "backlog_max": s.backlog_max,
            }
