"""Deterministic fault injection for the tile pipeline.

The runtime grew real failure surfaces — a tile retry ladder, manifest
resume, an async-fetch backlog that re-enters the ladder, a decoded-block
cache, a multihost merge — but a race- or device-fault recovery path was
only covered when the hardware happened to fail.  This module makes the
failure semantics as pinned as the numerics: every brittle seam carries a
**named injection point**, and a seeded :class:`FaultPlan` decides —
reproducibly, from ``(seed, seam, invocation index)`` alone — which
invocation of which seam raises which error class.  Tiles are independent
work units (Kennedy et al. 2010 per-pixel/per-tile semantics), so one bad
tile must never cost the other 10k; the plans below are how every "must
never" becomes a unit test (``tests/test_faults.py``) and a soak gate
(``tools/fault_soak.py``).

Seams (the public contract — hosts call :func:`check` / :func:`fired` /
:func:`corrupt` with these names):

=================== =======================================================
``feed``            driver feed job (any stack; ``runtime/driver.py``)
``feed.decode``     windowed GeoTIFF block decode (``io/geotiff.py``)
``cache.corrupt``   decoded-block cache consumption — corruption, not an
                    exception (``io/geotiff.py`` via the blockcache hook)
``store.corrupt``   persistent block-store consumption — corruption of a
                    store-served block (``io/blockcache.py`` store tier)
``upload.wait``     packed host→device upload landing
                    (``runtime/feed.PackedUpload.arrays``)
``dispatch``        device dispatch of one tile's program (driver)
``compute.wait``    the sanctioned compute-waits (driver)
``fetch.wait``      device→host fetch landing (``runtime/fetch._to_host``)
``manifest.record`` tile artifact + manifest-line persist (entry)
``manifest.torn``   post-rename artifact truncation (behavioral: the
                    manifest truncates its own artifact, then raises)
``lease.acquire``   elastic lease-batch claim (``runtime/leases.py``):
                    the whole acquisition fails; the host backs off and
                    retries next cycle
``lease.steal``     an expired-lease steal claim: the steal write fails
                    (the tile stays stealable; a sibling or the next
                    cycle takes it)
``lease.expire``    behavioral: a live foreign lease reads as EXPIRED to
                    the probing host — forces the steal-while-the-owner-
                    still-runs double-execution race deterministically
                    (first durable write wins, artifacts byte-identical)
``merge.peer``      multihost event merge — a probed peer reads as
                    not-terminal (slow/dead peer; behavioral)
``serve.submit``    serve-mode job admission (``serve/server.py``): the
                    submission fails and is rejected; the server lives
``serve.job``       serve-mode job execution start: the job fails
                    terminally; sibling jobs and the server live
``debug.profile``   on-demand profiler capture (``POST /debug/profile``):
                    the capture fails (``profile_captured`` carries
                    ``ok=false``); the job and the server live
``obs.publish``     fleet snapshot publish (``obs/publish.py``): the
                    beat is skipped, the host ages toward stale; the
                    run lives
``history.append``  fleet history-ring append (``obs/history.py``):
                    one sample is lost; the ring stays consistent
``router.forward``  fleet-router job forward (``fleet/router.py``): the
                    POST to the chosen replica fails; the job re-enters
                    the router queue and routes again (bounded by
                    ``route_retries``) — never a lost job
``replica.health``  fleet-router health probe (behavioral): a live
                    replica's probe reads as FAILED — enough
                    consecutive fires mark the replica unready without
                    failing any accepted job
``tune.probe``      autotuner calibration probe (``tune/autotune.py``):
                    the knob group's probe fails and is SKIPPED — its
                    knobs fall back to defaults (``tune_probe`` event
                    ``ok=false``); the tuner and the run behind it live
``batch.pack``      cross-job batch membership claim (``serve/batching``):
                    the candidate job is EXCLUDED from the batch and runs
                    solo later; the batch and its other members live
``batch.demux``     batched-result demux to one member's manifest
                    (``serve/batching``): that member stops receiving
                    demuxed tiles and recomputes them in its own run
                    (byte-identical); batch-mates are untouched
``router.journal``  admission-journal append (``fleet/journal.py``): the
                    record cannot be made durable, so THAT admission
                    fails loudly (503 ``journal_error``) instead of
                    accepting a job a crash would orphan; a resubmit
                    after the fault clears completes normally
``router.recover``  post-restart reconciliation probe (``fleet/router``):
                    the replica answer is unavailable, so the replayed
                    job is requeued front with ``resume=true`` — the
                    pinned workdir resumes byte-identically under the
                    preserved trace id; never a lost or doubled job
=================== =======================================================

Schedules are strings (CLI ``--fault-schedule``) or :class:`FaultSpec`
lists (tests)::

    seed=7,dispatch@1               # 2nd dispatch invocation raises
    seed=7,fetch.wait@0*3=io        # invocations 0,1,2 raise OSError
    seed=3,feed.decode%0.25         # each invocation fires with p=0.25
    seed=1,compute.wait@1=hang:30   # sliced 30s hang (watchdog food)

Error kinds: ``runtime`` (RuntimeError — the device-fault shape), ``io``
(OSError), ``enospc`` (OSError errno.ENOSPC), ``value`` (ValueError — the
corrupt-stream shape), ``hang:SECS`` (interruptible sliced sleep, for the
stall watchdog), ``slow:SECS`` (sleep then proceed — stragglers/crash
windows), ``corrupt`` (only meaningful at ``cache.corrupt``) and ``fire``
(behavioral seams).  Probability draws hash ``(seed, seam, index)``
through :func:`zlib.crc32` — no interpreter hash salt, no shared RNG
stream — so a schedule reproduces across processes and thread schedules.
Invocation INDICES are deterministic when each seam's consumers run in a
deterministic order (the shipped soak/tests use single feed/writer
workers); readahead prefetch tasks never consume io-seam indices (see
``blockcache.fault_check``), so demand reads keep their ordering even
with a busy prefetch pool.

Everything here is stdlib-only and import-light: io-layer hosts reach the
active plan through :func:`land_trendr_tpu.io.blockcache.fault_check`
(registered by :func:`activate`) so ``io/`` never imports ``runtime/``.
"""

from __future__ import annotations

import errno
import threading
import time
import zlib
from typing import Callable, NamedTuple

__all__ = [
    "SEAMS",
    "FaultSpec",
    "FaultPlan",
    "parse_schedule",
    "activate",
    "deactivate",
    "active",
    "check",
    "fired",
    "corrupt",
    "set_observer",
]

#: every seam a host module declares (misspelled schedule specs are
#: config errors, not silently-dead injections)
SEAMS = (
    "feed",
    "feed.decode",
    "cache.corrupt",
    "store.corrupt",
    "upload.wait",
    "dispatch",
    "compute.wait",
    "fetch.wait",
    "manifest.record",
    "manifest.torn",
    "lease.acquire",
    "lease.steal",
    "lease.expire",
    "merge.peer",
    "serve.submit",
    "serve.job",
    "debug.profile",
    "obs.publish",
    "history.append",
    "router.forward",
    "replica.health",
    "tune.probe",
    "loadgen.tick",
    "batch.pack",
    "batch.demux",
    "router.journal",
    "router.recover",
)

#: error kinds that RAISE at the seam (vs behavioral kinds)
_RAISING_KINDS = ("runtime", "io", "enospc", "value")

_DEFAULT_KIND = {
    "feed": "io",
    "feed.decode": "value",
    "cache.corrupt": "corrupt",
    "store.corrupt": "corrupt",
    "upload.wait": "runtime",
    "dispatch": "runtime",
    "compute.wait": "runtime",
    "fetch.wait": "runtime",
    "manifest.record": "io",
    "manifest.torn": "fire",
    "lease.acquire": "io",
    "lease.steal": "io",
    "lease.expire": "fire",
    "merge.peer": "fire",
    "serve.submit": "io",
    "serve.job": "runtime",
    "debug.profile": "runtime",
    "obs.publish": "io",
    "history.append": "io",
    "router.forward": "io",
    "replica.health": "fire",
    "tune.probe": "runtime",
    "loadgen.tick": "fire",
    "batch.pack": "io",
    "batch.demux": "io",
    "router.journal": "io",
    "router.recover": "io",
}


class FaultSpec(NamedTuple):
    """One scheduled fault: WHERE (seam), WHEN (``at``+``times`` exact
    invocations, or ``prob`` per invocation), WHAT (error kind + numeric
    ``arg`` for ``hang``/``slow`` seconds)."""

    seam: str
    at: "int | None" = None
    times: int = 1
    prob: "float | None" = None
    error: str = ""      # "" = the seam's default kind
    arg: "float | None" = None


class FaultInjected(RuntimeError):
    """Marker mixin-free base so consumers can tell injected faults in
    logs; raising seams still raise realistic classes (OSError etc.) —
    this type is only used for the generic ``runtime`` kind."""


def _make_error(kind: str, seam: str, index: int) -> BaseException:
    msg = f"injected fault at {seam}#{index}"
    if kind == "io":
        return OSError(msg)
    if kind == "enospc":
        return OSError(errno.ENOSPC, f"No space left on device ({msg})")
    if kind == "value":
        return ValueError(msg)
    return FaultInjected(msg)


def _hang(seconds: float) -> None:
    """Sliced sleep: a hung-device stand-in the stall watchdog's
    ``interrupt_main`` CAN preempt (a pending ``KeyboardInterrupt`` is
    delivered between slices, unlike one long C-level sleep)."""
    end = time.monotonic() + seconds
    while time.monotonic() < end:
        time.sleep(0.05)


class FaultPlan:
    """A seeded, deterministic schedule over the named seams.

    Thread-safe: seams fire from the driver loop, the feed/writer pools
    and the watchdog alike.  Each seam keeps its own invocation counter;
    firing decisions depend only on ``(seed, seam, index)`` and the
    specs, so a plan replays identically run over run.
    """

    def __init__(self, seed: int = 0, specs: "tuple[FaultSpec, ...]" = ()) -> None:
        for s in specs:
            if s.seam not in SEAMS:
                raise ValueError(
                    f"unknown fault seam {s.seam!r}; choose from {SEAMS}"
                )
            if (s.at is None) == (s.prob is None):
                raise ValueError(
                    f"spec for {s.seam!r} needs exactly one of @index or "
                    "%probability"
                )
            if s.at is not None and s.at < 0:
                raise ValueError(
                    f"spec for {s.seam!r}: @index {s.at} must be >= 0"
                )
            if s.times < 1:
                raise ValueError(
                    f"spec for {s.seam!r}: *times {s.times} must be >= 1"
                )
            if s.prob is not None and not (0.0 < s.prob <= 1.0):
                # "%25" meaning 25% would otherwise fire on EVERY
                # invocation — a config typo, not a schedule
                raise ValueError(
                    f"spec for {s.seam!r}: probability {s.prob} outside "
                    "(0, 1] — write 25% as %0.25"
                )
            if s.error and s.error not in (
                *_RAISING_KINDS, "hang", "slow", "corrupt", "fire"
            ):
                raise ValueError(f"unknown error kind {s.error!r}")
        self.seed = int(seed)
        self.specs = tuple(specs)
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._injected: list[tuple[str, int, str]] = []
        self._observer: "Callable[[str, int, str], None] | None" = None

    # -- scheduling --------------------------------------------------------
    def _draw(self, seam: str, index: int, prob: float) -> bool:
        h = zlib.crc32(f"{self.seed}:{seam}:{index}".encode())
        return (h / 2**32) < prob

    def _next(self, seam: str) -> "tuple[int, FaultSpec | None]":
        """Advance ``seam``'s counter; return (index, firing spec or None)."""
        with self._lock:
            index = self._counts.get(seam, 0)
            self._counts[seam] = index + 1
        for s in self.specs:
            if s.seam != seam:
                continue
            if s.at is not None and s.at <= index < s.at + s.times:
                return index, s
            if s.prob is not None and self._draw(seam, index, s.prob):
                return index, s
        return index, None

    def _note(self, seam: str, index: int, kind: str) -> None:
        with self._lock:
            self._injected.append((seam, index, kind))
        obs = self._observer
        if obs is not None:
            try:
                obs(seam, index, kind)
            except Exception:
                pass  # observation must never change injection behavior

    # -- seam APIs ---------------------------------------------------------
    def check(self, seam: str) -> None:
        """Raising seam: raise the scheduled error on a firing invocation
        (``slow`` sleeps then proceeds; ``hang`` sleeps interruptibly)."""
        index, spec = self._next(seam)
        if spec is None:
            return
        kind = spec.error or _DEFAULT_KIND[seam]
        self._note(seam, index, kind)
        if kind == "slow":
            time.sleep(spec.arg if spec.arg is not None else 0.5)
            return
        if kind == "hang":
            _hang(spec.arg if spec.arg is not None else 30.0)
            return
        raise _make_error(kind, seam, index)

    def fired(self, seam: str) -> bool:
        """Behavioral seam: True when this invocation is scheduled (the
        host implements the fault itself — e.g. the manifest truncating
        its artifact, the merge treating a peer as not-terminal)."""
        index, spec = self._next(seam)
        if spec is None:
            return False
        self._note(seam, index, spec.error or _DEFAULT_KIND[seam])
        return True

    def corrupt(self, seam: str, arr):
        """Corruption seam: return a damaged stand-in for ``arr`` on a
        firing invocation (a truncated view — the wrong-shape damage the
        consumer-side validation must catch), else ``arr`` unchanged."""
        index, spec = self._next(seam)
        if spec is None:
            return arr
        self._note(seam, index, spec.error or "corrupt")
        return arr.reshape(-1)[: max(1, arr.size // 2)]

    def injected(self) -> "list[tuple[str, int, str]]":
        """(seam, index, kind) log of every fault this plan fired."""
        with self._lock:
            return list(self._injected)

    def counts(self) -> "dict[str, int]":
        with self._lock:
            return dict(self._counts)


def parse_schedule(text: str) -> FaultPlan:
    """``--fault-schedule`` string → :class:`FaultPlan`.

    Grammar: comma-separated items.  ``seed=N`` (anywhere, default 0)
    seeds the probability draws; every other item is
    ``SEAM@INDEX[*TIMES]`` or ``SEAM%PROB``, optionally suffixed
    ``=KIND`` or ``=KIND:ARG``.  Raises ``ValueError`` on any typo —
    a misspelled seam is a dead injection, which is a config error.
    """
    seed = 0
    specs: list[FaultSpec] = []
    for raw in text.split(","):
        item = raw.strip()
        if not item:
            continue
        if item.startswith("seed="):
            seed = int(item[5:])
            continue
        kind, arg = "", None
        if "=" in item:
            item, _, err = item.partition("=")
            if ":" in err:
                kind, _, a = err.partition(":")
                arg = float(a)
            else:
                kind = err
        if "@" in item:
            seam, _, where = item.partition("@")
            times = 1
            if "*" in where:
                where, _, n = where.partition("*")
                times = int(n)
            specs.append(
                FaultSpec(seam, at=int(where), times=times, error=kind, arg=arg)
            )
        elif "%" in item:
            seam, _, p = item.partition("%")
            specs.append(FaultSpec(seam, prob=float(p), error=kind, arg=arg))
        else:
            raise ValueError(
                f"fault spec {raw!r} has no @index or %probability"
            )
    return FaultPlan(seed=seed, specs=tuple(specs))


# -- process-wide activation (one plan at a time, like the blockcache) ----
_active: "FaultPlan | None" = None


def activate(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` as the process's active schedule and register
    the layer hooks (:func:`land_trendr_tpu.io.blockcache.
    set_fault_plan` for the decode-path seams, :func:`land_trendr_tpu.
    obs.publish.set_fault_plan` for the fleet-telemetry seams) so those
    layers see it without importing ``runtime/``."""
    global _active
    _active = plan
    from land_trendr_tpu.io import blockcache
    from land_trendr_tpu.obs import publish as obs_publish

    blockcache.set_fault_plan(plan)
    obs_publish.set_fault_plan(plan)
    return plan


def deactivate() -> None:
    global _active
    _active = None
    from land_trendr_tpu.io import blockcache
    from land_trendr_tpu.obs import publish as obs_publish

    blockcache.set_fault_plan(None)
    obs_publish.set_fault_plan(None)


def active() -> "FaultPlan | None":
    return _active


def set_observer(fn: "Callable[[str, int, str], None] | None") -> None:
    """Register a per-fire callback ``(seam, index, kind)`` on the active
    plan — how the driver turns injections into ``fault_injected``
    telemetry events without this module knowing telemetry exists."""
    plan = _active
    if plan is not None:
        plan._observer = fn


def check(seam: str) -> None:
    """Module-level raising seam (no-op when no plan is active)."""
    plan = _active
    if plan is not None:
        plan.check(seam)


def fired(seam: str) -> bool:
    plan = _active
    return plan.fired(seam) if plan is not None else False


def corrupt(seam: str, arr):
    plan = _active
    return plan.corrupt(seam, arr) if plan is not None else arr
