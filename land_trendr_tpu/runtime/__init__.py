"""runtime subpackage: host driver, tile manifest, stack loading."""

from land_trendr_tpu.runtime.driver import (
    Run,
    RunCancelled,
    RunConfig,
    StallError,
    TileRetriesExhausted,
    TileSpec,
    assemble_outputs,
    plan_tiles,
    run_stack,
)
from land_trendr_tpu.runtime.leases import LeaseQueue
from land_trendr_tpu.runtime.manifest import TileManifest, run_fingerprint
from land_trendr_tpu.runtime.stack import (
    RasterStack,
    load_stack_dir,
    load_stack_dir_c2,
    stack_from_synthetic,
)

__all__ = [
    "Run",
    "RunCancelled",
    "RunConfig",
    "StallError",
    "TileRetriesExhausted",
    "TileSpec",
    "assemble_outputs",
    "plan_tiles",
    "run_stack",
    "RasterStack",
    "load_stack_dir",
    "load_stack_dir_c2",
    "stack_from_synthetic",
    "LeaseQueue",
    "TileManifest",
    "run_fingerprint",
]
