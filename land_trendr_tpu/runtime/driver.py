"""The tile-run driver: stacks in, segment rasters out.

This is the TPU-native replacement for the reference's L5+L4 layers
(SURVEY.md §2): where the reference driver serialises one record per pixel
and submits a Hadoop MapReduce job ("one map task per pixel", §4 call
stacks 1-3), this driver cuts the scene into fixed-size tiles, feeds each
as an HBM-resident ``(tile_px, year)`` batch to the fused device op
(:func:`land_trendr_tpu.ops.tile.process_tile_dn`), and reassembles the
per-pixel outputs into segment rasters on the input grid — the same
stacks-in / rasters-out contract, with the process-spawn + text-shuffle
overhead deleted.

Design points (SURVEY.md §5 / §7):

* **One compilation**: every tile — including edge tiles — is padded to the
  same ``tile_size²`` pixel count with fully-masked rows, so the kernel
  compiles once per run.
* **Checkpoint/resume**: each finished tile persists via
  :class:`~land_trendr_tpu.runtime.manifest.TileManifest`; a resumed run
  skips them.  The manifest *is* the checkpoint.
* **Failure handling**: tiles are independent; a failed tile is retried
  ``max_retries`` times before the run aborts (Hadoop's task-retry
  equivalent, minus speculative execution which a single SPMD program does
  not need).
* **Observability**: structured per-tile logs (px/sec, no-fit rate, mean
  p-of-F) through :mod:`logging`, plus a run summary dict; with
  ``RunConfig.telemetry`` the run additionally reports through
  :mod:`land_trendr_tpu.obs` — a schema-versioned ``events.jsonl`` stream
  (run/tile lifecycle, retries, backlog depths), a Prometheus
  ``metrics.prom`` exposition refreshed in flight, and an optional live
  ``/metrics`` endpoint (``metrics_port``) — the Hadoop-counters
  equivalent a production-scale deployment scrapes.
"""

from __future__ import annotations

import _thread
import dataclasses
import logging
import os
import random
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

import jax
import numpy as np

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.io import blockcache, native
from land_trendr_tpu.obs.spans import StragglerDetector
from land_trendr_tpu.io.geotiff import GeoTiffStreamWriter
from land_trendr_tpu.ops import indices as idx
from land_trendr_tpu.ops.change import ChangeFilter
from land_trendr_tpu.ops.tile import PALLAS_BLOCK, process_tile_dn, resolve_impl
from land_trendr_tpu.runtime import feed as feedmod
from land_trendr_tpu.runtime import fetch as fetchmod
from land_trendr_tpu.runtime import faults
from land_trendr_tpu.runtime.leases import LeaseQueue
from land_trendr_tpu.runtime.manifest import (
    ARTIFACT_COMPRESS,
    TileManifest,
    run_fingerprint,
)
from land_trendr_tpu.runtime.stack import RasterStack
from land_trendr_tpu.tune import resolve_config
from land_trendr_tpu.utils.profiling import StageTimer

__all__ = [
    "Run",
    "RunCancelled",
    "RunConfig",
    "StallError",
    "TileRetriesExhausted",
    "TileSpec",
    "plan_tiles",
    "run_stack",
    "assemble_outputs",
]

log = logging.getLogger("land_trendr_tpu.runtime")

#: one-time warning latch for the native feed-gather fallback
_warned_gather_fallback = False

#: demote the packed fetch path to per-product sync transfers after this
#: many fetch-wait failures in one run — a sick link must not keep
#: spending every subsequent tile's retry budget on transfer faults
_FETCH_DEMOTE_AFTER = 3

#: the upload mirror: demote the packed host→device path to the
#: per-array sync dispatch after this many CONSECUTIVE upload failures
_UPLOAD_DEMOTE_AFTER = 3

#: retry backoff ceiling: the exponential ladder never sleeps longer
#: than this between attempts, whatever max_retries is set to
_BACKOFF_CAP_S = 30.0


class TileRetriesExhausted(RuntimeError):
    """One tile failed ``attempts`` times (dispatch, device wait, fetch,
    or feed).  Without ``RunConfig.quarantine_tiles`` it aborts the run
    (CLI exit code 3); with it, the tile is recorded as failed in the
    manifest and the run continues."""

    def __init__(self, tile_id: int, attempts: int, cause: BaseException) -> None:
        super().__init__(f"tile {tile_id} failed after {attempts} attempts")
        self.tile_id = tile_id
        self.attempts = attempts
        self.cause = cause


class StallError(RuntimeError):
    """The stall watchdog aborted the run: no tile progress for
    ``RunConfig.stall_timeout_s`` (CLI exit code 4)."""


class RunCancelled(RuntimeError):
    """The run's cancel event was set (job cancel / job timeout in serve
    mode): the run unwound through the normal abort path — every tile
    recorded before the cancel stays durable, so the manifest is
    resumable and a re-run completes exactly the remaining tiles."""


class _StallWatchdog:
    """Abort a run whose device wait hangs instead of hanging with it.

    A daemon thread watches a progress timestamp the driver ticks at
    every pipeline step (feed result, dispatch, compute wait, fetch
    landing, write collection, retry attempts).  When the gap exceeds
    ``timeout_s`` it calls ``on_stall`` (telemetry ``stall`` event — the
    stream must say WHY the run died even if the unwind never finishes),
    then interrupts the main thread; the driver converts that into
    :class:`StallError`, so the normal abort path (telemetry ``run_done
    aborted``, pool shutdown) still runs.  If the main thread is stuck in
    an uninterruptible native call and the run has not unwound within the
    grace period, the watchdog hard-exits the process with the documented
    stall code (4) — the one case where a clean unwind is impossible by
    definition.
    """

    def __init__(
        self,
        timeout_s: float,
        on_stall: "Callable[[float], None]",
        grace_s: "float | None" = None,
    ) -> None:
        self._timeout = float(timeout_s)
        self._grace = float(grace_s) if grace_s is not None else max(
            30.0, self._timeout
        )
        self._on_stall = on_stall
        self._lock = threading.Lock()
        self._last = time.monotonic()
        self._done = threading.Event()
        self.stalled = False
        self._thread = threading.Thread(
            target=self._run, name="lt-stall-watchdog", daemon=True
        )

    def start(self) -> "_StallWatchdog":
        self._thread.start()
        return self

    def tick(self) -> None:
        """Note pipeline progress (any step counts — first-tile compiles
        and retry ladders are slow but alive)."""
        with self._lock:
            self._last = time.monotonic()

    def stop(self) -> None:
        self._done.set()
        self._thread.join(timeout=5.0)

    def _run(self) -> None:
        poll = min(1.0, self._timeout / 4.0)
        while not self._done.wait(poll):
            with self._lock:
                idle = time.monotonic() - self._last
            if idle < self._timeout:
                continue
            with self._lock:
                self.stalled = True
            log.critical(
                "stall watchdog: no tile progress for %.1fs "
                "(stall_timeout_s=%.1f); aborting the run", idle, self._timeout,
            )
            try:
                self._on_stall(idle)
            except Exception:
                log.exception("stall watchdog: stall-event emit failed")
            _thread.interrupt_main()
            if not self._done.wait(self._grace):
                log.critical(
                    "stall watchdog: run did not unwind within %.0fs grace; "
                    "hard abort (exit 4)", self._grace,
                )
                os._exit(4)
            return


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """Everything that defines a segmentation run over one stack."""

    index: str = "nbr"
    ftv_indices: tuple[str, ...] = ()
    params: LTParams = LTParams()
    #: scene tiling granularity (pixels per side).  ``"auto"`` resolves
    #: through the tuning store at Run construction (see
    #: ``tune_store_dir``) — like every tunable knob below, an explicit
    #: value always wins and ``"auto"`` with no profile is the default.
    #: Fingerprinted (via the resolved value): tiling defines artifacts.
    tile_size: "int | str" = 256
    workdir: str = "lt_work"
    out_dir: str = "lt_out"
    resume: bool = True
    max_retries: int = 2
    #: base of the exponential retry backoff: attempt ``n`` sleeps about
    #: ``retry_backoff_s * 2**(n-1)`` (±50% jitter, capped at 30s) before
    #: re-dispatching — a sick device gets breathing room instead of an
    #: immediate hammer.  ``0`` restores the immediate-retry behavior.
    retry_backoff_s: float = 0.5
    #: after ``max_retries`` a tile is recorded as FAILED in the manifest
    #: and the run continues (Kennedy et al. 2010 semantics: tiles are
    #: independent — one bad tile must not cost the other 10k).  The run
    #: summary carries ``tiles_quarantined``; the CLI exits 3 and skips
    #: assembly; a resume re-attempts quarantined tiles.  Off by default:
    #: a single-tile run aborting loudly is the right default semantics.
    quarantine_tiles: bool = False
    #: abort the run after this many seconds without tile progress (feed,
    #: dispatch, device wait, fetch, write, retries all count) — a hung
    #: device wait is otherwise an infinite hang.  Emits the ``stall``
    #: telemetry event and raises :class:`StallError` (CLI exit 4; a main
    #: thread stuck in an uninterruptible native call is hard-exited with
    #: the same code after a grace period).  ``None`` disables.  Set it
    #: well above the first tile's compile time and the retry ladder's
    #: worst-case backoff (≤30s per attempt).
    stall_timeout_s: float | None = None
    #: bound on the multihost primary's wait for straggler peers'
    #: ``run_done`` during the event-log merge.  ``None`` (default)
    #: derives it from this run's wall time (``max(60, min(2*wall,
    #: 900))``); operators who know their pod's straggler profile set it
    #: explicitly.
    merge_timeout_s: float | None = None
    #: live straggler threshold: a tile whose in-flight duration exceeds
    #: ``straggler_k`` x the rolling median of recent tile durations is
    #: flagged (``tile_straggler`` event, ``lt_stragglers_total``,
    #: ``/debug/jobs`` and ``lt top`` on serve runs).  Pure observability
    #: — a flagged tile keeps running; the elastic scheduler (ROADMAP
    #: item 2) is the consumer this contract is built for.  Must be
    #: >= 1 (below the median would flag typical tiles).
    straggler_k: float = 4.0
    #: no straggler verdicts until this many tiles have completed in the
    #: run — the first tile carries the jit compile and a one-sample
    #: median is noise, so early tiles must never false-positive.
    straggler_min_tiles: int = 5
    #: elastic pod scheduling (:mod:`land_trendr_tpu.runtime.leases`):
    #: ``0`` (default) keeps the static ``host_share`` tile split; ``N >
    #: 0`` replaces it with the shared-manifest lease queue — this
    #: process claims tiles ``N`` at a time, renews its leases on
    #: progress ticks, and steals tiles whose leases expired (dead or
    #: wedged peer) or were never claimed, so hosts may join/leave
    #: mid-run and one slow host no longer strands a static share.
    #: Correctness never rides the lease: the done record stays the one
    #: durability signal and double execution resolves to byte-identical
    #: artifacts at the atomic rename.  An execution fact — never
    #: fingerprinted; a resume may freely mix static and leased runs.
    lease_batch: int = 0
    #: lease time-to-live, seconds: a lease not renewed within this
    #: window is stealable by any sibling.  Size it comfortably above
    #: the slowest tile (renewals tick from the driver loop, so a tile
    #: longer than the TTL invites a benign duplicate execution) and
    #: above the pod's worst wall-clock skew.  A throughput knob, never
    #: a correctness one.
    lease_ttl_s: float = 30.0
    #: with ``lease_batch > 0``: straggler-steered speculative
    #: execution — an idle host re-leases a tile the owner's live
    #: StragglerDetector flagged (still in flight, lease unexpired);
    #: first durable write wins, the loser's write lands as an identical
    #: no-op.  The PR-10 verdicts steer instead of merely watch.
    speculate: bool = False
    #: deterministic fault-injection schedule
    #: (:func:`land_trendr_tpu.runtime.faults.parse_schedule`, e.g.
    #: ``"seed=7,dispatch@1,fetch.wait@0*2=io"``) — fires scheduled
    #: errors at the named pipeline seams so recovery paths run
    #: deterministically (tests, ``tools/fault_soak.py``).  ``None``
    #: (production) keeps every seam inert.  An execution fact — never
    #: fingerprinted.
    fault_schedule: str | None = None
    write_fitted: bool = False  # include the (NY,) fitted trajectory raster
    #: segmentation products to checkpoint + assemble; ``None`` = the full
    #: set.  A subset (e.g. ``("n_vertices", "vertex_years",
    #: "seg_magnitude", "rmse", "model_valid")``) cuts manifest + output
    #: bytes proportionally — the knob that makes gigapixel runs fit
    #: bounded disk (BASELINE configs[4]; the reference's driver likewise
    #: writes only requested outputs).  Change products are governed by
    #: ``change_filt``, FTV products by ``ftv_indices``; this filters the
    #: per-pixel segmentation set only.  Fingerprinted: a resume cannot
    #: mix artifact schemas.
    products: "tuple[str, ...] | None" = None
    #: fetch float products from the device as float16 (cast on device,
    #: restored to the float32 manifest schema on host): halves
    #: device→host bytes for every float product.  Opt-in lossy packing
    #: (f16 quantization ~5e-4 relative — far inside the f32 tolerance
    #: contract's measured decision envelope but far above kernel rounding,
    #: hence not the default).  The dominant cost on a tunneled chip
    #: (SCENE_TPU_r04.json: fetch was 96% of wall) and a real PCIe/DCN
    #: saving in any deployment.  Not fingerprinted content-wise — but it
    #: changes written values, so it IS part of the run fingerprint.
    fetch_f16: bool = False
    #: device→host fetch strategy (:mod:`land_trendr_tpu.runtime.fetch`):
    #: ``"auto"`` (default) packs every tile's selected products into ONE
    #: contiguous device buffer — one D2H transfer per tile instead of
    #: ~10 latency-bound per-product ones, with ``fetch_f16`` casts fused
    #: into the pack program and the transfer overlapping the next tile's
    #: compute — on accelerator backends, and keeps the per-product path
    #: on CPU (where ``np.asarray`` is zero-copy and packing is pure
    #: overhead).  ``True``/``False`` force.  A pure execution strategy:
    #: packed and unpacked artifacts are byte-identical (pinned by
    #: ``tests/test_fetch.py``), so it is NOT fingerprinted and a resume
    #: may mix the two.
    fetch_packed: "bool | str" = "auto"
    #: bound on in-flight packed fetches: tile ``i``'s readback lands
    #: while tiles up to ``i + fetch_depth`` compute.  Host memory grows
    #: by one packed tile buffer plus one fed input (kept for the retry
    #: ladder — an async-fetch device error re-dispatches from it) per
    #: depth step; 2 gives full compute/readback overlap for a
    #: steady-state pipeline.  ``"auto"`` resolves through the tuning
    #: store (a pure execution knob — never fingerprinted).
    fetch_depth: "int | str" = 2
    #: host→device upload strategy (:mod:`land_trendr_tpu.runtime.feed`):
    #: ``"auto"`` (default) packs every tile's fed band/QA arrays into
    #: ONE contiguous host buffer and issues a single asynchronous
    #: ``jax.device_put`` per tile — the transfer crosses the link while
    #: earlier tiles compute, and a tiny jitted device program unpacks it
    #: back into the per-band arrays — on accelerator backends, and keeps
    #: the per-array sync path on CPU (where ``device_put`` is near
    #: zero-copy and packing is pure overhead) and on mesh runs (sharded
    #: placement is per-array by construction).  ``True``/``False``
    #: force; forcing ``True`` with a mesh raises.  A pure execution
    #: strategy — the wire format is a bit-exact reinterpretation, so
    #: packed and per-array artifacts are byte-identical and the knob is
    #: never fingerprinted.
    upload_packed: "bool | str" = "auto"
    #: bound on in-flight packed uploads: up to this many fed tiles have
    #: their packed buffers crossing the link ahead of dispatch (double-
    #: buffering against the current tile's compute).  Host memory grows
    #: by one packed buffer plus one fed input (retained for the retry
    #: ladder — an upload error surfacing through the async wait
    #: re-dispatches from it on the per-array path) per depth step.
    #: ``"auto"`` resolves through the tuning store (execution knob).
    upload_depth: "int | str" = 2
    #: persistent decoded-block store budget (MiB) for the windowed feed
    #: path (:mod:`land_trendr_tpu.io.blockstore`): decoded TIFF blocks
    #: spill to a memory-mapped on-disk column store under the workdir,
    #: keyed by the same ``(path, mtime_ns, size, page, block)``
    #: fingerprint as the RAM cache — so a second run over the same
    #: stacks ("ingest once, serve many") skips TIFF decode entirely.
    #: ``0`` (default) disables the store.  An execution fact — NOT
    #: fingerprinted; a rewritten input file invalidates itself via the
    #: fingerprint key.
    ingest_store_mb: int = 0
    #: store directory override (default ``<workdir>/ingest_store``) —
    #: point several runs' workdirs at one shared store for the
    #: service-mode "same stacks, many runs" workload.
    ingest_store_dir: "str | None" = None
    #: fuse on-device change-map selection into every tile's program
    #: (ops/change.select_change over arrays already in HBM); the per-tile
    #: change products ride the manifest and assemble into change_*.tif
    #: rasters alongside the segment products.  The spatial mmu sieve
    #: needs global connectivity — apply ops.change.sieve_change_rasters
    #: to the assembled out_dir (the CLI's --change-mmu does).
    change_filt: "ChangeFilter | None" = None
    scale: float = 2.75e-5
    offset: float = -0.2
    reject_bits: int = idx.DEFAULT_QA_REJECT
    #: output raster compression: "deflate" (default), "lzw" (what most
    #: GDAL-era pipelines emit), or "none"
    out_compress: str = "deflate"
    #: per-tile checkpoint artifact compression: "none" (default — measured
    #: ~18× faster than zlib-6 and the write stage otherwise dominates host
    #: time at device-rate throughput; see manifest._write_npz) or
    #: "deflate" (zlib-1, for constrained workdir storage).  A pure
    #: speed/size trade: resume reads either, so it is not fingerprinted.
    manifest_compress: str = "none"
    #: background tile-writer threads.  One writer sustains ~0.64M px/s
    #: (HOSTPATH_r03.json write.none) — enough to overlap a CPU run but
    #: ~16× short of the 10M px/s north star, so device-rate hosts scale
    #: the writer pool instead.  Host memory stays bounded: at most
    #: ``write_workers + 2`` tiles are live at once.
    write_workers: int = 1
    #: background feed threads (the writer pool's mirror on the input
    #: side).  One thread of the threaded native gather sustains ~4.1M
    #: px/s (HOSTPATH_r03.json feed.native), so the 10M px/s north star
    #: needs ~3; the default 1 still overlaps the NEXT tile's gather with
    #: the current tile's device wait (prefetch depth feed_workers + 1).
    #: ``"auto"`` resolves through the tuning store (execution knob).
    feed_workers: "int | str" = 1
    #: decoded-block cache budget (MiB) for the windowed feed path
    #: (:mod:`land_trendr_tpu.io.blockcache`): tile windows that revisit a
    #: compressed TIFF block — tile-boundary overlap, ``LazyBandCube``
    #: re-reads, resume passes — decode it once (GIGA_r05.json: the feed
    #: stage was the dominant non-compute cost).  ``0`` disables the
    #: cache and reproduces the uncached codec byte for byte.  The cache
    #: is process-wide (like GDAL's block cache) and an execution fact —
    #: NOT fingerprinted; run_stack (re)configures it per run.
    #: ``"auto"`` resolves through the tuning store.
    feed_cache_mb: "int | str" = 256
    #: feed-decode threads (the ``io.blockcache`` knob, governing both
    #: the native codec's C++ threading and the NumPy path's shared
    #: pool): 0 = auto (native auto-threads; NumPy min(8, cores)),
    #: 1 = fully serial decode, N = N threads.  ``"auto"`` resolves
    #: through the tuning store (execution knob; distinct from 0, the
    #: codec's own auto-threading).
    decode_workers: "int | str" = 0
    #: readahead: the feed pool hints the NEXT planned tile's block set
    #: (``LazyBandCube.prefetch_window``) so its decode overlaps the
    #: current tile's device wait.  Only effective with a file-backed
    #: lazy stack and ``feed_cache_mb > 0``; eager in-RAM stacks have no
    #: blocks to prefetch.
    feed_readahead: bool = True
    #: overview pyramid levels on output rasters (0 = none, N = that many
    #: 2× reductions, "auto" = until the smaller dimension < 256) — the
    #: gdaladdo-style reduced pages GIS viewers expect on scene-scale
    #: rasters.  Nearest-neighbour decimation: several products are
    #: categorical (model_valid, n_vertices, vertex slots), where
    #: averaging would fabricate values.
    out_overviews: int | str = 0
    #: transient-HBM bound for large tiles: tiles with more pixels than this
    #: run the segmentation through the chunked kernel (the kernel's working
    #: set is linear in the pixel axis — a 1024² tile at 40 years exceeds
    #: what a 256² tile needs by 16×).  ``None`` disables chunking;
    #: ``"auto"`` resolves through the tuning store.  Fingerprinted (via
    #: the resolved value): chunking changes f32 fusion knife-edges.
    chunk_px: "int | str | None" = 262_144
    #: segmentation kernel implementation: "auto" (Pallas family kernel on
    #: a TPU backend, XLA elsewhere — the round-4 measured default, ~3.3×
    #: faster on v5 lite with identical decisions), "pallas", or "xla".
    impl: str = "auto"
    #: run-wide telemetry (:mod:`land_trendr_tpu.obs`): a schema-versioned
    #: ``events.jsonl`` stream (one file per process in multihost runs) and
    #: a Prometheus ``metrics.prom`` exposition refreshed from a daemon
    #: thread, both under ``workdir``.  An execution fact like
    #: ``write_workers`` — NOT fingerprinted, and per-tile overhead is a
    #: few JSON lines (measured ≪ 2% of even a CPU-backend run's wall).
    telemetry: bool = False
    #: with ``telemetry``: also serve a live ``/metrics`` endpoint on this
    #: port (0 = ephemeral, reported in the run summary) so an in-flight
    #: gigapixel run is scrapeable.  ``None`` (default) = no server.
    #: Multi-process runs bind ``port + process_index`` (per-process, like
    #: the event/metrics file naming) so same-host pods don't collide.
    metrics_port: int | None = None
    #: bind address for the ``/metrics`` server.  Default ``""`` = all
    #: interfaces (the scrape-from-another-host use case); operators on
    #: shared nodes can restrict the unauthenticated endpoint with
    #: ``"127.0.0.1"``
    metrics_host: str = ""
    #: ``metrics.prom`` refresh period, seconds
    metrics_interval_s: float = 5.0
    #: with ``telemetry``: flight recorder (:mod:`land_trendr_tpu.obs.
    #: flight`) — a bounded in-memory ring mirroring every telemetry
    #: emit plus a periodic resource sampler thread (``flight_sample``
    #: events: RSS, open fds, threads, pipeline backlogs, cache
    #: occupancy, HBM watermark), dumped to ``<workdir>/flight.jsonl``
    #: at run end (success AND abort — the post-mortem window).  An
    #: execution fact, never fingerprinted; overhead is within the
    #: telemetry noise band (``FLIGHT_r12.json``).
    flight: bool = False
    #: flight-ring capacity, events: the "last N events" window the ring
    #: holds (a dump/debug read shows at most this much history)
    flight_ring_events: int = 2048
    #: flight resource-sampler period, seconds
    sampler_interval_s: float = 5.0
    #: with ``telemetry``: fleet telemetry publish (:mod:`land_trendr_tpu.
    #: obs.publish`) — periodically snapshot this process's metrics
    #: registry + live progress/straggler/quarantine state into an
    #: atomic ``<telemetry_dir>/<host>.<pid>.snap.json``, the
    #: per-process feed the pod aggregate (``tools/lt_fleet.py``,
    #: ``lt top --dir``, the serve fleet loop) folds into one pane of
    #: glass.  An execution fact, never fingerprinted; a failed publish
    #: beat is a skipped beat (the host ages toward stale), never a
    #: failed run.
    publish: bool = False
    #: fleet snapshot refresh period, seconds
    publish_interval_s: float = 5.0
    #: shared telemetry directory override (default
    #: ``<workdir>/telemetry``) — point a pod's processes (or several
    #: runs) at one directory to aggregate them as one fleet
    telemetry_dir: "str | None" = None
    #: on-disk tuning store (:mod:`land_trendr_tpu.tune`) the ``"auto"``
    #: knob sentinels resolve through at Run construction: the
    #: ``lt tune``-probed profile for this ``(device kind, backend,
    #: scene shape class)`` supplies the knob values; a key miss (or
    #: ``None``, the default) falls back to the hardcoded defaults —
    #: byte-identical behavior.  Point a fleet's replicas at one shared
    #: store so the whole fleet runs tuned.  Resolution is a
    #: deterministic store read — never a probe — so it is not an
    #: execution hazard; the RESOLVED knob values are what
    #: fingerprinting sees.
    tune_store_dir: "str | None" = None

    def __post_init__(self) -> None:
        from land_trendr_tpu.tune import AUTO

        for name in (
            "tile_size", "chunk_px", "fetch_depth", "upload_depth",
            "feed_workers", "decode_workers", "feed_cache_mb",
        ):
            v = getattr(self, name)
            if isinstance(v, str) and v != AUTO:
                # "auto" is the ONE string spelling (the tuning-store
                # sentinel); anything else is a config typo, caught at
                # exit-2 time like every other validation below
                raise ValueError(
                    f"{name}={v!r} must be an integer or 'auto'"
                )
        if isinstance(self.tile_size, int) and self.tile_size < 1:
            raise ValueError(f"tile_size={self.tile_size} must be >= 1")
        # fail fast: an invalid choice must not surface only at
        # assemble_outputs, after the whole run's compute
        if self.out_compress not in ("deflate", "lzw", "none"):
            raise ValueError(
                f"out_compress={self.out_compress!r} not one of "
                "'deflate', 'lzw', 'none'"
            )
        if self.manifest_compress not in ARTIFACT_COMPRESS:
            raise ValueError(
                f"manifest_compress={self.manifest_compress!r} not one of "
                f"{ARTIFACT_COMPRESS}"
            )
        if self.products is not None:
            bad = [p for p in self.products if p not in _SEG_PRODUCTS]
            if bad:
                raise ValueError(
                    f"unknown products {bad}; choose from {_SEG_PRODUCTS}"
                )
            if not self.products:
                raise ValueError("products subset must not be empty (use None)")
        if self.impl not in ("auto", "pallas", "xla"):
            raise ValueError(
                f"impl={self.impl!r} not one of 'auto', 'pallas', 'xla'"
            )
        if (
            self.impl == "pallas"  # "auto" is validated in run_stack once
            # the backend is known — resolving it here would initialise a
            # JAX client as a side effect of constructing a config
            and isinstance(self.chunk_px, int)  # "auto" re-validates resolved
            and self.chunk_px > PALLAS_BLOCK
            and self.chunk_px % PALLAS_BLOCK
        ):
            # chunks <= the block clamp the block instead; checked here so
            # a bad combination fails at config time, not mid-run
            raise ValueError(
                f"chunk_px={self.chunk_px} must be a multiple of "
                f"{PALLAS_BLOCK} (the Pallas block) when impl='pallas'"
            )
        if isinstance(self.chunk_px, int) and self.chunk_px < 1:
            # 0 is NOT the disable spelling (None is): a zero chunk would
            # divide-by-zero deep in the chunked kernel, minutes into a run
            raise ValueError(
                f"chunk_px={self.chunk_px} must be >= 1 (or None to "
                "disable chunking)"
            )
        if self.fetch_packed not in (True, False, "auto"):
            raise ValueError(
                f"fetch_packed={self.fetch_packed!r} not one of True, "
                "False, 'auto'"
            )
        if isinstance(self.fetch_depth, int) and self.fetch_depth < 1:
            raise ValueError(f"fetch_depth={self.fetch_depth} must be >= 1")
        if self.upload_packed not in (True, False, "auto"):
            raise ValueError(
                f"upload_packed={self.upload_packed!r} not one of True, "
                "False, 'auto'"
            )
        if isinstance(self.upload_depth, int) and self.upload_depth < 1:
            raise ValueError(f"upload_depth={self.upload_depth} must be >= 1")
        if self.ingest_store_mb < 0:
            raise ValueError(
                f"ingest_store_mb={self.ingest_store_mb} must be >= 0 "
                "(0 = off)"
            )
        if self.ingest_store_dir is not None and not self.ingest_store_mb:
            raise ValueError(
                "ingest_store_dir requires ingest_store_mb > 0 (there is "
                "no store to place without a budget)"
            )
        if self.write_workers < 1:
            raise ValueError(f"write_workers={self.write_workers} must be >= 1")
        if isinstance(self.feed_workers, int) and self.feed_workers < 1:
            raise ValueError(f"feed_workers={self.feed_workers} must be >= 1")
        if isinstance(self.feed_cache_mb, int) and self.feed_cache_mb < 0:
            raise ValueError(
                f"feed_cache_mb={self.feed_cache_mb} must be >= 0 (0 = off)"
            )
        if isinstance(self.decode_workers, int) and self.decode_workers < 0:
            raise ValueError(
                f"decode_workers={self.decode_workers} must be >= 0 (0 = auto)"
            )
        if self.out_overviews != "auto" and (
            not isinstance(self.out_overviews, int) or self.out_overviews < 0
        ):
            raise ValueError(
                f"out_overviews={self.out_overviews!r} must be >= 0 or 'auto'"
            )
        if self.metrics_port is not None:
            if not self.telemetry:
                raise ValueError(
                    "metrics_port requires telemetry=True (the registry the "
                    "endpoint serves only exists on telemetry runs)"
                )
            if not (0 <= self.metrics_port <= 65535):
                raise ValueError(
                    f"metrics_port={self.metrics_port} outside 0..65535"
                )
        elif self.metrics_host:
            raise ValueError(
                "metrics_host requires metrics_port (there is no server "
                "to bind without a port)"
            )
        if self.telemetry and self.metrics_interval_s <= 0:
            raise ValueError(
                f"metrics_interval_s={self.metrics_interval_s} must be > 0"
            )
        if self.flight and not self.telemetry:
            raise ValueError(
                "flight requires telemetry=True (the ring mirrors the "
                "telemetry event stream; there is nothing to record "
                "without one)"
            )
        if self.flight_ring_events < 2 and self.flight_ring_events != 0:
            raise ValueError(
                f"flight_ring_events={self.flight_ring_events} must be "
                ">= 2 (a useful ring holds at least a run_start and one "
                "event) or 0 (ring + sampler disabled, the serve "
                "convention)"
            )
        if self.sampler_interval_s <= 0:
            raise ValueError(
                f"sampler_interval_s={self.sampler_interval_s} must be > 0"
            )
        if self.publish and not self.telemetry:
            raise ValueError(
                "publish requires telemetry=True (the fleet snapshot is "
                "a dump of the telemetry registry; there is nothing to "
                "publish without one)"
            )
        if self.publish_interval_s <= 0:
            raise ValueError(
                f"publish_interval_s={self.publish_interval_s} must be > 0"
            )
        if self.telemetry_dir is not None and not self.publish:
            raise ValueError(
                "telemetry_dir requires publish=True (there is no "
                "snapshot to place without a publisher)"
            )
        if self.retry_backoff_s < 0:
            raise ValueError(
                f"retry_backoff_s={self.retry_backoff_s} must be >= 0"
            )
        if self.stall_timeout_s is not None and self.stall_timeout_s <= 0:
            raise ValueError(
                f"stall_timeout_s={self.stall_timeout_s} must be > 0 "
                "(or None to disable the watchdog)"
            )
        if self.merge_timeout_s is not None and self.merge_timeout_s <= 0:
            raise ValueError(
                f"merge_timeout_s={self.merge_timeout_s} must be > 0 "
                "(or None for the wall-time-derived bound)"
            )
        if self.straggler_k < 1.0:
            raise ValueError(
                f"straggler_k={self.straggler_k} must be >= 1.0 (a "
                "threshold below the rolling median would flag typical "
                "tiles as stragglers)"
            )
        if self.straggler_min_tiles < 1:
            raise ValueError(
                f"straggler_min_tiles={self.straggler_min_tiles} must be "
                ">= 1"
            )
        if self.lease_batch < 0:
            raise ValueError(
                f"lease_batch={self.lease_batch} must be >= 0 (0 = static "
                "host_share split, N = elastic lease queue)"
            )
        if self.lease_ttl_s <= 0:
            raise ValueError(f"lease_ttl_s={self.lease_ttl_s} must be > 0")
        if self.speculate and not self.lease_batch:
            raise ValueError(
                "speculate requires lease_batch > 0 (speculative execution "
                "is a lease-queue path; there is no queue to re-lease from)"
            )
        if self.fault_schedule is not None:
            # parse NOW: a typo'd seam/spec is a config error at exit-2
            # time, not a dead injection discovered after the soak run
            faults.parse_schedule(self.fault_schedule)

    def fingerprint(self, stack: RasterStack) -> str:
        return run_fingerprint(
            {
                "index": self.index,
                "ftv": list(self.ftv_indices),
                "params": self.params.to_dict(),
                "tile": self.tile_size,
                "years": stack.years.tolist(),
                "shape": list(stack.shape),
                "scale": self.scale,
                "offset": self.offset,
                "reject_bits": self.reject_bits,
                # changes the set of arrays each tile artifact carries, so a
                # toggled resume must not reuse old artifacts
                "write_fitted": self.write_fitted,
                "products": (
                    list(self.products) if self.products is not None else None
                ),
                "fetch_f16": self.fetch_f16,
                "change": (
                    dataclasses.asdict(self.change_filt)
                    if self.change_filt is not None else None
                ),
                # chunking changes f32 fusion choices (~0.003% knife-edge
                # decision flips) — a resume must not mix chunkings.  The
                # mesh device count is checked separately via the manifest
                # header's context (assembly must stay mesh-blind).
                "chunk_px": self.chunk_px,
                # NOT fingerprinted: the resolved kernel implementation.
                # It is an execution fact like mesh_devices — recorded in
                # the manifest CONTEXT so a compute resume cannot mix
                # pallas- and xla-produced tiles, while assembly (which
                # never runs the kernel and may happen on a CPU-only
                # controller of a TPU run) stays implementation-blind.
            }
        )


def _device_live_bytes() -> "int | None":
    """Sum of allocator live bytes across local devices, or None where the
    backend exposes no ``memory_stats`` (CPU) — the HBM watermark feed for
    the telemetry gauges."""
    total, seen = 0, False
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
        except Exception:
            return None
        if ms and "bytes_in_use" in ms:
            total += int(ms["bytes_in_use"])
            seen = True
    return total if seen else None


#: the full per-pixel segmentation product set (RunConfig.products domain);
#: "fitted" is governed by write_fitted, change_*/ftv_* by their own knobs.
#: Canonical home is the fetch plan (runtime/fetch.py), which must know
#: every product's wire representation; re-exported here for config
#: validation and existing importers.
_SEG_PRODUCTS = fetchmod.SEG_PRODUCTS


@dataclasses.dataclass(frozen=True)
class TileSpec:
    """One tile's window in the scene grid."""

    tile_id: int
    y0: int
    x0: int
    h: int
    w: int


def plan_tiles(height: int, width: int, tile_size: int) -> list[TileSpec]:
    """Row-major fixed-grid tiling; edge tiles are smaller windows but are
    padded to the full tile pixel count at feed time."""
    tiles = []
    tid = 0
    for y0 in range(0, height, tile_size):
        for x0 in range(0, width, tile_size):
            tiles.append(
                TileSpec(
                    tile_id=tid,
                    y0=y0,
                    x0=x0,
                    h=min(tile_size, height - y0),
                    w=min(tile_size, width - x0),
                )
            )
            tid += 1
    return tiles


def _feed_tile(
    stack: RasterStack, t: TileSpec, tile_px: int, bands: tuple[str, ...]
) -> tuple[dict[str, np.ndarray], np.ndarray]:
    """Slice one tile into ``(tile_px, NY)`` arrays, padding with QA=fill.

    Only ``bands`` (the union the index selection needs — see
    :func:`~land_trendr_tpu.ops.indices.required_bands`) are cut and
    shipped: range-masking on an unused band would drop usable
    observations, and unused bands are wasted host→HBM bytes.  The
    transpose puts the year axis innermost, the layout the kernel's
    per-pixel scans want; padded rows carry the fill QA bit so the device
    mask logic (not special-case host code) voids them.
    """
    ny = stack.n_years
    px = t.h * t.w

    def cut(a: np.ndarray) -> np.ndarray:
        # the feed path's hot transpose (SURVEY.md §7 hard-part 4): the
        # threaded native gather sustains ~2.3 GB/s/core vs NumPy's ~1;
        # both produce identical arrays.  Lazy file-backed cubes
        # (stack.LazyBandCube — no in-RAM buffer for ctypes to point at)
        # take the slicing path, which window-reads just this tile.
        if native.available() and isinstance(a, np.ndarray):
            try:
                return native.gather_tile(a, t.y0, t.x0, t.h, t.w)
            except native.NativeCodecError as e:
                global _warned_gather_fallback
                if not _warned_gather_fallback:
                    _warned_gather_fallback = True
                    log.warning(
                        "native gather_tile unavailable (%s); feeding via "
                        "the slower NumPy path for this run", e,
                    )
        win = a[:, t.y0 : t.y0 + t.h, t.x0 : t.x0 + t.w]
        return np.ascontiguousarray(win.reshape(ny, px).T)

    dn = {name: cut(stack.dn_bands[name]) for name in bands}
    qa = cut(stack.qa)
    if px < tile_px:
        pad = tile_px - px
        dn = {
            name: np.concatenate([a, np.zeros((pad, ny), a.dtype)]) for name, a in dn.items()
        }
        qa_pad = np.full((pad, ny), 1, dtype=qa.dtype)  # QA fill bit set
        qa = np.concatenate([qa, qa_pad])
    return dn, qa


def _prefetch_tile(
    stack: RasterStack, t: TileSpec, bands: tuple[str, ...]
) -> None:
    """Readahead hint for one planned tile: every lazy file-backed cube
    this run feeds (selected bands + QA) queues its window's block decode
    into the shared cache.  No-op for eager ndarray cubes."""
    for name in (*bands, "qa"):
        cube = stack.qa if name == "qa" else stack.dn_bands.get(name)
        pf = getattr(cube, "prefetch_window", None)
        if pf is not None:
            pf(t.y0, t.x0, t.h, t.w)


def _tile_arrays(out, t: TileSpec, cfg: RunConfig) -> dict[str, np.ndarray]:
    """Device outputs → host npz payload, cropped back to the real window.

    The kernel fits in the disturbance-positive orientation
    (``DISTURBANCE_SIGN`` flip, SURVEY.md §3.1 orientation note); written
    products undo the flip so rasters carry the index's *natural* values —
    healthy-forest NBR reads +0.7, and a disturbance is a ``seg_magnitude``
    drop — matching the reference's output convention (indices.py contract).
    Durations, rmse, p-of-F and vertex bookkeeping are sign-invariant.

    Thin synchronous wrapper over the fetch subsystem's per-product path
    (:mod:`land_trendr_tpu.runtime.fetch`) for tools that fetch single
    tiles outside a run (``tools/host_path_bench.py``); ``run_stack``
    itself drives :class:`~land_trendr_tpu.runtime.fetch.TileFetcher`
    directly so packed transfers overlap compute.
    """
    arrays, _fit = fetchmod.TileFetcher(cfg, packed=False).start(out).tile_arrays(t)
    return arrays


def run_stack(
    stack: RasterStack,
    cfg: RunConfig,
    tiles: Sequence[TileSpec] | None = None,
    mesh: "jax.sharding.Mesh | None" = None,
) -> dict:
    """Segment a whole stack tile by tile; returns the run summary.

    ``mesh`` (a 1-D :func:`land_trendr_tpu.parallel.make_mesh` mesh over
    THIS PROCESS's devices — ``make_mesh(jax.local_devices())``) shards
    every tile's pixel axis over those chips: inputs are placed with
    ``NamedSharding(mesh, P("pixels", None))`` and XLA partitions the
    vmapped kernel with zero cross-pixel collectives — one tile then uses
    all local chips instead of one.  On a multi-host pod, tiles (not
    shards) are the cross-host unit: each process takes its
    :func:`~land_trendr_tpu.parallel.host_share` of the tiles and runs
    them on its own local mesh; a shared-filesystem workdir makes the
    manifest/assembly global, mirroring the reference's HDFS-backed job
    state.  A mesh spanning other processes' devices is rejected.  With a
    mesh, the per-device pixel slice must itself satisfy ``chunk_px``
    (chunking cannot be combined with a sharded pixel axis); oversized
    combinations raise instead of silently exceeding the HBM bound.

    The tile loop is a depth-1 software pipeline over three resources that
    would otherwise idle each other (SURVEY.md §7 step 4 "host
    prefetch/double-buffering"): JAX dispatch is asynchronous, so tile
    ``i``'s device program runs while the host slices tile ``i+1``'s input
    (feed) and a pool of ``cfg.write_workers`` background writer threads
    persists earlier tiles' artifacts.  ``block_until_ready`` on tile
    ``i`` happens only after tile ``i+1`` has been fed and dispatched.
    Device→host readback is its own pipeline stage
    (:mod:`land_trendr_tpu.runtime.fetch`): with the packed fetch path a
    completed tile's products leave the device as ONE asynchronous
    transfer that lands while the next tiles compute, bounded at
    ``cfg.fetch_depth`` in flight.  Host→device upload is its own stage
    too (:mod:`land_trendr_tpu.runtime.feed`): with the packed upload
    path a fed tile's band/QA arrays leave the host as ONE asynchronous
    ``device_put`` issued as soon as its feed completes, crossing the
    link while the tile ahead computes, bounded at ``cfg.upload_depth``
    in flight.  The write queue is bounded at ``write_workers`` in-flight
    jobs (the oldest is collected before a new one is submitted —
    backpressure and fail-fast for writer errors), so at most
    ``write_workers + fetch_depth + upload_depth + 2`` tiles are live at
    once and host memory stays bounded.

    A tile that fails — at dispatch or when its result is awaited — is
    retried synchronously up to ``max_retries`` times before the run
    aborts; a writer error fails the run fast, re-raised within at most
    ``write_workers`` subsequent tiles by the queue's backpressure
    collection.

    Throughput note: the kernel has executed end to end on a real TPU v5
    lite chip (round 3, TPU_PROBE_r03.md), but no trustworthy TPU
    throughput number exists yet (the tunnel's timing artifacts are
    documented there); the measured kernel rates are CPU diagnostics
    (BENCH_r03_cpu.json, PROFILE_r03.json: ~24 k px/s on one core) and
    the scene-scale end-to-end split in SCENE_r03.json.  The *design*
    target is host→HBM feed-bound operation: ~6 B/pixel-year (two int16
    bands + QA for NBR — SURVEY.md §7 hard-part 4) is ~2.4 GB/s per chip
    at the 10M px/s north star, within PCIe-class bandwidth; the
    measured host-stage budget (HOSTPATH_r03.json: native gather 4.1M
    px/s/core, uncompressed artifact write 0.64M px/s/core) says that
    rate rides a handful of feed cores plus parallel writers.
    ``stage_s`` in the summary shows where a given run actually spent
    host time (``compute_s`` includes waiting out transfers on
    bandwidth-limited links).

    Raster outputs are *not* written here — call :func:`assemble_outputs`
    after (or on a later resume; assembly only needs the workdir).
    """
    return Run(stack, cfg, tiles=tiles, mesh=mesh).execute()


class Run:
    """One segmentation run's explicit, per-run state.

    ``run_stack`` used to keep every run-scoped object (manifest,
    telemetry, fetcher/uploader, watchdog, quarantine ledger, stage
    timer, ingest store, fault plan) as function locals — fine for the
    one-shot CLI, fatal for a long-lived server where N runs must
    coexist in one process.  This class makes the run scope explicit:

    * **per-run** — manifest, telemetry (with an optional ``job_id``
      threaded onto every event), fetcher/uploader, stall watchdog,
      stage timer, quarantine ledger;
    * **explicitly shared** — the process-wide decoded-block cache, an
      optional ``shared_store`` (the server's persistent ingest store —
      the run uses it but never closes it, and leaves the process cache
      configuration to its owner), a ``programs``
      :class:`~land_trendr_tpu.serve.programs.ProgramCache` (warm
      compiled-program admission across runs), and the process-global
      fault plan (a run only arms a plan when none is active; a
      server-armed plan is used, never disarmed);
    * **cancellable** — ``cancel`` (a ``threading.Event``) is polled at
      every pipeline step boundary; once set the run raises
      :class:`RunCancelled` and unwinds through the normal abort path,
      so every tile already recorded stays durable and the manifest is
      resumable.

    ``run_stack`` remains the one-shot wrapper: construct + execute.
    """

    def __init__(
        self,
        stack: RasterStack,
        cfg: RunConfig,
        tiles: "Sequence[TileSpec] | None" = None,
        mesh: "jax.sharding.Mesh | None" = None,
        *,
        job_id: "str | None" = None,
        trace_id: "str | None" = None,
        cancel: "threading.Event | None" = None,
        programs=None,
        shared_store=None,
        shared_cache: bool = False,
        flight=None,
        on_tile_durable=None,
    ) -> None:
        # "auto" knob resolution (land_trendr_tpu/tune): any RunConfig
        # field carrying the "auto" sentinel is replaced HERE, before
        # anything reads a knob, from the tuning store's profile for
        # (device kind, backend, scene shape class) — or the hardcoded
        # defaults when no profile exists (byte-identical behavior).
        # Deterministic store READ, never a probe; ``tune_info`` is the
        # tune_profile event execute() emits (None = nothing was auto).
        cfg, self.tune_info = resolve_config(
            cfg, scene_shape=(*stack.shape, stack.n_years)
        )
        self.stack = stack
        self.cfg = cfg
        self.mesh = mesh
        self.tiles = (
            list(tiles) if tiles is not None
            else plan_tiles(*stack.shape, cfg.tile_size)
        )
        self.job_id = job_id
        #: the request-tracing correlation id (minted at router/serve
        #: admission) — stamped beside job_id onto every event of this
        #: run's scope, never part of the config or the fingerprint
        self.trace_id = trace_id
        self.cancel = cancel
        self.programs = programs
        self.shared_store = shared_store
        #: True when the process-wide decoded-block cache is owned by the
        #: caller (a server configures it ONCE at startup; per-run cache
        #: knobs are then deliberately ignored).  Implied by
        #: ``shared_store``.
        self.shared_cache = bool(shared_cache or shared_store is not None)
        if self.shared_cache and shared_store is None and cfg.ingest_store_mb:
            # the run would build a store it can never attach (the cache
            # configuration belongs to the caller): an explicit config
            # conflict, not a silently-dead ingest
            raise ValueError(
                "ingest_store_mb is set but the process cache is caller-"
                "owned (shared_cache=True): pass the caller's store via "
                "shared_store, or drop ingest_store_mb from this run's "
                "config"
            )
        #: the flight ring this run's telemetry mirrors into.  Passed in
        #: by a serving layer (the SERVER's shared ring — job tile
        #: traffic then shows up in /debug/flight live) or created here
        #: when ``cfg.flight`` asks for a standalone one; only an owned
        #: ring gets a sampler thread and a run-end dump.
        self.flight = flight
        self.owns_flight = False
        self.sampler = None
        #: live progress snapshot for the /debug surface and the flight
        #: sampler.  Keys are FIXED at construction (values overwrite in
        #: place), so a point-in-time ``dict(run.progress)`` from another
        #: thread can never race a dict resize; the int/str stores are
        #: atomic and advisory — introspection data, not run state.
        self.progress: dict = {
            "phase": "init",
            "tiles_total": 0,
            "tiles_todo": 0,
            "tiles_done": 0,
            "tiles_quarantined": 0,
            "retries": 0,
            "stragglers": 0,
            "tiles_leased": 0,
            "tiles_stolen": 0,
            "tiles_speculated": 0,
            "feed_backlog": 0,
            "write_backlog": 0,
            "fetch_backlog": 0,
            "upload_backlog": 0,
            "batch_jobs": 0,
            "batch_tiles": 0,
            "batch_occupancy": 0.0,
        }
        #: durability callback (serve/batching demux): invoked on the
        #: writer thread AFTER a tile's artifact is durable, with
        #: (tile, arrays, meta).  Callback errors are swallowed — a
        #: consumer's failure must never fail this run's tile.
        self.on_tile_durable = on_tile_durable
        #: live straggler detector (obs/spans): the driver registers
        #: every dispatched attempt and checks completions; the flight
        #: sampler additionally scans in-flight tiles, so a tile wedging
        #: the driver's own wait still gets flagged.  Verdicts land in
        #: telemetry (``tile_straggler`` + ``lt_stragglers_total``) and
        #: this progress dict (``/debug/jobs``, ``lt top``).
        self.straggler = StragglerDetector(
            k=cfg.straggler_k,
            min_tiles=cfg.straggler_min_tiles,
            on_straggler=self._note_straggler,
        )
        # per-run state, populated by execute(); exposed so a serving
        # layer can introspect a live or finished run
        self.lease_q: "LeaseQueue | None" = None
        self.manifest: "TileManifest | None" = None
        self.telemetry = None
        self.fetcher = None
        self.uploader = None
        self.watchdog: "_StallWatchdog | None" = None
        self.store = None
        self.timer: "StageTimer | None" = None
        self.quarantined: "list[int]" = []
        self.fault_plan = None
        self.program_stats: "dict | None" = None
        self.summary: "dict | None" = None

    def _note_straggler(
        self,
        tile_id: int,
        duration_s: float,
        threshold_s: float,
        median_s: float,
        in_flight: bool,
        attempt: int,
    ) -> None:
        """Detector verdict → progress + telemetry (``tile_straggler``
        event and ``lt_stragglers_total``).  Runs on the driver thread
        (completion checks) or the flight-sampler thread (in-flight
        scans) — both stop before ``run_done``, so the stream's scope
        tail stays terminal."""
        self.progress["stragglers"] = self.straggler.stats()["stragglers"]
        log.warning(
            "tile %d is a straggler: in-flight %.3fs > %.3fs "
            "(%.1fx rolling median %.3fs%s)",
            tile_id, duration_s, threshold_s, self.cfg.straggler_k,
            median_s, ", still running" if in_flight else "",
        )
        tel = self.telemetry
        if tel is not None:
            tel.tile_straggler(
                tile_id, duration_s, threshold_s, median_s,
                in_flight=in_flight, attempt=attempt,
            )
        # elastic mode: the verdict STEERS, not merely watches — flag the
        # held lease in the shared manifest so an idle sibling may
        # speculatively re-lease the tile (first durable write wins).
        # Only in-flight verdicts matter (a completed straggler is done);
        # best-effort: a flag append failing on a sick shared FS must
        # never kill the sampler thread or the run.
        lq = self.lease_q
        if lq is not None and in_flight:
            try:
                lq.flag(tile_id)
            except Exception as exc:
                log.warning(
                    "straggler flag append failed for tile %d: %s",
                    tile_id, exc,
                )

    def _sampler_probes(self) -> dict:
        """Host gauges for the flight sampler's ``flight_sample`` events:
        pipeline backlogs, decode-cache occupancy, and the device
        allocator watermark where the backend exposes one.  Also the
        liveness half of straggler detection: the sampler thread scans
        in-flight tiles here, so a tile wedging the driver's own device
        wait is still flagged while it runs.  Only while the run is live
        — the phase flips to done/aborted at the top of teardown, BEFORE
        the terminal ``run_done``, so a late sampler beat must not append
        verdicts behind the scope's terminal event."""
        if self.progress.get("phase") not in ("done", "aborted"):
            self.straggler.scan()
        p = self.progress
        out = {
            k: int(p[k])
            for k in (
                "feed_backlog", "write_backlog", "fetch_backlog",
                "upload_backlog", "stragglers", "tiles_stolen",
                "tiles_speculated",
            )
        }
        out.update(blockcache.occupancy_probe())
        dev = _device_live_bytes()
        if dev is not None:
            out["device_bytes_in_use"] = dev
        return out

    def _publish_probes(self) -> dict:
        """The ``state`` block of this run's fleet snapshot
        (obs/publish): the live progress dict plus the
        straggler/quarantine verdicts — a point-in-time copy (progress
        keys are fixed at construction, so the copy can never race a
        dict resize).  Read-only: unlike the flight sampler's probes,
        publishing never scans the straggler detector — the snapshot
        observes, the sampler judges."""
        return {
            "progress": dict(self.progress),
            "stragglers": self.straggler.stats()["stragglers"],
            "tiles_quarantined": len(self.quarantined),
            "job_id": self.job_id,
            # which tuning profile (key + age + source) this run resolved
            # its "auto" knobs through — how lt_fleet / lt top --dir make
            # a mixed tuned/untuned fleet visible instead of silent
            **({"tune": self.tune_info} if self.tune_info else {}),
        }

    def _dump_flight(self) -> "str | None":
        """Dump an OWNED ring to ``<workdir>/flight.jsonl`` (per-process
        under multihost), best-effort: the dump is a post-mortem aid and
        must never mask the run's own outcome."""
        if self.flight is None or not self.owns_flight:
            return None
        from land_trendr_tpu.obs.flight import flight_path

        path = flight_path(
            self.cfg.workdir, jax.process_index(), jax.process_count()
        )
        try:
            self.flight.dump(path)
        except Exception as exc:
            log.error("flight-ring dump failed (%s): %s", path, exc)
            return None
        return path

    def _check_cancel(self) -> None:
        """Raise :class:`RunCancelled` once the cancel event is set.

        Polled at pipeline step boundaries (tile loop, retry ladder), so
        cancellation lands within about one tile's latency and unwinds
        through the normal abort path — pending writes drain, recorded
        tiles stay durable, the manifest stays resumable.
        """
        if self.cancel is not None and self.cancel.is_set():
            raise RunCancelled(
                "run cancelled"
                + (f" (job {self.job_id})" if self.job_id else "")
            )

    def execute(self) -> dict:
        """Run the tile pipeline; returns (and stores) the run summary."""
        stack, cfg, mesh = self.stack, self.cfg, self.mesh
        tiles = self.tiles
        tile_px = cfg.tile_size * cfg.tile_size
        n_mesh = int(mesh.devices.size) if mesh is not None else 1

        # NOTE: the ingest store / process cache configuration happens
        # further down, immediately before telemetry construction — a
        # config-validation ValueError below must not leave an owned
        # store's mmaps open and attached to the process-global cache
        # (LT008 found exactly that gap)

        # validate the mesh configuration BEFORE touching the workdir, so a
        # rejected run cannot stamp a fresh manifest with a bad context
        if cfg.metrics_port and cfg.metrics_port + jax.process_count() - 1 > 65535:
            # the per-process fan-out binds port + process_index; a
            # near-ceiling base port must fail fast here, not as a bind
            # OSError deep in a non-primary process minutes into the run
            raise ValueError(
                f"metrics_port={cfg.metrics_port}: port + process_index "
                f"exceeds 65535 for a {jax.process_count()}-process run"
            )
        share = list(tiles)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from land_trendr_tpu.parallel import PIXEL_AXIS, host_share

            # Tiles are the CROSS-HOST work unit (host_share below); the mesh
            # shards one tile's pixels over this process's chips only.  A mesh
            # spanning other processes' devices would make device_put treat
            # each host's different tile as shards of one global array — a
            # silent cross-host mix — so it is rejected outright.
            me = jax.process_index()
            if any(d.process_index != me for d in mesh.devices.flat):
                raise ValueError(
                    "run_stack needs an ADDRESSABLE mesh — build it with "
                    "make_mesh(jax.local_devices()); tiles are distributed "
                    "across hosts by host_share, not by sharding one tile "
                    "over the pod"
                )
            # chunking a sharded pixel axis would reshard (lax.map reshapes),
            # so the per-device slice itself must satisfy the HBM bound
            if cfg.chunk_px is not None and tile_px / n_mesh > cfg.chunk_px:
                raise ValueError(
                    f"per-device pixel slice {tile_px // n_mesh} exceeds "
                    f"chunk_px={cfg.chunk_px}: reduce tile_size (or raise "
                    "chunk_px if the devices' HBM allows it) — chunking "
                    "cannot be combined with a sharded pixel axis"
                )
            # Each process takes its share of the FULL deterministic tile list
            # (identical on every process), THEN filters resume-done tiles.
            # Sharing the post-resume list instead would race: processes that
            # open the shared manifest at different times would partition
            # different lists, leaving tiles in nobody's share.
            # Elastic mode (lease_batch > 0) replaces the static split
            # with the shared-manifest lease queue: every process sees
            # the FULL list and claims work dynamically, so a slow or
            # dead host strands nothing and late joiners just claim.
            if not cfg.lease_batch:
                share = host_share(share)
            px_sharding = NamedSharding(mesh, PartitionSpec(PIXEL_AXIS, None))
            # _feed_tile pads to feed_px with the QA fill bit, which also
            # covers the divisibility the sharded pixel axis needs
            feed_px = tile_px + (-tile_px) % n_mesh
            chunk = None
        else:
            px_sharding = None
            feed_px = tile_px
            chunk = cfg.chunk_px

        impl_resolved = resolve_impl(cfg.impl)
        fetch_packed = fetchmod.resolve_packed(cfg.fetch_packed)
        upload_packed = feedmod.resolve_packed(cfg.upload_packed)
        if mesh is not None and upload_packed:
            if cfg.upload_packed is True:
                # packed upload places ONE buffer; a sharded mesh needs the
                # per-array NamedSharding placement loop — an explicit force
                # is a config conflict, not something to silently drop
                raise ValueError(
                    "upload_packed=True cannot be combined with a mesh "
                    "(sharded placement is per-array); use upload_packed="
                    "'auto' or False"
                )
            upload_packed = False
        if (
            impl_resolved == "pallas"
            and chunk is not None
            and chunk > PALLAS_BLOCK
            and chunk % PALLAS_BLOCK
        ):
            raise ValueError(
                f"chunk_px={chunk} must be a multiple of {PALLAS_BLOCK} (the "
                "Pallas block) when the resolved impl is 'pallas' — adjust "
                "chunk_px or pass impl='xla'"
            )
        if (
            cfg.telemetry and self.flight is None and cfg.flight
            and cfg.flight_ring_events
        ):
            # standalone --flight run: this run owns its ring (and, in
            # the arming block further down, the sampler + run-end
            # dump).  A serving layer passes the SERVER's shared ring
            # instead — shared rings are mirrored into but never sampled
            # or dumped here.  Created BEFORE any leakable resource
            # (executor pools, store, telemetry): the ring is a plain
            # deque, safe to abandon on any later unwind.
            from land_trendr_tpu.obs.flight import FlightRecorder

            self.flight = FlightRecorder(cfg.flight_ring_events)
            self.owns_flight = True

        manifest = self.manifest = TileManifest(
            cfg.workdir,
            cfg.fingerprint(stack),
            context={"mesh_devices": n_mesh, "impl": impl_resolved},
        )
        done = manifest.open(cfg.resume)
        years = stack.years.astype(np.int32)
        bands = idx.required_bands(cfg.index, cfg.ftv_indices)
        lease_q: "LeaseQueue | None" = None
        if cfg.lease_batch:
            # the elastic work source: tiles are claimed from the shared
            # manifest in lease_batch batches instead of being assigned
            # up front — ``todo`` starts empty and grows as claims win
            lease_q = self.lease_q = LeaseQueue(
                manifest.path,
                [t.tile_id for t in share],
                ttl_s=cfg.lease_ttl_s,
                done0=done,
            )
            spec_by_id = {t.tile_id: t for t in share}
            todo: "list[TileSpec]" = []
            n_todo_start = sum(1 for t in share if t.tile_id not in done)
            n_resume_skipped = len(share) - n_todo_start
        else:
            todo = [t for t in share if t.tile_id not in done]
            n_todo_start = len(todo)
            n_resume_skipped = len(share) - len(todo)
        self.progress.update(
            phase="setup", tiles_total=len(tiles), tiles_todo=n_todo_start
        )

        t_run = time.perf_counter()
        timer = self.timer = StageTimer()

        # robustness state: the quarantine ledger, the packed-fetch failure
        # counter behind graceful demotion, and the stall watchdog (created
        # after telemetry so its stall event has somewhere to go)
        quarantined = self.quarantined
        fetch_failures = 0
        upload_failures = 0
        watchdog: "_StallWatchdog | None" = None

        def _backoff(attempt: int) -> None:
            """Exponential backoff + jitter before re-dispatching a failed
            tile: immediate retry hammers a sick device with the exact work
            that just killed it.  Jitter (±50%) keeps a pod's hosts from
            retrying in lockstep against a shared sick filesystem."""
            if cfg.retry_backoff_s <= 0:
                return
            delay = cfg.retry_backoff_s * 2 ** (attempt - 1) * (0.5 + random.random())
            # cap AFTER jitter: the 30s ceiling is documented as a hard bound
            # (operators size stall_timeout_s against it)
            time.sleep(min(delay, _BACKOFF_CAP_S))

        def _note_fetch_failure() -> None:
            """Count one fetch-wait failure; demote the packed path once the
            run has seen ``_FETCH_DEMOTE_AFTER`` CONSECUTIVE ones (the
            per-product sync path produces byte-identical artifacts, so
            demotion costs throughput, never correctness).  Consecutive, not
            cumulative: a compute fault XLA defers to the async wait, or a
            transient blip recovered hours ago, must not push a 10k-tile run
            over the threshold — a sick link fails back to back."""
            nonlocal fetch_failures
            fetch_failures += 1
            if fetch_failures >= _FETCH_DEMOTE_AFTER and fetcher.packed:
                fetcher.demote()
                log.warning(
                    "packed fetch demoted to per-product sync transfers after "
                    "%d consecutive fetch failures (artifacts unaffected)",
                    fetch_failures,
                )
                if telemetry is not None:
                    telemetry.fetch_demoted(fetch_failures)

        def _note_fetch_ok() -> None:
            """A landed fetch resets the consecutive-failure streak."""
            nonlocal fetch_failures
            fetch_failures = 0

        def _note_upload_failure() -> None:
            """The upload mirror of :func:`_note_fetch_failure`: demote the
            packed host→device path to per-array sync dispatch after
            ``_UPLOAD_DEMOTE_AFTER`` CONSECUTIVE upload-wait failures (the
            per-array path produces byte-identical artifacts, so demotion
            costs throughput, never correctness)."""
            nonlocal upload_failures
            upload_failures += 1
            if upload_failures >= _UPLOAD_DEMOTE_AFTER and uploader.packed:
                uploader.demote()
                log.warning(
                    "packed upload demoted to per-array sync dispatch after "
                    "%d consecutive upload failures (artifacts unaffected)",
                    upload_failures,
                )
                if telemetry is not None:
                    telemetry.upload_demoted(upload_failures)

        def _note_upload_ok() -> None:
            """A landed upload resets the consecutive-failure streak."""
            nonlocal upload_failures
            upload_failures = 0

        def _retry_step(t: TileSpec, attempt: int, err, what: str = "") -> int:
            """One failed attempt's shared bookkeeping — the single copy of
            the retry contract for the ladder, the feed retry, and the
            writer-path fetch retry: log, exhaustion check (``tile_failed``
            emit + :class:`TileRetriesExhausted`), ``tile_retry`` emit,
            watchdog tick, exponential backoff.  Returns the next attempt
            number."""
            # a cancelled job must not keep burning its backoff ladder —
            # checked here so cancellation also lands mid-retry
            self._check_cancel()
            log.warning(
                "tile %d %sattempt %d/%d failed: %s",
                t.tile_id, what, attempt, cfg.max_retries + 1, err,
            )
            if attempt > cfg.max_retries:
                if telemetry is not None:
                    telemetry.tile_failed(t.tile_id, attempt, err)
                exc = TileRetriesExhausted(t.tile_id, attempt, err)
                exc.__cause__ = err
                raise exc
            self.progress["retries"] += 1
            if telemetry is not None:
                telemetry.tile_retry(t.tile_id, attempt, err)
            if watchdog is not None:
                watchdog.tick()  # retrying is progress, not a stall
            _backoff(attempt)
            return attempt + 1

        def _quarantine(t: TileSpec, exc: TileRetriesExhausted) -> None:
            """Record an exhausted tile and keep going — or re-raise when
            quarantine mode is off (the pre-PR abort semantics)."""
            if not cfg.quarantine_tiles:
                raise exc
            quarantined.append(t.tile_id)
            # no straggler verdict for a tile that is GONE — the failure
            # events already tell its story
            self.straggler.drop(t.tile_id)
            self.progress["tiles_quarantined"] = len(quarantined)
            manifest.record_failed(t.tile_id, exc.attempts, str(exc.cause))
            if telemetry is not None:
                telemetry.tile_quarantined(t.tile_id, exc.attempts, str(exc.cause))
            log.error(
                "tile %d quarantined after %d attempts (%s); run continues — "
                "resume will re-attempt it", t.tile_id, exc.attempts, exc.cause,
            )

        def _dispatch(dn, qa):
            """Async-dispatch one tile; returns ``(out, None)`` or ``(None, exc)``."""
            try:
                with timer.stage("dispatch"):
                    faults.check("dispatch")
                    if px_sharding is not None:
                        dn = {
                            k: jax.device_put(v, px_sharding) for k, v in dn.items()
                        }
                        qa = jax.device_put(qa, px_sharding)
                    return (
                        process_tile_dn(
                            years,
                            dn,
                            qa,
                            index=cfg.index,
                            ftv_indices=cfg.ftv_indices,
                            params=cfg.params,
                            scale=cfg.scale,
                            offset=cfg.offset,
                            reject_bits=cfg.reject_bits,
                            chunk=chunk,
                            change_filt=cfg.change_filt,
                            impl=impl_resolved,
                        ),
                        None,
                    )
            except Exception as e:  # exercised via fault-injection tests
                return None, e

        # the fetch subsystem (runtime/fetch.py): packed mode moves every
        # tile's products in ONE device→host transfer issued asynchronously
        # right after the tile's compute completes, so readback of tile i
        # overlaps compute of tile i+1; unpacked mode is the per-product
        # synchronous path, byte-identical artifacts either way
        fetcher = self.fetcher = fetchmod.TileFetcher(cfg, packed=fetch_packed)
        # its upload mirror (runtime/feed.py): packed mode moves every fed
        # tile's band/QA arrays in ONE host→device transfer issued as soon
        # as the feed completes, so tile i+1's upload crosses the link while
        # tile i computes; sync mode is the per-array dispatch placement,
        # byte-identical artifacts either way
        uploader = self.uploader = feedmod.TileUploader(cfg, packed=upload_packed)

        def _write_job(t: TileSpec, handle, dt: float) -> tuple[int, int]:
            # StageTimer accumulation is locked, so concurrent writer threads
            # may share the "write" key; with write_workers > 1 the summed
            # write_s can legitimately exceed wall time.
            with timer.stage("write"):
                # packed: pure host unpack of already-landed bytes; unpacked:
                # the per-product synchronous fetch (the pre-packing path).
                # Either way model_valid rides the same payload, so the
                # fit-rate metadata never costs a separate blocking device
                # fetch (review r5 finding: --products without model_valid
                # crashed every tile write; its fix cost one extra transfer
                # per tile, now folded away).
                # The per-product handle re-fetches from its retained device
                # outputs, so a transient fetch fault HERE (the demoted /
                # fallback path, where transfers run in writer threads) gets
                # the same retry budget as the ladder instead of aborting the
                # run; persistent failure still fails fast via the writer's
                # backpressure collection.
                attempt = 1
                while True:
                    try:
                        arrays, fit = handle.tile_arrays(t)
                        break
                    except Exception as e:
                        try:
                            attempt = _retry_step(
                                t, attempt, e, what="writer-fetch "
                            )
                        except TileRetriesExhausted as exc:
                            # same quarantine contract as the ladder (one bad
                            # tile never costs the other 10k — also on the
                            # per-product / post-demotion path): record +
                            # skip, or re-raise through the writer future →
                            # _collect_write → run abort → CLI exit 3
                            _quarantine(t, exc)
                            return 0, 0
                px = t.h * t.w
                meta = {
                    "y0": t.y0,
                    "x0": t.x0,
                    "h": t.h,
                    "w": t.w,
                    # elastic runs stamp the done record with its writer:
                    # the FIRST done record's owner is the race winner —
                    # how speculative wins are attributed (and how the
                    # soaks audit who completed what)
                    **(
                        {"owner": self.lease_q.owner}
                        if self.lease_q is not None
                        else {}
                    ),
                    # dispatch + result-wait wall time: device compute + any
                    # transfer stalls; host work overlapped by the pipeline is
                    # excluded (an estimate, not a device-profile number)
                    "px_per_s": round(tile_px / dt, 1),
                    "no_fit_rate": round(1.0 - fit / px, 4),
                }
                manifest.record(
                    t.tile_id, arrays, meta, compress=cfg.manifest_compress
                )
            if self.on_tile_durable is not None:
                # cross-job demux (serve/batching): the artifact is durable;
                # a consumer failure is ITS problem, never this tile's
                try:
                    self.on_tile_durable(t, arrays, meta)
                except Exception:
                    log.warning(
                        "on_tile_durable callback failed for tile %d",
                        t.tile_id, exc_info=True,
                    )
            log.info(
                "tile %d (%d,%d %dx%d): %.2fM px/s, no-fit %.1f%%",
                t.tile_id, t.y0, t.x0, t.h, t.w,
                meta["px_per_s"] / 1e6, 100 * meta["no_fit_rate"],
            )
            return px, fit

        writer = ThreadPoolExecutor(
            max_workers=cfg.write_workers, thread_name_prefix="lt-writer"
        )
        pending_writes: deque = deque()  # bounded at write_workers in flight
        pending_fetches: deque = deque()  # bounded at fetch_depth in flight
        n_px = 0
        n_fit = 0
        n_done = 0

        def _collect_write(fut) -> None:
            """Backpressure + fail-fast: re-raises writer errors at the next tile."""
            nonlocal n_px, n_fit
            px, fit = fut.result()
            if watchdog is not None:
                watchdog.tick()
            n_px += px
            n_fit += fit

        def _drain_writes(limit: int) -> None:
            """Collect oldest write jobs until at most ``limit`` stay in flight."""
            while len(pending_writes) > limit:
                _collect_write(pending_writes.popleft())

        def _submit_write(t: TileSpec, handle, dt: float) -> None:
            _drain_writes(cfg.write_workers - 1)
            pending_writes.append(writer.submit(_write_job, t, handle, dt))

        def _retry_ladder(t: TileSpec, dn, qa, attempt: int, err):
            """Synchronous tile retry from the retained inputs.

            Shared by ``_finish`` (dispatch / device-wait / pack failures) and
            ``_drain_fetches`` (a device error surfacing through an in-flight
            async fetch): re-dispatches until the tile completes THROUGH a
            landed fetch — the fault already broke the pipeline, so the
            re-fetch is resolved synchronously before pipelining resumes.
            Attempts are spaced by :func:`_backoff` (exponential + jitter) so
            a sick device is not re-hammered immediately.  Returns
            ``(handle, dt, attempt)`` or raises :class:`TileRetriesExhausted`
            after ``max_retries``.
            """
            while True:
                attempt = _retry_step(t, attempt, err)  # raises at exhaustion
                if telemetry is not None:
                    telemetry.tile_start(t.tile_id, attempt=attempt)
                # fresh attempt, fresh in-flight clock: the ladder's
                # backoff already separates attempts, so the straggler
                # verdict judges this attempt, not the whole ladder
                self.straggler.start(t.tile_id, attempt)
                t0 = time.perf_counter()
                out, err = _dispatch(dn, qa)
                if err is not None:
                    continue
                try:
                    with timer.stage("compute"):
                        faults.check("compute.wait")
                        # the retry ladder's sanctioned compute-wait: the fault
                        # already broke the pipeline, nothing left to overlap
                        jax.block_until_ready(out)  # lt: noqa[LT002]
                    dt = time.perf_counter() - t0
                except Exception as e:  # device-side failure surfaces here
                    err = e
                    continue
                try:
                    t0_fx = time.perf_counter()
                    with timer.stage("fetch"):
                        handle = fetcher.start(out)
                        handle.wait()
                    _note_fetch_ok()
                    if telemetry is not None:
                        telemetry.span(
                            "fetch", t.tile_id, t0_fx, time.perf_counter(),
                            attempt=attempt,
                        )
                    return handle, dt, attempt
                except Exception as e:  # transfer failure: counts toward
                    _note_fetch_failure()  # packed-path demotion
                    err = e

        def _tile_completed(t: TileSpec, dt: float) -> None:
            """Emit tile_done and count the tile.

            On the packed path this fires only once the async fetch has
            LANDED — a tile whose fetch later exhausts its retries appears in
            the stream as a failure only, never as done-then-failed.  The
            per-product fallback keeps its historical semantics: tile_done at
            compute completion, with the synchronous fetches in the write job
            behind it — so on THAT path a quarantined writer-fetch tile shows
            tile_done followed by tile_quarantined (done = device result
            completed; ``write_done`` remains the stream's only durability
            signal), and a non-quarantine error aborts the run via the
            writer's fail-fast, exactly as before this subsystem existed."""
            nonlocal n_done
            n_done += 1
            self.progress.update(
                tiles_done=n_done,
                feed_backlog=len(pending_feeds),
                write_backlog=len(pending_writes),
                fetch_backlog=len(pending_fetches),
                upload_backlog=len(pending_uploads),
            )
            # completion verdict + an in-flight sweep of the tiles still
            # behind this one (the sampler thread also sweeps on flight
            # runs; the detector flags each tile at most once)
            self.straggler.finish(t.tile_id)
            self.straggler.scan()
            if watchdog is not None:
                watchdog.tick()
            if telemetry is not None:
                telemetry.tile_done(
                    t.tile_id,
                    t.h * t.w,
                    dt,
                    feed_backlog=len(pending_feeds),
                    write_backlog=len(pending_writes),
                    device_bytes_in_use=_device_live_bytes(),
                    fetch_backlog=len(pending_fetches),
                )

        def _drain_fetches(limit: int) -> None:
            """Collect oldest in-flight fetches until at most ``limit`` remain.

            The wait here is where the packed transfer's landing is awaited —
            overlapped with the newer tiles' compute already dispatched behind
            it.  A device error surfacing through the async fetch re-enters
            the retry ladder; the fed inputs ride the backlog entry for
            exactly that.  Landed tiles hand off to the writer pool.
            """
            while len(pending_fetches) > limit:
                t, handle, dn, qa, dt, attempt = pending_fetches.popleft()
                try:
                    t0_fx = time.perf_counter()
                    with timer.stage("fetch"):
                        handle.wait()
                    _note_fetch_ok()
                    if telemetry is not None:
                        # the BLOCKING remainder of the async fetch — the
                        # host-experienced cost after overlap, which is
                        # what critical-path attribution decomposes
                        telemetry.span(
                            "fetch", t.tile_id, t0_fx, time.perf_counter(),
                            attempt=attempt,
                        )
                except Exception as err:
                    _note_fetch_failure()
                    try:
                        handle, dt, attempt = _retry_ladder(
                            t, dn, qa, attempt, err
                        )
                    except TileRetriesExhausted as e:
                        _quarantine(t, e)
                        continue
                _tile_completed(t, dt)
                _submit_write(t, handle, dt)

        def _finish(pending) -> None:
            """Await one in-flight tile (retrying on failure), issue its async
            fetch, and queue writes as the bounded fetch backlog drains.  The
            pending tuple's attempt is > 1 when the tile's FEED already spent
            retries — one budget per tile across phases."""
            t, out, err, dn, qa, dt_dispatch, attempt = pending
            handle = None
            if err is None:
                try:
                    t0 = time.perf_counter()
                    with timer.stage("compute"):
                        faults.check("compute.wait")
                        # THE sanctioned compute-wait of the pipeline (tile
                        # i+1 is already dispatched behind it)
                        jax.block_until_ready(out)  # lt: noqa[LT002]
                    dt = dt_dispatch + (time.perf_counter() - t0)
                    if watchdog is not None:
                        watchdog.tick()
                    with timer.stage("fetch"):
                        # async: the packed buffer lands while the next tiles
                        # compute; the per-product fallback defers its
                        # (synchronous) transfers to the writer pool instead
                        handle = fetcher.start(out)
                except Exception as e:  # device-side failure surfaces here
                    err = e
            if err is not None:
                try:
                    handle, dt, attempt = _retry_ladder(t, dn, qa, attempt, err)
                except TileRetriesExhausted as e:
                    _quarantine(t, e)
                    return
            if not fetcher.packed:
                # per-product fallback: the pre-packing flow exactly — the
                # write job runs the synchronous fetches itself, nothing to
                # overlap, no retained inputs beyond this call
                _tile_completed(t, dt)
                _submit_write(t, handle, dt)
                return
            # the retained (dn, qa) ride the backlog for the retry ladder: a
            # device error surfacing through the in-flight fetch re-dispatches
            # from them.  Bounded at fetch_depth entries.
            pending_fetches.append((t, handle, dn, qa, dt, attempt))
            fetcher.note_backlog(len(pending_fetches))
            _drain_fetches(cfg.fetch_depth - 1)

        # feed pool, mirroring the writer pool on the input side (VERDICT r3
        # next-round item #3): ``cfg.feed_workers`` threads run the native
        # gather for UPCOMING tiles while the current tile computes, keeping a
        # bounded prefetch queue of ``feed_workers + 1`` fed tiles.  The
        # native gather releases the GIL (threaded C++), so workers scale to
        # real cores; HOSTPATH_r03.json's budget (4.1M px/s/core ⇒ ~2.4 cores
        # at the 10M px/s north star) becomes ``feed_workers=3``.  Like
        # ``write_s``, overlapped ``feed_s`` can exceed wall time.  Host
        # memory stays bounded: at most ``feed_workers + 1`` fed inputs plus
        # ``write_workers + 2`` finished tiles are live at once.
        try:
            feeder = ThreadPoolExecutor(
                max_workers=cfg.feed_workers, thread_name_prefix="lt-feeder"
            )
        except BaseException:
            # feed_workers<=0 is a config error surfacing HERE: the
            # already-built writer pool must not outlive the failed run
            writer.shutdown(wait=False, cancel_futures=True)
            raise
        pending_feeds: deque = deque()  # (tile, future), consumed in order

        def _feed_job(t: TileSpec, readahead: "TileSpec | None" = None):
            """Returns ``(dn, qa, (t0, t1))`` — the fed arrays plus the
            feed span's monotonic bounds.  The feed span is EMITTED by
            the consumer on the driver thread, not here: a feeder thread
            still finishing through an abort unwind must never append
            events behind the scope's terminal ``run_done``."""
            t0_span = time.perf_counter()
            with timer.stage("feed"):
                faults.check("feed")  # injection seam: transient feed I/O
                fed = _feed_tile(stack, t, feed_px, bands)
            t1_span = time.perf_counter()
            if readahead is not None:
                # fire-and-forget: hint the next PLANNED tile (one past the
                # feed queue) so its block decode overlaps the current tiles'
                # device wait — lazy file-backed cubes only; eager ndarray
                # stacks have no compressed blocks to prefetch
                _prefetch_tile(stack, readahead, bands)
            return (*fed, (t0_span, t1_span))

        def _refeed(t: TileSpec, err: BaseException):
            """Synchronous feed retry: a transient stack-read error (NFS blip,
            decode hiccup) re-enters the same per-tile retry budget as device
            faults instead of aborting the whole run.  Returns ``(dn, qa,
            feed_span, attempt)`` — the attempt number the tile continues
            from, so its ``tile_start`` and any later dispatch retries share
            ONE per-tile budget — or ``None`` when the tile was quarantined;
            an exhausted budget raises :class:`TileRetriesExhausted`
            (chaining the original feed error) exactly like the device-fault
            ladder, so the CLI's exit-3 contract covers every per-tile
            failure class.
            """
            attempt = 1
            while True:
                try:
                    attempt = _retry_step(t, attempt, err, what="feed ")
                except TileRetriesExhausted as exc:
                    _quarantine(t, exc)
                    return None
                try:
                    return (*_feed_job(t), attempt)
                except Exception as e:
                    err = e

        # the feed-path decode subsystem (process-wide, like GDAL's block
        # cache): decoded-block LRU + shared decode pool + readahead — pure
        # acceleration of the windowed lazy feed, byte-identical either way.
        # With ingest_store_mb the decoded blocks additionally spill to the
        # persistent on-disk store, so a rerun over the same stacks skips
        # TIFF decode entirely ("ingest once, serve many").  A serving
        # layer instead passes its long-lived store via ``shared_store``:
        # the run uses it but never closes it, and the store's owner (the
        # server) owns the process-wide cache configuration too.
        store = self.shared_store
        owns_store = store is None and bool(cfg.ingest_store_mb)

        def _release_setup() -> None:
            """Reverse-order unwind for a failure between resource
            acquisition and the owning try/finally below: the executor
            pools and an OWNED store (close + process-cache detach) must
            not outlive a run whose telemetry/fault arming failed."""
            feeder.shutdown(wait=False, cancel_futures=True)
            writer.shutdown(wait=False, cancel_futures=True)
            if store is not None and owns_store:
                try:
                    store.close()
                except Exception as exc:
                    log.error(
                        "ingest-store close failed during setup unwind: %s",
                        exc,
                    )
                blockcache.detach_store(store)

        try:
            if owns_store:
                from land_trendr_tpu.io.blockstore import BlockStore

                store = BlockStore(
                    cfg.ingest_store_dir
                    or os.path.join(cfg.workdir, "ingest_store"),
                    budget_bytes=cfg.ingest_store_mb << 20,
                )
            if not self.shared_cache:
                blockcache.configure(
                    budget_bytes=cfg.feed_cache_mb << 20,
                    workers=cfg.decode_workers,
                    store=store,
                )
            feed_cache_base = blockcache.stats_snapshot()
            store_base = (
                store.stats_snapshot() if store is not None else None
            )
        except BaseException:
            _release_setup()
            raise
        self.store = store

        # constructed LAST, immediately before the try/finally that owns its
        # shutdown: an exception anywhere between construction and that
        # finally would leak the exporter thread / metrics port / event fd
        # and leave a stream with no terminal run_done
        telemetry = None
        if cfg.telemetry:
            from land_trendr_tpu.obs import Telemetry
            from land_trendr_tpu.obs import publish as obs_publish

            try:
                # per-process port fan-out (port + process_index, like
                # the per-process event/metrics FILE naming): a same-host
                # pod would otherwise have every process after the first
                # die binding the one configured port.  0 (ephemeral)
                # needs no offset; each process's bound port lands in its
                # own run summary.
                metrics_port = cfg.metrics_port
                if metrics_port:
                    metrics_port += jax.process_index()
                telemetry = self.telemetry = Telemetry(
                    cfg.workdir,
                    fingerprint=manifest.fingerprint,
                    process_index=jax.process_index(),
                    process_count=jax.process_count(),
                    metrics_port=metrics_port,
                    metrics_host=cfg.metrics_host,
                    metrics_interval_s=cfg.metrics_interval_s,
                    # serve mode: the job id (and the fleet-wide trace
                    # id) rides EVERY event of this run's scope, so a
                    # fleet-wide fold can attribute tile traffic to the
                    # request that caused it and lt_request can join
                    # the run scope to the router's request spans
                    job_id=self.job_id,
                    trace_id=self.trace_id,
                    flight=self.flight,
                    # fleet publish: the per-process snapshot feed the
                    # pod aggregate folds (lifecycle owned by the
                    # telemetry bundle — stopped in close(), success
                    # and abort paths alike)
                    publish_dir=(
                        (
                            cfg.telemetry_dir
                            or obs_publish.telemetry_dir(cfg.workdir)
                        )
                        if cfg.publish
                        else None
                    ),
                    publish_interval_s=cfg.publish_interval_s,
                    publish_probes=self._publish_probes,
                )
            except BaseException:
                # e.g. a busy --metrics-port: Telemetry cleans up its own
                # half-built state; the pools and owned store are ours
                _release_setup()
                raise
            try:
                # the manifest reports write_done events once each tile is
                # durable
                manifest.telemetry = telemetry
                rs_rec = telemetry.run_start(
                    fingerprint=manifest.fingerprint,
                    process_index=jax.process_index(),
                    process_count=jax.process_count(),
                    tiles_total=len(tiles),
                    tiles_todo=n_todo_start,
                    tiles_skipped_resume=n_resume_skipped,
                    mesh_devices=n_mesh,
                    impl=impl_resolved,
                    # the POD-WIDE correlation id, agreed through the
                    # shared manifest header (one process stamps it,
                    # every process reads it back) — all N per-host
                    # streams of one pod run carry the same run_id.
                    # Pre-run_id manifests leave it None: run_start then
                    # stamps a per-process fallback id
                    **(
                        {"run_id": manifest.run_id}
                        if manifest.run_id is not None
                        else {}
                    ),
                )
                # mirror the scope's clock anchor into the shared
                # manifest (pod-trace assembly can then align a host
                # whose event file was lost); best-effort — a full-disk
                # manifest append must not kill a run telemetry survived
                try:
                    manifest.record_clock_anchor(
                        run_id=rs_rec.get("run_id", ""),
                        host=rs_rec.get("host", ""),
                        process_index=jax.process_index(),
                        anchor_wall=rs_rec.get("anchor_wall", rs_rec["t_wall"]),
                        anchor_mono=rs_rec.get("anchor_mono", rs_rec["t_mono"]),
                    )
                except OSError as exc:
                    log.warning("manifest clock-anchor append failed: %s", exc)
                if self.tune_info is not None:
                    # which profile this run's "auto" knobs resolved
                    # through (probes=0 always: resolution never probes)
                    telemetry.tune_profile(**self.tune_info)
            except BaseException:
                # a failed run_start emit surfaces before the try/finally
                # below owns shutdown — unwind here or the exporter thread /
                # metrics port / event fd leak into the caller's process
                manifest.telemetry = None
                try:
                    telemetry.close()
                finally:
                    _release_setup()
                raise

        # fault injection + stall watchdog + flight sampler are armed AFTER
        # telemetry exists (their events need somewhere to go) and disarmed
        # in the finally; a failure arming them must unwind telemetry like
        # run_start's guard
        fault_plan = None
        sampler = None
        try:
            if cfg.fault_schedule:
                if faults.active() is not None:
                    # a serving layer arms ONE process-wide plan for all
                    # its jobs; a job additionally carrying its own
                    # schedule is a config conflict, not something to
                    # silently clobber
                    raise ValueError(
                        "fault_schedule set while another fault plan is "
                        "already active in this process (a server-armed "
                        "plan is shared by every run; per-run schedules "
                        "need an idle process)"
                    )
                fault_plan = self.fault_plan = faults.activate(
                    faults.parse_schedule(cfg.fault_schedule)
                )
                if telemetry is not None:
                    faults.set_observer(telemetry.fault_injected)
                log.warning(
                    "fault injection ACTIVE (%s) — this is a test/soak run",
                    cfg.fault_schedule,
                )
            if cfg.stall_timeout_s is not None:
                if threading.current_thread() is not threading.main_thread():
                    # the watchdog aborts via interrupt_main: armed from a
                    # worker thread it would interrupt an UNRELATED main
                    # thread and hard-exit the whole host process on stall
                    raise ValueError(
                        "stall_timeout_s requires run_stack on the process "
                        "main thread (the watchdog aborts via "
                        "interrupt_main); run without the watchdog or move "
                        "the run to the main thread"
                    )

                def _on_stall(idle_s: float) -> None:
                    if telemetry is not None:
                        telemetry.stall(idle_s, cfg.stall_timeout_s)

                watchdog = self.watchdog = _StallWatchdog(
                    cfg.stall_timeout_s, _on_stall
                ).start()
            if self.owns_flight:
                # the resource sampler emits flight_sample events through
                # the normal event log (file + ring alike), started only
                # AFTER run_start so the stream still opens its scope
                from land_trendr_tpu.obs.flight import ResourceSampler

                sampler = self.sampler = ResourceSampler(
                    telemetry.events.emit,
                    cfg.sampler_interval_s,
                    probes=self._sampler_probes,
                ).start()
        except BaseException:
            # telescoped: each step may itself raise (LT008 found the
            # skip), so the later steps ride finallys — the event fd and
            # the owned store must close even if the fault disarm fails
            try:
                if sampler is not None:
                    sampler.stop()
            finally:
                try:
                    if watchdog is not None:
                        # armed a step above: a sampler-start failure
                        # must not leave the watchdog ticking toward an
                        # interrupt of a run that never started
                        watchdog.stop()
                finally:
                    try:
                        if fault_plan is not None:
                            faults.set_observer(None)
                            faults.deactivate()
                    finally:
                        try:
                            if telemetry is not None:
                                manifest.telemetry = None
                                telemetry.close()
                        finally:
                            _release_setup()
            raise

        # readahead targets ride the feed submissions: the tile fed at index
        # i hints the tile at i + feed_workers + 1 — the first one past the
        # bounded feed queue, so its decode lands in the cache exactly when
        # the feed pool would otherwise start it cold
        ra_depth = cfg.feed_workers + 1
        readahead_on = cfg.feed_readahead and cfg.feed_cache_mb > 0

        def _submit_feed(i: int) -> None:
            ra = todo[i + ra_depth] if readahead_on and i + ra_depth < len(todo) else None
            pending_feeds.append((todo[i], feeder.submit(_feed_job, todo[i], ra)))

        pending_uploads: deque = deque()  # bounded at upload_depth in flight

        def _pump_uploads() -> None:
            """Resolve fed tiles and issue their uploads until the bounded
            in-flight window is full (or the feed queue is empty).

            On the packed path this is the double-buffering step: up to
            ``cfg.upload_depth`` packed buffers cross the link while the
            tile ahead of them computes.  On the per-array path the window
            is 1 — the handle is a pass-through and a deeper queue would
            only hold extra fed inputs in host memory for nothing.  A feed
            failure re-enters the per-tile retry budget exactly as before
            (``_refeed``); a quarantined feed never enters the queue.
            """
            nonlocal next_i
            depth = cfg.upload_depth if uploader.packed else 1
            while pending_feeds and len(pending_uploads) < depth:
                t, fut = pending_feeds.popleft()
                # top up the queue BEFORE resolving this feed: if it failed,
                # the synchronous retry below backs off for seconds — the
                # feed pool should keep decoding tiles i+1.. meanwhile
                if next_i < len(todo):
                    _submit_feed(next_i)
                    next_i += 1
                attempt0 = 1
                try:
                    dn, qa, feed_span = fut.result()
                except Exception as e:
                    # transient feed I/O enters the retry budget (sync,
                    # with backoff) instead of aborting the whole run
                    fed = _refeed(t, e)
                    if fed is None:
                        continue  # tile quarantined; the rest of the run goes on
                    dn, qa, feed_span, attempt0 = fed
                if telemetry is not None:
                    # emitted HERE (driver thread) from the feeder's
                    # recorded bounds — see _feed_job's ordering note
                    telemetry.span(
                        "feed", t.tile_id, *feed_span, attempt=attempt0
                    )
                if watchdog is not None:
                    watchdog.tick()
                with timer.stage("upload"):
                    try:
                        handle = uploader.start(dn, qa)
                    except Exception as e:
                        # an ISSUE-time upload failure (device_put raising
                        # eagerly, pack allocation) must not abort the run:
                        # it counts toward demotion like a wait-side fault,
                        # and this tile falls back to the per-array handle —
                        # the dispatch path transfers (and retries) as before
                        _note_upload_failure()
                        log.warning(
                            "tile %d packed-upload issue failed (%s); "
                            "per-array dispatch for this tile", t.tile_id, e,
                        )
                        handle = feedmod.SyncUpload(uploader, dn, qa)
                pending_uploads.append((t, handle, dn, qa, attempt0))
                uploader.note_backlog(len(pending_uploads))

        def _warm_programs() -> dict:
            # serve-mode warm program cache: an explicit admission index
            # over JAX's in-process executable cache.  On a MISS the run
            # pays its compile NOW, against one fully-masked dummy tile
            # pushed through the exact upload → dispatch → fetch program
            # chain (same shapes, dtypes and static arguments as every
            # real tile, so the executables JAX caches here are the ones
            # the tiles reuse); on a HIT the dummy is skipped entirely —
            # a warm job runs zero compiles.  The dummy tile rides the
            # normal upload/fetch transfer stats (one phantom tile on
            # miss runs) and, on injection runs, consumes one invocation
            # index at each driver seam it crosses.
            key = self.programs.key_for(
                fingerprint=manifest.fingerprint,
                backend=jax.default_backend(),
                impl=impl_resolved,
                mesh_devices=n_mesh,
                feed_px=int(feed_px),
                ny=int(stack.n_years),
                chunk=chunk,
                fetch_packed=bool(fetcher.packed),
                upload_packed=bool(uploader.packed),
                dtypes={
                    name: str(np.dtype(stack.dn_bands[name].dtype))
                    for name in bands
                } | {"qa": str(np.dtype(stack.qa.dtype))},
            )
            t0_warm = time.perf_counter()
            hit = self.programs.admit(key)
            probe_ok = True
            if not hit:
                try:
                    ny = int(stack.n_years)
                    dummy_dn = {
                        name: np.zeros(
                            (feed_px, ny), dtype=stack.dn_bands[name].dtype
                        )
                        for name in bands
                    }
                    # QA fill bit set everywhere: the kernel masks every
                    # pixel, so the warm tile costs compile + ~no compute
                    dummy_qa = np.full((feed_px, ny), 1, dtype=stack.qa.dtype)
                    wh = uploader.start(dummy_dn, dummy_qa)
                    w_dn, w_qa = wh.arrays()
                    w_out, w_err = _dispatch(w_dn, w_qa)
                    if w_err is not None:
                        raise w_err
                    # warm compile wait: nothing is pipelined yet, the
                    # whole point is to pay the compile before tile 0
                    jax.block_until_ready(w_out)  # lt: noqa[LT002]
                    fetcher.start(w_out).wait()
                    _note_fetch_ok()
                except Exception as e:
                    # a failed warm probe is not a failed run: the first
                    # real tile compiles inline (and retries) as always.
                    # It is also NOT a compile — record(ok=False) leaves
                    # the key unregistered so the next same-key run
                    # probes again instead of being falsely admitted warm
                    probe_ok = False
                    log.warning(
                        "program warm probe failed (%s); first tile "
                        "compiles inline", e,
                    )
            if watchdog is not None:
                watchdog.tick()  # the probe compile was progress
            compile_s = 0.0 if hit else time.perf_counter() - t0_warm
            self.programs.record(
                key, hit=hit, compile_s=compile_s, ok=probe_ok
            )
            return {
                "hits": int(hit),
                "misses": int(not hit),
                "compile_s": round(compile_s, 6),
            }

        def _prime_feeds() -> None:
            """Fill the bounded feed queue from ``todo`` — the shared
            priming step for run start and for elastic refills (the
            pipeline must restart itself after running dry)."""
            nonlocal next_i
            while next_i < len(todo) and len(pending_feeds) < ra_depth:
                _submit_feed(next_i)
                next_i += 1

        def _refill_work() -> int:
            """Elastic mode: claim another lease batch and feed the won
            tiles.  Returns the number won.  Acquisition failures (the
            lease.acquire / lease.steal fault seams, a shared-FS blip)
            are logged and retried next cycle — a filesystem hiccup must
            not kill a run the artifact path would have survived."""
            try:
                won = lease_q.acquire(
                    cfg.lease_batch, speculate=cfg.speculate
                )
            except Exception as e:
                log.warning(
                    "lease acquisition failed (%s); retrying next cycle", e
                )
                if watchdog is not None:
                    watchdog.tick()  # a failed claim is still liveness
                return 0
            for tile_id, mode, lease in won:
                todo.append(spec_by_id[tile_id])
                self.progress["tiles_leased"] += 1
                if mode == "steal":
                    self.progress["tiles_stolen"] += 1
                    log.info(
                        "stole tile %d (lease expired; claimed gen %d)",
                        tile_id, lease.gen,
                    )
                    if telemetry is not None:
                        telemetry.lease_stolen(
                            tile_id, lease.gen, owner=lease_q.owner,
                            from_owner=lease.prev_owner,
                        )
                elif mode == "spec":
                    self.progress["tiles_speculated"] += 1
                    log.info(
                        "speculatively re-leased straggler tile %d "
                        "(gen %d; first durable write wins)",
                        tile_id, lease.gen,
                    )
                    if telemetry is not None:
                        telemetry.tile_speculated(
                            tile_id, lease.gen, owner=lease_q.owner,
                            from_owner=lease.prev_owner,
                        )
                elif telemetry is not None:
                    telemetry.tile_leased(
                        tile_id, lease.gen, owner=lease_q.owner
                    )
            if won:
                _prime_feeds()
            return len(won)

        def _lease_idle_wait() -> None:
            """Nothing claimable, yet undone tiles remain on peers: wait
            one bounded beat.  Deliberate idleness is progress for the
            watchdog (waiting out a live peer's lease is not a stall);
            the cancel event still lands within a beat via the loop's
            ``_check_cancel``."""
            if watchdog is not None:
                watchdog.tick()
            time.sleep(min(0.5, max(cfg.lease_ttl_s / 8.0, 0.05)))

        program_stats = None
        run_ok = False
        try:
            if self.programs is not None:
                # inside the guarded try: a Ctrl-C / stall interrupt
                # landing mid-compile unwinds through the normal abort
                # path (run_done "aborted", pool shutdown, plan disarm)
                # exactly like a tile-0 compile did before this existed
                self.progress["phase"] = "warmup"
                program_stats = self.program_stats = _warm_programs()
            self.progress["phase"] = "pipeline"
            next_i = 0
            if lease_q is not None:
                _refill_work()
            _prime_feeds()
            pending = None
            while True:
                self._check_cancel()
                if lease_q is not None:
                    lease_q.renew()
                    if len(todo) - next_i <= ra_depth:
                        _refill_work()
                _pump_uploads()
                if not pending_uploads:
                    if lease_q is None:
                        break  # feeds exhausted (or remainder quarantined)
                    # elastic: the local pipeline ran dry — resolve the
                    # in-flight tail first (its done records are what
                    # retire our held leases), then claim / steal /
                    # speculate, and only then wait on live peers
                    if pending is not None:
                        _finish(pending)
                        pending = None
                        continue
                    if pending_fetches:
                        _drain_fetches(0)
                        continue
                    if pending_writes:
                        _drain_writes(0)
                        continue
                    if _refill_work():
                        continue
                    try:
                        complete = lease_q.run_complete()
                    except Exception as e:
                        # same contract as _refill_work: a shared-FS blip
                        # while polling completion must not abort a run
                        # the artifact path would have survived
                        log.warning(
                            "lease completion poll failed (%s); retrying "
                            "next cycle", e,
                        )
                        complete = False
                    if complete:
                        break
                    _lease_idle_wait()
                    continue
                t, handle, dn, qa, attempt0 = pending_uploads.popleft()
                if telemetry is not None:
                    # attempt0 > 1 after feed retries: the stream's
                    # tile_retry(1..n) → tile_start(n+1) stays coherent, and
                    # dispatch retries continue the SAME per-tile budget
                    telemetry.tile_start(t.tile_id, attempt=attempt0)
                # the tile's in-flight clock starts here — dispatch is the
                # point a straggler verdict is measured from
                self.straggler.start(t.tile_id, attempt0)
                t0 = time.perf_counter()
                out = err = None
                try:
                    with timer.stage("upload"):
                        # packed: wait out the landing (short — it has been
                        # crossing the link while earlier tiles computed) and
                        # run the device unpack; sync: a pass-through of the
                        # host arrays, transferred at dispatch as always
                        u_dn, u_qa = handle.arrays()
                    if handle.packed:
                        _note_upload_ok()
                    if telemetry is not None:
                        # the BLOCKING remainder of the async upload (the
                        # landing wait + device unpack the driver paid)
                        telemetry.span(
                            "upload", t.tile_id, t0, time.perf_counter(),
                            attempt=attempt0,
                        )
                except Exception as e:
                    # an upload error surfacing through the async wait enters
                    # the SAME retry ladder as a dispatch fault — the ladder
                    # re-dispatches from the retained HOST inputs on the
                    # per-array path, so a sick link cannot wedge the tile
                    if handle.packed:
                        _note_upload_failure()
                    err = e
                if err is None:
                    out, err = _dispatch(u_dn, u_qa)
                dt_dispatch = time.perf_counter() - t0
                if pending is not None:
                    _finish(pending)
                    pending = None
                if err is not None:
                    # synchronous dispatch failure: resolve (retry or abort) now
                    # rather than dispatching further tiles behind a known fault
                    _finish((t, out, err, dn, qa, dt_dispatch, attempt0))
                else:
                    pending = (t, out, err, dn, qa, dt_dispatch, attempt0)
            if pending is not None:
                _finish(pending)
            self.progress["phase"] = "drain"
            _drain_fetches(0)
            _drain_writes(0)
            run_ok = True
        except KeyboardInterrupt:
            if watchdog is not None and watchdog.stalled:
                # the watchdog's interrupt_main landed: convert it to the
                # documented abort (CLI exit 4) — a real Ctrl-C propagates
                raise StallError(
                    f"run stalled: no tile progress for over "
                    f"{cfg.stall_timeout_s}s (stall watchdog abort)"
                ) from None
            raise
        finally:
            try:
                self.progress["phase"] = "done" if run_ok else "aborted"
                if sampler is not None:
                    # before the terminal rollups: a sample emitted into a
                    # closing log is a lost beat, not an error — but the
                    # stream reads better when run_done is the scope's tail
                    sampler.stop()
                # NOTE: the watchdog stays armed through this whole unwind — a
                # writer thread hung in a native transfer would otherwise block
                # writer.shutdown(wait=True) forever with the hard-exit grace
                # clock already cancelled, reinstating exactly the infinite hang
                # the watchdog exists to prevent.  A stall firing mid-unwind
                # ends, at worst, in the documented os._exit(4).
                feeder.shutdown(wait=False, cancel_futures=True)
                writer.shutdown(wait=True)
                for fut in pending_writes:
                    if (exc := fut.exception()):
                        # a compute abort is already propagating; surface, don't mask
                        log.error("tile write also failed during abort: %s", exc)
                    else:
                        # writes the shutdown drain completed are real durable
                        # tiles: fold them in so the aborted run_done's pixels /
                        # fit_rate stay consistent with its own tiles_done
                        # (success path drained everything before run_ok)
                        px, fit = fut.result()
                        n_px += px
                        n_fit += fit
                if store is not None and owns_store:
                    # (a shared_store is the server's: it outlives this run
                    # by design and only its owner closes it)
                    # persist what this run ingested, abort path included —
                    # the next run's warm start is the whole point.  close()
                    # flushes AND releases the segment mmaps/fds, and the
                    # detach drops the process-global reference so nothing
                    # writes into a store whose owning run has ended (the
                    # RAM tier persists process-wide as before; stats reads
                    # below still work on a closed store).  An error here
                    # (the same full disk that killed the run) must not mask
                    # the propagating failure.
                    try:
                        store.close()
                    except Exception as exc:
                        log.error("ingest-store flush/close failed: %s", exc)
                    blockcache.detach_store(store)
                if lease_q is not None and not run_ok:
                    # relinquish unfinished claims so siblings may claim
                    # NOW instead of waiting out the TTL.  Best-effort
                    # and AFTER the writer drain (tiles whose writes the
                    # drain completed are done, not released); a
                    # SIGKILLed host never runs this — the TTL is the
                    # backstop that keeps its tiles stealable.
                    try:
                        n_rel = lease_q.release_held("aborted")
                        if n_rel:
                            log.warning(
                                "released %d unfinished tile lease(s) on "
                                "abort; siblings may claim them immediately",
                                n_rel,
                            )
                    except Exception as exc:
                        log.error("abort-path lease release failed: %s", exc)
                if fault_plan is not None and not run_ok:
                    # abort path: disarm here (after the writer drain, so seam
                    # indices stay deterministic through the last record()).  On
                    # success the plan stays active through the multihost merge —
                    # the merge.peer seam fires there — and is disarmed at the
                    # end of run_stack.
                    faults.set_observer(None)
                    faults.deactivate()
                if telemetry is not None and not run_ok:
                    # abort visibility: the stream must say the run died, not just
                    # stop — consumers treat a missing run_done as "still running".
                    # Best-effort only: the run-failure exception is propagating
                    # through this finally, and a telemetry emit error (e.g. the
                    # SAME full disk that killed the write) must not replace it
                    abort_wall = time.perf_counter() - t_run
                    try:
                        if cfg.feed_cache_mb:
                            # the post-mortem of a died gigapixel run is exactly
                            # where the cache/decode counters matter — emit the
                            # rollup for the aborted scope too (still just before
                            # its run_done, like the success path)
                            telemetry.feed_cache(
                                blockcache.stats_delta(feed_cache_base)
                            )
                        # fetch rollup likewise: a run that died mid-readback is
                        # the one whose transfer/wait counters the post-mortem
                        # needs
                        telemetry.fetch(fetcher.summary())
                        # and the upload/store rollups — a run that died
                        # mid-ingest is the one whose upload-wait and
                        # store-put counters the post-mortem needs
                        telemetry.upload(uploader.summary())
                        if store is not None:
                            telemetry.ingest_store(store.stats_delta(store_base))
                        if program_stats is not None:
                            # the warm-cache verdict matters most on the
                            # aborted/cancelled scope a serve post-mortem
                            # reads
                            telemetry.program_cache(program_stats)
                        lease_stats = (
                            lease_q.stats() if lease_q is not None else None
                        )
                        if lease_stats is not None:
                            telemetry.lease_summary(lease_stats)
                        telemetry.run_done(
                            "aborted",
                            tiles_done=n_done,
                            pixels=n_px,
                            wall_s=round(abort_wall, 3),
                            px_per_s=round(n_px / abort_wall, 1) if n_px else 0.0,
                            fit_rate=(n_fit / n_px) if n_px else 0.0,
                            stage_s=timer.summary(),
                            tiles_quarantined=len(quarantined),
                            tiles_stolen=(
                                lease_stats["stolen"]
                                if lease_stats is not None else None
                            ),
                            tiles_speculated=(
                                lease_stats["speculated"]
                                if lease_stats is not None else None
                            ),
                        )
                    except Exception as exc:
                        log.error("abort-path telemetry run_done failed: %s", exc)
                    finally:
                        try:
                            telemetry.close()
                        except Exception as exc:
                            log.error("abort-path telemetry close failed: %s", exc)
                        # the flight dump is MOST valuable here: the last
                        # N events + resource samples of a run that died
                        # (dumped after close so run_done is in the ring)
                        self._dump_flight()
                if watchdog is not None:
                    # LAST: disarmed only once the unwind is through — the
                    # success tail below (merge wait included) has its own
                    # bounded timeouts and must not be subject to stall aborts
                    watchdog.stop()
            except KeyboardInterrupt:
                if watchdog is not None and watchdog.stalled:
                    # the watchdog fired DURING the unwind (e.g. a writer
                    # thread hung in a native transfer blocking the
                    # shutdown drain above): the remaining cleanup cannot
                    # run, the stall event is already durable — exit with
                    # the documented stall code rather than dying as an
                    # unexplained KeyboardInterrupt (~130) with the fault
                    # plan still armed
                    log.critical(
                        "stall during abort unwind; hard abort (exit 4)"
                    )
                    if telemetry is not None:
                        try:
                            telemetry.close()
                        except Exception:
                            pass
                    os._exit(4)
                raise

        wall = time.perf_counter() - t_run
        summary = {
            "tiles": len(tiles),
            "tiles_skipped_resume": n_resume_skipped,
            "pixels": n_px,
            "fit_rate": (n_fit / n_px) if n_px else 0.0,
            "wall_s": round(wall, 3),
            "px_per_s": round(n_px / wall, 1) if n_px else 0.0,
            "stage_s": timer.summary(),
            "fingerprint": manifest.fingerprint,
            "mesh_devices": n_mesh,
            # always present (empty on healthy runs): orchestrators branch on
            # it, and the CLI maps non-empty to exit code 3
            "tiles_quarantined": sorted(quarantined),
            # live straggler verdicts (obs/spans): tiles whose in-flight
            # duration exceeded straggler_k x the rolling median
            "stragglers": self.straggler.stats()["stragglers"],
        }
        if self.tune_info is not None:
            # which tuning profile resolved this run's "auto" knobs
            summary["tune"] = self.tune_info
        if lease_q is not None:
            # elastic scheduling rollup: acquisitions, steals,
            # speculative re-leases and their win count (first durable
            # done record ours), renewals, torn lease-log lines skipped
            summary["lease"] = lease_q.stats()
            summary["tiles_stolen"] = summary["lease"]["stolen"]
            summary["tiles_speculated"] = summary["lease"]["speculated"]
        feed_cache_stats = blockcache.stats_delta(feed_cache_base)
        if cfg.feed_cache_mb:
            summary["feed_cache"] = feed_cache_stats
        summary["fetch"] = fetcher.summary()
        summary["upload"] = uploader.summary()
        if store is not None:
            summary["ingest_store"] = store.stats_delta(store_base)
        if program_stats is not None:
            summary["program_cache"] = program_stats
        # the success tail can itself raise (a full-disk run_done emit, a
        # merge I/O error) — the plan must still disarm, or it leaks into
        # the process's NEXT run and fires faults nobody scheduled
        try:
            if telemetry is not None:
                if cfg.feed_cache_mb:
                    # one terminal rollup per run scope (matching the run-scoped
                    # stage_s), not a per-tile stream: the counters are cheap but
                    # the EVENT volume wouldn't be
                    telemetry.feed_cache(feed_cache_stats)
                # same one-rollup-per-scope shape for the fetch subsystem
                telemetry.fetch(summary["fetch"])
                # and for its upload mirror + the persistent ingest store
                telemetry.upload(summary["upload"])
                if store is not None:
                    telemetry.ingest_store(summary["ingest_store"])
                if program_stats is not None:
                    # one warm-cache rollup per run scope, like the
                    # fetch/upload/store rollups above
                    telemetry.program_cache(program_stats)
                if lease_q is not None:
                    # terminal lease counters (renewals, speculative
                    # wins) into the lt_lease_*/lt_speculative_* gauges
                    telemetry.lease_summary(summary["lease"])
                try:
                    telemetry.run_done(
                        "ok",
                        tiles_done=n_done,
                        pixels=n_px,
                        wall_s=summary["wall_s"],
                        px_per_s=summary["px_per_s"],
                        fit_rate=summary["fit_rate"],
                        stage_s=timer.summary(),
                        tiles_quarantined=len(quarantined),
                        tiles_stolen=(
                            summary.get("tiles_stolen")
                            if lease_q is not None else None
                        ),
                        tiles_speculated=(
                            summary.get("tiles_speculated")
                            if lease_q is not None else None
                        ),
                    )
                finally:
                    # the terminal-event emit may raise (full disk) and that error
                    # should surface on a succeeded run — but close() must still
                    # run, or the metrics port / exporter thread / event fd leak
                    # into the caller's process
                    summary["telemetry"] = {
                        "events": telemetry.events_file,
                        "metrics": telemetry.metrics_file,
                    }
                    if telemetry.metrics_port is not None:
                        summary["telemetry"]["metrics_port"] = telemetry.metrics_port
                    if telemetry.publish_file is not None:
                        summary["telemetry"]["snapshot"] = telemetry.publish_file
                    telemetry.close()  # final exposition flush before anyone reads it
                    # the closed event log can take no more fault_injected emits;
                    # merge.peer fires past this point are still counted/logged
                    # by the plan itself
                    faults.set_observer(None)
                    # owned-ring dump (run_done included — the close above
                    # already mirrored it): the "how did the end look"
                    # slice next to the full stream
                    flight_file = self._dump_flight()
                    if flight_file is not None:
                        summary["telemetry"]["flight"] = flight_file
                if jax.process_count() > 1 and jax.process_index() == 0:
                    # primary-host fold: per-process event files live in the SHARED
                    # workdir (the manifest's filesystem is the pod's job state), so
                    # the merge is a bounded wait for every peer's run_done line —
                    # no collective, usable even when a peer aborted
                    from land_trendr_tpu.parallel.multihost import merge_host_event_logs

                    # wait bound scaled to THIS run: all hosts started together on
                    # similar tile shares, so a straggler peer gets up to the
                    # primary's own wall again — but capped, because a peer that
                    # died WITHOUT its run_done line (OOM kill) must not make the
                    # primary of a 10-hour run poll for another 10 hours; then
                    # the partial fold (with its log warning) is the right answer.
                    # cfg.merge_timeout_s overrides for pods whose straggler
                    # profile the operator knows better than this heuristic.
                    merge_timeout_s = (
                        cfg.merge_timeout_s
                        if cfg.merge_timeout_s is not None
                        else max(60.0, min(2.0 * wall, 900.0))
                    )
                    summary["telemetry"]["hosts"] = merge_host_event_logs(
                        cfg.workdir,
                        expect_hosts=jax.process_count(),
                        timeout_s=merge_timeout_s,
                        # coarsen the straggler poll with the wait bound: a 900s
                        # wait does not need 10Hz probes of a shared filesystem
                        poll_s=max(0.1, min(2.0, merge_timeout_s / 600.0)),
                        # guard a reused workdir: a peer file untouched since this
                        # run began (60s clock-skew slack) holds only a PREVIOUS
                        # scope — its old run_done must not pass for a live host
                        newer_than=time.time() - wall - 60.0,
                    )
        finally:
            if fault_plan is not None:
                # disarmed only now, AFTER the multihost merge — the
                # merge.peer seam fires inside merge_host_event_logs; the
                # injection log is collected last for the same reason
                summary["faults_injected"] = [
                    {"seam": s, "index": i, "error": k}
                    for s, i, k in fault_plan.injected()
                ]
                faults.set_observer(None)
                faults.deactivate()
        log.info("run complete: %s", summary)
        self.summary = summary
        return summary




def assemble_outputs(stack: RasterStack, cfg: RunConfig) -> dict[str, str]:
    """Mosaic per-tile artifacts into segment rasters (SURVEY.md §4 stack 3).

    One multi-band GeoTIFF per product; band axis is the per-pixel vector
    axis (vertex slot / segment slot / year).  Returns product → path.
    """
    # "auto" fallback for STANDALONE assembly (a later process assembling
    # a finished workdir).  In-process callers (the CLI, the serve job
    # loop) pass the Run's already-RESOLVED config instead — a store
    # re-probed between run and assembly must not resolve the same
    # sentinel to different values (a fingerprint mismatch here reads as
    # "tiles missing" after a fully successful run).
    cfg, _ = resolve_config(cfg, scene_shape=(*stack.shape, stack.n_years))
    tiles = plan_tiles(*stack.shape, cfg.tile_size)
    manifest = TileManifest(cfg.workdir, cfg.fingerprint(stack))
    done = manifest.open(resume=True)
    missing = [t.tile_id for t in tiles if t.tile_id not in done]
    if missing:
        raise RuntimeError(
            f"cannot assemble: {len(missing)} tiles missing from manifest "
            f"(first few: {missing[:5]}); run run_stack first"
        )

    h, w = stack.shape
    os.makedirs(cfg.out_dir, exist_ok=True)
    # STREAMING assembly: every tile artifact is read exactly ONCE and its
    # windows pushed into one GeoTiffStreamWriter per product, so peak host
    # memory is O(tile × products) — never a full (depth, H, W) mosaic
    # (which at BASELINE configs[4] CONUS scale would be ~36 GB for one
    # float32 band and ~1.4 TB for the fitted raster).  Completed 256×256
    # blocks leave for disk immediately; run tiles are grid-aligned, so
    # tile_size % 256 == 0 buffers nothing and other sizes buffer at most
    # one block-row per product.
    with np.load(manifest.tile_path(tiles[0].tile_id)) as z:
        first = {name: z[name] for name in z.files}

    def out_dtype(dt: np.dtype) -> np.dtype:
        if dt == np.bool_:
            return np.dtype(np.uint8)
        if dt == np.float64:
            return np.dtype(np.float32)
        return dt

    writers: dict[str, GeoTiffStreamWriter] = {}
    paths: dict[str, str] = {}
    try:
        for name, a in sorted(first.items()):
            depth = 1 if a.ndim == 1 else a.shape[1]
            paths[name] = os.path.join(cfg.out_dir, f"{name}.tif")
            writers[name] = GeoTiffStreamWriter(
                paths[name],
                h,
                w,
                depth,
                out_dtype(a.dtype),
                geo=stack.geo,
                compress=cfg.out_compress,
                overviews=cfg.out_overviews,
            )
        for t in tiles:
            if first is not None and t is tiles[0]:
                arrays, first = first, None
            else:
                with np.load(manifest.tile_path(t.tile_id)) as z:
                    arrays = {name: z[name] for name in z.files}
            for name, wr in writers.items():
                a = arrays[name]
                wr.write(
                    t.y0,
                    t.x0,
                    a.reshape(t.h, t.w, -1).astype(wr.dtype, copy=False),
                )
            arrays = {}
        for wr in writers.values():
            wr.close()
    except BaseException:
        for wr in writers.values():  # release handles; leave no half files
            try:
                wr.abort()
            except Exception:
                pass
        for p in paths.values():
            if os.path.exists(p):
                os.unlink(p)
        raise
    return paths
