"""Packed async device→host fetch: one transfer per tile, overlapped.

`SCENE_TPU_r04.json` measured the fetch half of the host path at 96% of
scene wall on a tunneled chip: each tile's outputs left the device as ~10
independent per-product `np.asarray` calls, every one paying the link's
per-transfer latency, all of them serialized inside the write stage.  PR 2
fixed the *feed* half of the host path (`io/blockcache.py`); this module
is the *fetch* half — the host-I/O-bound regime the massively-parallel
break-detection literature names as the practical ceiling for per-pixel
time-series analysis (Gieseke et al., arXiv:1807.01751).

Three pieces:

* **Device-side pack** (:func:`pack_tile`): one tiny jitted program
  bitcasts every selected product — seg products, fitted, change, FTV,
  and the always-needed ``model_valid`` byte — into a single contiguous
  ``uint32`` word buffer (words, not bytes: XLA's byte-element concat
  measured ~4× slower for identical output).  ``fetch_f16`` casts are
  fused into the same program, so a tile costs ONE device→host transfer
  instead of ~10 latency-bound small ones.
* **Async overlap**: the driver issues :meth:`TileFetcher.start` right
  after ``block_until_ready`` — the packed buffer starts its
  ``copy_to_host_async`` immediately and lands while the NEXT tile
  computes; a bounded backlog (``RunConfig.fetch_depth``) keeps host
  memory and retry state bounded.  :meth:`FetchHandle.wait` blocks only
  on transfers that have not landed yet.
* **Host-side unpack** (:func:`unpack_tile`): crop to the tile's real
  pixels FIRST, then f16→f32 upcast / sign flip / dtype conversion —
  byte-for-byte the per-product path's output, without the per-product
  path's full-padded-shape upcast allocation.

The contract: ``packed`` and ``unpacked`` runs produce **byte-identical
artifacts** (``tests/test_fetch.py`` pins the matrix), because both paths
are driven by the same :class:`FetchPlan` — the single description of
what leaves the device, in what order, at what wire dtype, and how it is
restored on host.  ``fetch_packed="auto"`` resolves to packed only where
it pays: on a CPU backend ``np.asarray`` is zero-copy and the pack
program would be pure overhead, so auto keeps the per-product path there.

Everything here is a pure execution strategy — nothing is fingerprinted,
and a resume may freely mix packed and unpacked tiles.
"""

from __future__ import annotations

import functools
import math
import sys
import threading
import time
from typing import TYPE_CHECKING, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from land_trendr_tpu.ops import indices as idx
from land_trendr_tpu.runtime import faults

if TYPE_CHECKING:  # pragma: no cover - type-only imports (cycle with driver)
    from land_trendr_tpu.ops.tile import TileOutputs
    from land_trendr_tpu.runtime.driver import RunConfig, TileSpec

__all__ = [
    "SEG_PRODUCTS",
    "SIGNED_PRODUCTS",
    "FetchPlan",
    "PlanEntry",
    "TileFetcher",
    "build_plan",
    "pack_tile",
    "plan_wire_bytes",
    "resolve_packed",
    "unpack_tile",
]

#: the full per-pixel segmentation product set (``RunConfig.products``
#: domain); "fitted" is governed by ``write_fitted``, change_*/ftv_* by
#: their own knobs.  Lives here (not driver.py) because the fetch plan is
#: the one place that must know every product's wire representation.
SEG_PRODUCTS = (
    "n_vertices", "vertex_indices", "vertex_years", "vertex_src_vals",
    "vertex_fit_vals", "seg_magnitude", "seg_duration", "seg_rate",
    "rmse", "p_of_f", "model_valid",
)

#: value-carrying products that flip with the index's disturbance sign
#: (must match cli._SIGNED_FIELDS and the raster orientation contract)
SIGNED_PRODUCTS = frozenset(
    {"vertex_src_vals", "vertex_fit_vals", "seg_magnitude", "seg_rate"}
)


class PlanEntry(NamedTuple):
    """One product's place in the packed wire format.

    ``key`` is the artifact name (``""`` for the ``model_valid`` rider
    that travels only for the fit-rate metadata); ``src``/``field``
    resolve the device array inside a :class:`TileOutputs`; ``suffix`` is
    the per-pixel shape; ``dtype`` the device dtype, ``wire`` the dtype
    that crosses the link (f16 under ``fetch_f16``, uint8 for bool);
    ``signed``/``sign`` apply the disturbance-orientation flip on host;
    ``conv`` is the per-product host conversion the unpacked path has
    always applied (change yod→int32, change floats→float32, bool view).
    """

    key: str
    src: str            # "seg" | "change" | "ftv"
    field: str
    suffix: tuple[int, ...]
    dtype: str
    wire: str
    signed: bool
    sign: float
    conv: str           # "" | "int32" | "float32" | "bool"


class FetchPlan(NamedTuple):
    """Hashable (jit-static) description of one run's tile fetch."""

    entries: tuple[PlanEntry, ...]
    px: int  # PADDED device pixel count every tile shares


def _resolve(out: "TileOutputs", e: PlanEntry):
    if e.src == "seg":
        return getattr(out.seg, e.field)
    if e.src == "change":
        return out.change[e.field]
    return out.ftv[e.field]


def build_plan(out: "TileOutputs", cfg: "RunConfig") -> FetchPlan:
    """The run's fetch plan, from the first tile's (shared) output shapes.

    Entry order is the per-product path's historical fetch order, so the
    two paths stay structurally identical: seg products in
    :data:`SEG_PRODUCTS` order filtered by ``cfg.products``, fitted,
    change products, FTV products, then the ``model_valid`` rider when
    the product subset excludes it (1 B/px in the same transfer — the
    fit-rate metadata must never cost a separate blocking fetch).
    """
    sign = idx.DISTURBANCE_SIGN[cfg.index.lower()]
    want = SEG_PRODUCTS if cfg.products is None else cfg.products
    entries: list[PlanEntry] = []

    def add(key, src, field, arr, signed=False, sgn=1.0, conv=""):
        dt = np.dtype(arr.dtype)
        if dt == np.bool_:
            wire = "uint8"
            conv = conv or "bool"
        elif cfg.fetch_f16 and np.issubdtype(dt, np.floating):
            wire = "float16"
        else:
            wire = dt.name
        entries.append(
            PlanEntry(
                key, src, field, tuple(int(s) for s in arr.shape[1:]),
                dt.name, wire, bool(signed), float(sgn), conv,
            )
        )

    for name in SEG_PRODUCTS:
        if name in want:
            add(
                name, "seg", name, getattr(out.seg, name),
                signed=name in SIGNED_PRODUCTS, sgn=sign,
            )
    if cfg.write_fitted:
        add("fitted", "seg", "fitted", out.seg.fitted, signed=True, sgn=sign)
    if out.change is not None:
        for name, arr in out.change.items():
            conv = "int32" if name == "yod" else (
                "" if name == "mask" else "float32"
            )
            add(f"change_{name}", "change", name, arr, conv=conv)
    for name, arr in out.ftv.items():
        add(
            f"ftv_{name}", "ftv", name, arr,
            signed=True, sgn=idx.DISTURBANCE_SIGN[name.lower()],
        )
    if "model_valid" not in want:
        add("", "seg", "model_valid", out.seg.model_valid)
    return FetchPlan(
        entries=tuple(entries), px=int(out.seg.model_valid.shape[0])
    )


@functools.lru_cache(maxsize=None)
def _layout(plan: FetchPlan) -> tuple[tuple[tuple[int, int], ...], int]:
    """Per-entry ``(byte_offset, real_bytes)`` and the total wire bytes.

    Every entry starts on a word boundary (sub-word entries — bool, f16 —
    are zero-padded to the next word on device), so host unpack is a pure
    reinterpreting view at a known offset.
    """
    offs: list[tuple[int, int]] = []
    off = 0
    for e in plan.entries:
        n = plan.px * math.prod(e.suffix) * np.dtype(e.wire).itemsize
        offs.append((off, n))
        off += 4 * ((n + 3) // 4)
    return tuple(offs), off


def plan_wire_bytes(plan: FetchPlan) -> int:
    """Bytes one packed tile transfer moves (word padding included)."""
    return _layout(plan)[1]


def _to_words(a: jnp.ndarray) -> jnp.ndarray:
    """Reinterpret any array as a flat little-endian ``uint32`` stream."""
    it = a.dtype.itemsize
    if it >= 4:
        # 4-byte dtypes bitcast 1:1; 8-byte gain a trailing word pair
        return jax.lax.bitcast_convert_type(a, jnp.uint32).reshape(-1)
    if it == 2:
        b = jax.lax.bitcast_convert_type(a, jnp.uint16).reshape(-1)
        if b.size % 2:
            b = jnp.concatenate([b, jnp.zeros((1,), jnp.uint16)])
        return jax.lax.bitcast_convert_type(b.reshape(-1, 2), jnp.uint32)
    b = a.reshape(-1)
    if b.size % 4:
        b = jnp.concatenate([b, jnp.zeros(((-b.size) % 4,), b.dtype)])
    return jax.lax.bitcast_convert_type(b.reshape(-1, 4), jnp.uint32)


@functools.partial(jax.jit, static_argnames=("plan",))
def pack_tile(out: "TileOutputs", plan: FetchPlan) -> jnp.ndarray:
    """One device program: every planned product → one ``uint32`` buffer.

    ``fetch_f16`` casts (``wire`` ≠ ``dtype``) are fused here, so the
    narrowed representation is what crosses the link.  Unselected fields
    of ``out`` are dead arguments XLA removes.  Compiles once per run —
    every tile, edge tiles included, shares the padded pixel count.
    """
    parts = []
    for e in plan.entries:
        a = _resolve(out, e)
        if e.wire != e.dtype:
            a = a.astype(e.wire)
        parts.append(_to_words(a))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def _post(e: PlanEntry, a: np.ndarray) -> np.ndarray:
    """Shared host-side restore: f16 upcast → sign flip → conversion.

    Runs AFTER the ``[:px]`` crop (both paths), so the f32 upcast never
    allocates for padded rows — the pre-PR path upcast the full padded
    device shape first, wasting up to a tile of host f32 per product.
    """
    if a.dtype == np.float16:
        a = a.astype(np.float32)
    if e.signed:
        a = e.sign * a
    if e.conv == "int32":
        a = a.astype(np.int32)
    elif e.conv == "float32":
        a = a.astype(np.float32)
    return a


def unpack_tile(
    plan: FetchPlan, words: np.ndarray, px: int
) -> tuple[dict[str, np.ndarray], np.ndarray]:
    """Landed host words → (artifact arrays, cropped ``model_valid``).

    Pure host work (reinterpreting views + the :func:`_post` restores) —
    it runs inside the writer pool's write stage, off the driver loop's
    critical path.
    """
    buf = words.view(np.uint8)
    offs, _total = _layout(plan)
    arrays: dict[str, np.ndarray] = {}
    model_valid: np.ndarray | None = None
    for e, (off, nbytes) in zip(plan.entries, offs):
        a = buf[off : off + nbytes].view(e.wire).reshape(plan.px, *e.suffix)
        a = a[:px]
        if e.conv == "bool":
            a = a.view(np.bool_)
        a = _post(e, a)
        if e.key:
            arrays[e.key] = a
        if e.src == "seg" and e.field == "model_valid":
            model_valid = a
    assert model_valid is not None  # build_plan always includes the rider
    return arrays, model_valid


@jax.jit
def _jit_f16(a):
    """Device-side f16 cast for the per-product fallback path (one tiny
    program per dtype — the packed path fuses the casts into pack_tile)."""
    return a.astype(jnp.float16)


def _to_host(arr) -> np.ndarray:
    """The one device→host materialization point (fault seam
    ``fetch.wait``: a device error in an in-flight async fetch surfaces
    here, in the driver's drain, where the retry ladder runs)."""
    faults.check("fetch.wait")
    return np.asarray(arr)


def resolve_packed(fetch_packed: "bool | str") -> bool:
    """Resolve ``RunConfig.fetch_packed`` ("auto"/True/False) to a bool.

    "auto" packs only where a transfer is a real wire: on the CPU backend
    ``np.asarray`` of a device array is zero-copy, so the pack program
    would be pure overhead.  The wire format is little-endian (the device
    side of every supported backend); a big-endian HOST cannot
    reinterpret it, so auto falls back and an explicit ``True`` raises.
    """
    if fetch_packed == "auto":
        return jax.default_backend() != "cpu" and sys.byteorder == "little"
    if fetch_packed and sys.byteorder != "little":
        raise ValueError(
            "fetch_packed=True needs a little-endian host (the packed wire "
            "format is the device's LE byte order); use fetch_packed=False"
        )
    return bool(fetch_packed)


class _Stats:
    """Thread-safe fetch counters (unpack runs in writer-pool threads)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.tiles = 0
        self.transfers = 0
        self.bytes = 0
        self.pack_s = 0.0
        self.wait_s = 0.0
        self.unpack_s = 0.0
        self.backlog_max = 0

    def add(self, **deltas) -> None:
        with self._lock:
            for k, v in deltas.items():
                setattr(self, k, getattr(self, k) + v)

    def note_backlog(self, depth: int) -> None:
        with self._lock:
            if depth > self.backlog_max:
                self.backlog_max = depth


class PackedHandle:
    """One tile's in-flight packed transfer.

    ``wait`` is idempotent and thread-safe: the driver's bounded drain
    calls it on the loop thread (where a surfacing device error enters
    the retry ladder); ``tile_arrays`` — writer-pool threads — reuses the
    landed buffer.
    """

    def __init__(self, fetcher: "TileFetcher", words) -> None:
        self._fetcher = fetcher
        self._words = words
        self._lock = threading.Lock()
        self._host: np.ndarray | None = None

    def wait(self) -> None:
        """Block until the packed buffer has landed on host."""
        with self._lock:
            if self._host is None:
                t0 = time.perf_counter()
                self._host = _to_host(self._words)
                self._fetcher.stats.add(wait_s=time.perf_counter() - t0)
                self._words = None  # release the device buffer reference

    def tile_arrays(self, t: "TileSpec") -> tuple[dict[str, np.ndarray], int]:
        self.wait()
        t0 = time.perf_counter()
        arrays, model_valid = unpack_tile(
            self._fetcher.plan, self._host, t.h * t.w
        )
        # tiles counts COMPLETED tile fetches (one tile_arrays call per
        # tile); transfers/bytes count wire traffic, which a retried tile
        # legitimately pays more than once — so transfers >= tiles always
        self._fetcher.stats.add(unpack_s=time.perf_counter() - t0, tiles=1)
        return arrays, int(model_valid.sum())


class UnpackedHandle:
    """The per-product fallback: today's path, byte for byte.

    No device work happens at construction; every product is fetched
    synchronously inside ``tile_arrays`` — i.e. in the writer pool,
    inside the write stage, exactly where the pre-PR driver fetched.  The
    one (deliberate) improvement: ``model_valid`` is fetched alongside
    the products instead of as a separate blocking fetch inside the write
    timer's metadata branch when ``--products`` excludes it.
    """

    def __init__(self, fetcher: "TileFetcher", out: "TileOutputs") -> None:
        self._fetcher = fetcher
        self._out = out

    def wait(self) -> None:  # transfers happen in tile_arrays, as before
        return None

    def tile_arrays(self, t: "TileSpec") -> tuple[dict[str, np.ndarray], int]:
        stats = self._fetcher.stats
        px = t.h * t.w
        arrays: dict[str, np.ndarray] = {}
        model_valid: np.ndarray | None = None
        for e in self._fetcher.plan.entries:
            dev = _resolve(self._out, e)
            if e.wire == "float16" and e.dtype != "float16":
                dev = _jit_f16(dev)
            t0 = time.perf_counter()
            host = _to_host(dev)
            stats.add(
                wait_s=time.perf_counter() - t0,
                transfers=1,
                bytes=host.nbytes,
            )
            a = _post(e, host[:px])
            if e.key:
                arrays[e.key] = a
            if e.src == "seg" and e.field == "model_valid":
                model_valid = a
        assert model_valid is not None
        # counted AFTER the product loop (like the packed handle counts
        # after its fetch lands): a fetch that dies mid-tile must never
        # leave tiles ahead of transfers in the abort-path rollup
        stats.add(tiles=1)
        return arrays, int(model_valid.sum())


class TileFetcher:
    """Per-run fetch strategy: plan once, then one handle per tile."""

    def __init__(self, cfg: "RunConfig", packed: bool) -> None:
        self.cfg = cfg
        self.packed = packed
        self.demoted = False
        self.plan: FetchPlan | None = None
        self.stats = _Stats()

    def demote(self) -> None:
        """Graceful degradation: drop to the per-product synchronous path
        for the REST of the run (the driver calls this after repeated
        packed-fetch failures — a sick link should not keep eating the
        retry budget of every subsequent tile).  Artifacts are
        byte-identical either way (the FetchPlan contract), so demotion
        is safe mid-run; in-flight packed handles still drain normally.
        """
        self.packed = False
        self.demoted = True

    def start(self, out: "TileOutputs") -> "PackedHandle | UnpackedHandle":
        """Issue one tile's fetch; packed handles begin landing NOW."""
        if self.plan is None:
            self.plan = build_plan(out, self.cfg)
        if not self.packed:
            return UnpackedHandle(self, out)
        t0 = time.perf_counter()
        words = pack_tile(out, plan=self.plan)
        words.copy_to_host_async()
        self.stats.add(
            pack_s=time.perf_counter() - t0,
            transfers=1,
            bytes=plan_wire_bytes(self.plan),
        )
        return PackedHandle(self, words)

    def note_backlog(self, depth: int) -> None:
        self.stats.note_backlog(depth)

    def summary(self) -> dict:
        """Run-scoped counters for the run summary / ``fetch`` event."""
        s = self.stats
        with s._lock:
            return {
                "packed": self.packed,
                "demoted": self.demoted,
                "tiles": s.tiles,
                "transfers": s.transfers,
                "bytes": s.bytes,
                "pack_s": round(s.pack_s, 6),
                "wait_s": round(s.wait_s, 6),
                "unpack_s": round(s.unpack_s, 6),
                "backlog_max": s.backlog_max,
            }
