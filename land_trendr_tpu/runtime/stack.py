"""Host-side Landsat stack handling for the runtime driver.

Replaces the reference driver's GDAL stack-enumeration step (SURVEY.md §2
layer L1 / §4 call stack (1): "read Landsat stack, compute index, mask" in
the driver process).  Unlike the reference, the loaded representation stays
in the *narrow* on-disk dtype — int16 surface-reflectance DNs + uint16 QA —
because index math and masking run fused on device
(:mod:`land_trendr_tpu.ops.tile`); the host never materialises float32
bands for the whole scene.
"""

from __future__ import annotations

import dataclasses
import os
import re

import numpy as np

from land_trendr_tpu.io.geotiff import GeoMeta, read_geotiff
from land_trendr_tpu.io.synthetic import SyntheticStack
from land_trendr_tpu.ops.indices import BANDS

__all__ = [
    "LazyBandCube",
    "RasterStack",
    "load_stack_dir",
    "load_stack_dir_c2",
    "open_stack_dir_c2_lazy",
    "stack_from_synthetic",
]

# A plausible acquisition year, not any 4-digit run: Landsat product ids put
# path/row digits ("045030") before the date, so take the LAST match of a
# standalone (19|20)xx group.
_YEAR_RE = re.compile(r"(?<!\d)((?:19|20)\d{2})(?!\d)")

# Landsat Collection-2 Level-2 per-band file name, e.g.
# ``LC08_L2SP_045030_20200715_20200912_02_T1_SR_B5.TIF`` — the layout the
# USGS distributes (one file per band + QA_PIXEL), which the GDAL-based
# reference ingests through its stack enumeration (SURVEY.md §2 L1).
_C2_RE = re.compile(
    r"^(?P<sensor>L[COTEM]\d{2})_[A-Z0-9]{4}_(?P<pathrow>\d{6})_"
    r"(?P<date>\d{8})_\d{8}_\d{2}_(?:T1|T2|RT)_"
    r"(?P<prod>SR_B\d|QA_PIXEL)\.tiff?$",
    re.IGNORECASE,
)

#: SR band number → canonical band name, by sensor generation:
#: TM/ETM+ (LT04/LT05/LE07) vs OLI (LC08/LC09, numbering shifted by one).
_C2_TM_BANDS = {1: "blue", 2: "green", 3: "red", 4: "nir", 5: "swir1", 7: "swir2"}
_C2_OLI_BANDS = {2: "blue", 3: "green", 4: "red", 5: "nir", 6: "swir1", 7: "swir2"}


def _c2_band_name(sensor: str, prod: str) -> str | None:
    """Canonical band name for an ``SR_B<n>``/``QA_PIXEL`` product, or None
    for bands the pipeline does not use (e.g. OLI's coastal B1)."""
    if prod.upper() == "QA_PIXEL":
        return "qa"
    n = int(prod[-1])
    table = _C2_OLI_BANDS if sensor.upper() in ("LC08", "LC09") else _C2_TM_BANDS
    return table.get(n)


@dataclasses.dataclass
class RasterStack:
    """An annual Landsat stack in device-feed layout.

    ``dn_bands[name]`` is ``(NY, H, W)`` int16 or uint16 (real C2 SR files
    are uint16 — DNs up to 43636 — and keep that dtype; the device-side
    ``scale_sr`` conversion is dtype-agnostic); ``qa`` is ``(NY, H, W)``
    uint16; ``years`` is ``(NY,)`` int32 ascending.  ``geo`` carries the
    grid so output rasters inherit it (SURVEY.md §2: outputs are written on
    the input grid).
    """

    years: np.ndarray
    dn_bands: dict[str, np.ndarray]
    qa: np.ndarray
    geo: GeoMeta | None = None

    @property
    def shape(self) -> tuple[int, int]:
        return self.qa.shape[1], self.qa.shape[2]

    @property
    def n_years(self) -> int:
        return int(self.years.shape[0])


def _check_year_dtype(name: str, cube: np.ndarray, img: np.ndarray) -> None:
    """Dtype-uniformity guard: a mixed int16/uint16 archive would either
    silently promote (np.stack → int32, double the documented ~6
    B/pixel-year feed) or silently wrap on assignment into a preallocated
    cube — both outside RasterStack's 16-bit contract."""
    if img.dtype != cube.dtype:
        dtypes = sorted({str(cube.dtype), str(img.dtype)})
        raise ValueError(
            f"band {name!r}: mixed DN dtypes across years {dtypes} — "
            "re-export the archive with one dtype"
        )


def _use_bands(bands) -> tuple[str, ...]:
    """Validate a band-subset request against the canonical band list."""
    if bands is None:
        return BANDS
    use = tuple(bands)
    if not use:
        raise ValueError("bands subset must not be empty (pass None for all)")
    bad = [b for b in use if b not in BANDS]
    if bad:
        raise ValueError(f"unknown band(s) {bad}; choose from {BANDS}")
    return use


def _check_qa_dtype(fp: str, dtype: np.dtype) -> None:
    """The ONE QA_PIXEL whitelist both C2 loaders share.

    C2 defines QA_PIXEL as uint16 with flags through bit 15: a wider file
    would be silently truncated by a blind uint16 cast, and a narrower one
    cannot carry the full flag set — either way the file is not a valid C2
    QA band, so reject it loudly (ADVICE round 5).  Kept as one helper so
    the eager and lazy loaders cannot diverge."""
    if dtype != np.dtype(np.uint16):
        raise ValueError(
            f"{fp}: QA_PIXEL dtype {dtype} unsupported "
            "(expected uint16 bit flags)"
        )


def load_stack_dir(
    path: str,
    pattern: str = r"\.tif$",
    bands=None,
    composite: str | None = None,
    reject_bits: int | None = None,
    scale: float = 2.75e-5,
    offset: float = -0.2,
) -> RasterStack:
    """Load a directory of Landsat rasters, auto-detecting the layout.

    Two layouts are understood:

    * **pre-stacked**: one multi-band file per year whose name contains the
      4-digit year (the layout :func:`land_trendr_tpu.io.synthetic.
      write_stack` produces, and the common convention for annual
      composites), bands ordered ``blue, green, red, nir, swir1, swir2,
      QA_PIXEL``;
    * **Collection-2 per-band**: the USGS distribution layout — one file
      per band per acquisition (``*_SR_B2..B7.TIF`` + ``*_QA_PIXEL.TIF``)
      — detected by product-id file names and delegated to
      :func:`load_stack_dir_c2`.

    ``bands`` (optional iterable of canonical band names) loads only that
    subset plus QA — for an NBR run that is 3 cubes instead of 7 (~2.3×
    less host memory at scene scale; the CLI passes
    :func:`~land_trendr_tpu.ops.indices.required_bands` automatically).
    The per-band C2 layout additionally skips reading the unused files.
    ``composite`` ("medoid") applies to the C2 layout only, where multiple
    acquisitions per year can occur — see :func:`load_stack_dir_c2`.
    """
    names = sorted(
        n for n in os.listdir(path) if re.search(pattern, n, re.IGNORECASE)
    )
    if not names:
        raise FileNotFoundError(f"no rasters matching {pattern!r} in {path}")
    if any(_C2_RE.match(n) for n in names):
        return load_stack_dir_c2(
            path,
            pattern=pattern,
            bands=bands,
            composite=composite,
            reject_bits=reject_bits,
            scale=scale,
            offset=offset,
        )
    if composite is not None:
        raise ValueError(
            "composite applies to the Collection-2 per-band layout; the "
            "pre-stacked layout is one image per year by construction"
        )
    use = _use_bands(bands)
    entries = []
    for n in names:
        ms = _YEAR_RE.findall(n)
        if not ms:
            raise ValueError(f"cannot parse a plausible 4-digit year from {n!r}")
        entries.append((int(ms[-1]), os.path.join(path, n)))
    entries.sort()
    years = np.array([y for y, _ in entries], dtype=np.int32)
    if len(np.unique(years)) != len(years):
        raise ValueError(f"duplicate years in {path}: {years.tolist()}")

    # Cubes are PREALLOCATED and filled year by year so peak host memory is
    # one stack plus one year file.  (Accumulating per-year band views and
    # np.stack-ing at the end kept every year's full multi-band image alive
    # through the views PLUS the stacked copy — measured ~28 GB peak for a
    # 6 GB 5000²×40yr working set, SCENE_r03.json peak_rss_mib.)
    dn_cubes: dict[str, np.ndarray] = {}
    qa_cube: np.ndarray | None = None
    geo = None
    shape = None
    for k, (year, fp) in enumerate(entries):
        img, g, _info = read_geotiff(fp)
        if img.ndim == 2:
            img = img[None]
        if img.shape[0] < len(BANDS) + 1:
            raise ValueError(
                f"{fp}: expected {len(BANDS) + 1} bands "
                f"({', '.join(BANDS)}, QA_PIXEL); got {img.shape[0]}"
            )
        if img.dtype not in (np.dtype(np.int16), np.dtype(np.uint16)):
            # whitelist, not best-effort casting: float reflectance would
            # zero out, and wider integers (int32 DN exports) would wrap
            # bright pixels negative — both silently
            raise ValueError(
                f"{fp}: dtype {img.dtype} — the stack loaders take "
                "Collection-2 scaled 16-bit DNs (int16/uint16); re-export "
                "as DNs (reflectance = DN * 2.75e-5 - 0.2)"
            )
        if qa_cube is None:
            shape, geo = img.shape[1:], g
            dn_cubes = {
                b: np.empty((len(entries), *shape), img.dtype) for b in use
            }
            qa_cube = np.empty((len(entries), *shape), np.uint16)
        elif img.shape[1:] != shape:
            raise ValueError(f"{fp}: raster size {img.shape[1:]} != {shape}")
        else:
            _check_year_dtype(use[0], dn_cubes[use[0]], img)
        for b in use:
            # band position in the pre-stacked file follows BANDS order
            dn_cubes[b][k] = img[BANDS.index(b)]  # keeps the stored dtype
        qa_cube[k] = img[len(BANDS)].astype(np.uint16, copy=False)

    return RasterStack(
        years=years,
        dn_bands=dn_cubes,
        qa=qa_cube,
        geo=geo,
    )


def load_stack_dir_c2(
    path: str,
    pattern: str | None = None,
    bands=None,
    composite: str | None = None,
    reject_bits: int | None = None,
    scale: float = 2.75e-5,
    offset: float = -0.2,
) -> RasterStack:
    """Load a directory of Landsat Collection-2 Level-2 per-band files.

    The real USGS distribution layout (SURVEY.md §2 L1 — the reference's
    GDAL ingest reads it file by file): per acquisition, one GeoTIFF per
    surface-reflectance band (``*_SR_B2..B7.TIF``; TM/ETM+ numbering
    ``B1..B5,B7``) plus ``*_QA_PIXEL.TIF``.  Files group by acquisition
    YEAR; the band mapping follows each file's own sensor prefix, so a
    time series that switches from LT05 to LC08 mid-archive loads
    correctly.  SR DNs keep their on-disk integer dtype — real C2 SR is
    **uint16** (valid DN 7273–43636) and must not be narrowed to int16.

    LandTrendr is an annual-series algorithm, so each year must collapse
    to one image.  By default (``composite=None``) exactly one
    acquisition per year is required and multiple dates raise with the
    offending values listed; ``composite="medoid"`` instead builds the
    per-pixel QA-masked medoid composite of each multi-acquisition year
    on device (:func:`land_trendr_tpu.ops.composite.medoid_composite` —
    an extension beyond the reference, which tells users to composite
    first).  ``reject_bits``/``scale``/``offset`` feed the composite's
    validity masks and should match the run's ``RunConfig`` values so
    selection and segmentation mask identically (None → the C2
    defaults).  One WRS-2 path/row is required either way; ``pattern``
    (regex on file names, the same argument :func:`load_stack_dir`
    takes) pre-filters the directory, e.g. to select one path/row.
    """
    if composite not in (None, "medoid"):
        raise ValueError(f"composite={composite!r} not None|'medoid'")
    # year -> date -> band -> path
    groups: dict[int, dict[str, dict[str, str]]] = {}
    pathrows: set[str] = set()
    for n in sorted(os.listdir(path)):
        if pattern is not None and not re.search(pattern, n, re.IGNORECASE):
            continue
        m = _C2_RE.match(n)
        if not m:
            continue
        band = _c2_band_name(m["sensor"], m["prod"])
        if band is None:
            continue  # e.g. OLI coastal B1 — unused
        pathrows.add(m["pathrow"])
        year = int(m["date"][:4])
        groups.setdefault(year, {}).setdefault(m["date"], {})[band] = os.path.join(
            path, n
        )
    if not groups:
        raise FileNotFoundError(f"no Collection-2 per-band rasters in {path}")
    if len(pathrows) > 1:
        raise ValueError(
            f"{path}: multiple WRS-2 path/rows {sorted(pathrows)} in one "
            "stack — pass pattern=... to select one scene"
        )
    multi = {y: sorted(d) for y, d in groups.items() if len(d) > 1}
    if multi and composite is None:
        raise ValueError(
            f"{path}: multiple acquisitions per year {multi} — LandTrendr "
            "takes one image per year; pre-composite, prune, or pass "
            "composite='medoid'"
        )

    years = np.array(sorted(groups), dtype=np.int32)
    needed = (*_use_bands(bands), "qa")  # unused bands' files never read
    # preallocated cubes, filled per (year, band): peak memory is one stack
    # plus one year's acquisitions (see load_stack_dir's note)
    dn_cubes: dict[str, np.ndarray] = {}
    qa_cube: np.ndarray | None = None
    geo = None
    shape = None

    def read_band(fp: str, b: str) -> np.ndarray:
        nonlocal shape, geo
        img, gmeta, _info = read_geotiff(fp)
        if img.ndim != 2:
            raise ValueError(
                f"{fp}: expected a single-band raster; got {img.shape}"
            )
        if shape is None:
            shape, geo = img.shape, gmeta
        elif img.shape != shape:
            raise ValueError(f"{fp}: raster size {img.shape} != {shape}")
        if b == "qa":
            _check_qa_dtype(fp, img.dtype)
            return img
        if img.dtype not in (np.dtype(np.int16), np.dtype(np.uint16)):
            # keep the on-disk dtype: real C2 SR is uint16 with valid DNs
            # up to 43636 — an int16 cast would wrap bright pixels (snow,
            # cloud edge) negative with no error
            raise ValueError(
                f"{fp}: SR band dtype {img.dtype} unsupported "
                "(expected int16 or uint16 DNs)"
            )
        return img

    for k, year in enumerate(years.tolist()):
        by_date = groups[year]
        for date in sorted(by_date):
            missing = [b for b in needed if b not in by_date[date]]
            if missing:
                raise ValueError(
                    f"{path}: acquisition {date} is missing bands {missing} "
                    f"(have {sorted(by_date[date])})"
                )
        dates = sorted(by_date)
        if len(dates) == 1:
            per_band = {b: read_band(by_date[dates[0]][b], b) for b in needed}
        else:
            # stack the year's acquisitions and medoid-composite on device
            from land_trendr_tpu.ops.composite import medoid_composite
            from land_trendr_tpu.ops.indices import DEFAULT_QA_REJECT

            stacks = {}
            for b in needed:
                imgs = [read_band(by_date[d][b], b) for d in dates]
                # within-year uniformity: np.stack would silently promote
                # a mixed int16/uint16 year to int32 (same hazard
                # _check_year_dtype blocks across years)
                dtypes = sorted({str(a.dtype) for a in imgs})
                if b != "qa" and len(dtypes) > 1:
                    raise ValueError(
                        f"band {b!r}: mixed DN dtypes across year {year}'s "
                        f"acquisitions {dtypes} — re-export the archive "
                        "with one dtype"
                    )
                stacks[b] = np.stack(imgs)
            comp_dn, comp_qa = medoid_composite(
                {b: stacks[b] for b in needed if b != "qa"},
                stacks["qa"],
                reject_bits=(
                    DEFAULT_QA_REJECT if reject_bits is None else reject_bits
                ),
                scale=scale,
                offset=offset,
            )
            per_band = {**comp_dn, "qa": comp_qa}
        for b in needed:
            img = per_band[b]
            if b == "qa":
                if qa_cube is None:
                    qa_cube = np.empty((len(years), *shape), np.uint16)
                qa_cube[k] = img
            else:
                if b not in dn_cubes:
                    dn_cubes[b] = np.empty((len(years), *shape), img.dtype)
                else:
                    _check_year_dtype(b, dn_cubes[b], img)
                dn_cubes[b][k] = img

    assert qa_cube is not None  # needed bands are enforced per year
    return RasterStack(
        years=years,
        dn_bands=dn_cubes,
        qa=qa_cube,
        geo=geo,
    )


def stack_from_synthetic(stack: SyntheticStack, geo: GeoMeta | None = None) -> RasterStack:
    """Adapt an in-memory synthetic stack (tests / benchmarks) to the
    driver's feed layout without a disk round-trip."""
    return RasterStack(
        years=stack.years.astype(np.int32),
        dn_bands={b: stack.dn(b) for b in BANDS},
        qa=stack.qa.astype(np.uint16),
        geo=geo,
    )


class LazyBandCube:
    """``(NY, H, W)``-shaped lazy cube: one single-band raster per year.

    Holds no pixel data — ``__getitem__`` window-reads only the blocks a
    tile needs (:func:`~land_trendr_tpu.io.geotiff.read_geotiff_window`).
    This is the CONUS-scale ingest seam (BASELINE configs[4], SURVEY.md
    §2 L1): a gigapixel mosaic's input cubes cannot live in host RAM, so
    the reference reads GDAL windows on demand; this duck-types exactly
    the slicing the driver feed performs (``a[:, y0:y1, x0:x1]``) over
    per-year files instead.  Use :func:`open_stack_dir_c2_lazy` to build
    a :class:`RasterStack` of these.
    """

    def __init__(self, paths: list[str], shape: tuple[int, int], dtype):
        self.paths = list(paths)
        self.shape = (len(self.paths), *shape)
        self.dtype = np.dtype(dtype)
        self.ndim = 3

    def prefetch_window(self, y0: int, x0: int, h: int, w: int) -> int:
        """Readahead hint: decode the blocks of this window (every year)
        into the process-wide decoded-block cache off-thread, so a later
        ``self[:, y0:y0+h, x0:x0+w]`` is served from cache — the driver
        feed pool hints the NEXT planned tile while the current one waits
        on the device.  Fire-and-forget; returns the number of per-file
        hints actually queued (0 when the cache/readahead is off or the
        decode pool is saturated — the read then just decodes on demand).
        """
        from land_trendr_tpu.io import blockcache

        queued = 0
        for p in self.paths:
            if blockcache.prefetch_window(p, y0, x0, h, w):
                queued += 1
        return queued

    def __getitem__(self, key) -> np.ndarray:
        from land_trendr_tpu.io.geotiff import read_geotiff_window

        if not (isinstance(key, tuple) and len(key) == 3):
            raise TypeError(
                f"LazyBandCube supports [years, y, x] window slicing; got {key!r}"
            )
        ys, rows, cols = key
        ny, h_full, w_full = self.shape

        def norm_int(k: int, dim: int, axis: str) -> int:
            # ndarray index semantics: negatives count from the end; out of
            # range raises.  Without this, a negative int became a negative
            # window offset handed straight to read_geotiff_window
            # (ADVICE round 5).
            j = int(k)
            if j < 0:
                j += dim
            if not 0 <= j < dim:
                raise IndexError(
                    f"index {k} out of bounds for LazyBandCube {axis} axis "
                    f"of size {dim}"
                )
            return j

        yr_idx = (
            range(ny)[ys] if isinstance(ys, slice)
            else [norm_int(ys, ny, "year")]
        )
        r0, r1, rstep = (
            rows.indices(h_full) if isinstance(rows, slice)
            else ((r := norm_int(rows, h_full, "row")), r + 1, 1)
        )
        c0, c1, cstep = (
            cols.indices(w_full) if isinstance(cols, slice)
            else ((c := norm_int(cols, w_full, "col")), c + 1, 1)
        )
        if rstep != 1 or cstep != 1:
            raise ValueError("LazyBandCube windows must be contiguous (step 1)")
        h, w = r1 - r0, c1 - c0
        out = np.empty((len(yr_idx), h, w), self.dtype)
        for i, k in enumerate(yr_idx):
            win = read_geotiff_window(self.paths[k], r0, c0, h, w)
            if win.ndim != 2:
                raise ValueError(
                    f"{self.paths[k]}: expected a single-band raster for a "
                    f"lazy cube; got shape {win.shape}"
                )
            out[i] = win
        return out


def open_stack_dir_c2_lazy(
    path: str, pattern: str | None = None, bands=None
) -> RasterStack:
    """Open a Collection-2 per-band directory WITHOUT reading pixel data.

    Same layout rules as :func:`load_stack_dir_c2` (one acquisition per
    year — compositing requires the eager loader; one WRS-2 path/row),
    but each band becomes a :class:`LazyBandCube` whose windows are read
    on demand by the driver's tile feed.  Header-only validation up
    front: every needed file must exist, agree on raster size, and carry
    a 16-bit sample format.  Peak host memory for a run over the result
    is O(tile), not O(scene) — the configs[4] requirement.
    """
    from land_trendr_tpu.io.geotiff import read_geotiff_info

    groups: dict[int, dict[str, dict[str, str]]] = {}
    pathrows: set[str] = set()
    for n in sorted(os.listdir(path)):
        if pattern is not None and not re.search(pattern, n, re.IGNORECASE):
            continue
        m = _C2_RE.match(n)
        if not m:
            continue
        band = _c2_band_name(m["sensor"], m["prod"])
        if band is None:
            continue
        pathrows.add(m["pathrow"])
        year = int(m["date"][:4])
        groups.setdefault(year, {}).setdefault(m["date"], {})[band] = os.path.join(
            path, n
        )
    if not groups:
        raise FileNotFoundError(f"no Collection-2 per-band rasters in {path}")
    if len(pathrows) > 1:
        raise ValueError(
            f"{path}: multiple WRS-2 path/rows {sorted(pathrows)} in one "
            "stack — pass pattern=... to select one scene"
        )
    multi = {y: sorted(d) for y, d in groups.items() if len(d) > 1}
    if multi:
        raise ValueError(
            f"{path}: multiple acquisitions per year {multi} — the lazy "
            "opener takes one image per year (compositing needs the eager "
            "loader: load_stack_dir_c2(..., composite='medoid'))"
        )
    years = np.array(sorted(groups), dtype=np.int32)
    needed = (*_use_bands(bands), "qa")
    per_band_paths: dict[str, list[str]] = {b: [] for b in needed}
    for year in years.tolist():
        (date,) = groups[year]
        missing = [b for b in needed if b not in groups[year][date]]
        if missing:
            raise ValueError(
                f"{path}: acquisition {date} is missing bands {missing} "
                f"(have {sorted(groups[year][date])})"
            )
        for b in needed:
            per_band_paths[b].append(groups[year][date][b])

    shape = None
    geo = None
    dtypes: dict[str, str] = {}
    for b in needed:
        for fp in per_band_paths[b]:
            gmeta, info = read_geotiff_info(fp)
            if shape is None:
                shape, geo = (info.height, info.width), gmeta
            elif (info.height, info.width) != shape:
                raise ValueError(
                    f"{fp}: raster size {(info.height, info.width)} != {shape}"
                )
            if b == "qa":
                # the lazy feed casts windows to uint16 blindly, so the
                # header dtype must pass the shared whitelist up front
                _check_qa_dtype(fp, info.dtype)
            elif info.dtype not in (
                np.dtype(np.int16), np.dtype(np.uint16)
            ):
                # same whitelist as the eager loader's read_band: f16 has
                # itemsize 2 but rounds DNs above its 2048 integer-exact
                # range — reject, don't silently corrupt radiometry
                raise ValueError(
                    f"{fp}: SR band dtype {info.dtype} unsupported "
                    "(expected int16 or uint16 DNs)"
                )
            prev = dtypes.setdefault(b, str(info.dtype))
            if b != "qa" and prev != str(info.dtype):
                raise ValueError(
                    f"band {b!r}: mixed DN dtypes across years "
                    f"{sorted({prev, str(info.dtype)})} — re-export the "
                    "archive with one dtype"
                )
    dn = {
        b: LazyBandCube(per_band_paths[b], shape, np.dtype(dtypes[b]))
        for b in needed if b != "qa"
    }
    qa = LazyBandCube(per_band_paths["qa"], shape, np.uint16)
    return RasterStack(years=years, dn_bands=dn, qa=qa, geo=geo)
