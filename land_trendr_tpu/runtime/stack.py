"""Host-side Landsat stack handling for the runtime driver.

Replaces the reference driver's GDAL stack-enumeration step (SURVEY.md §2
layer L1 / §4 call stack (1): "read Landsat stack, compute index, mask" in
the driver process).  Unlike the reference, the loaded representation stays
in the *narrow* on-disk dtype — int16 surface-reflectance DNs + uint16 QA —
because index math and masking run fused on device
(:mod:`land_trendr_tpu.ops.tile`); the host never materialises float32
bands for the whole scene.
"""

from __future__ import annotations

import dataclasses
import os
import re

import numpy as np

from land_trendr_tpu.io.geotiff import GeoMeta, read_geotiff
from land_trendr_tpu.io.synthetic import SyntheticStack
from land_trendr_tpu.ops.indices import BANDS

__all__ = ["RasterStack", "load_stack_dir", "stack_from_synthetic"]

# A plausible acquisition year, not any 4-digit run: Landsat product ids put
# path/row digits ("045030") before the date, so take the LAST match of a
# standalone (19|20)xx group.
_YEAR_RE = re.compile(r"(?<!\d)((?:19|20)\d{2})(?!\d)")


@dataclasses.dataclass
class RasterStack:
    """An annual Landsat stack in device-feed layout.

    ``dn_bands[name]`` is ``(NY, H, W)`` int16; ``qa`` is ``(NY, H, W)``
    uint16; ``years`` is ``(NY,)`` int32 ascending.  ``geo`` carries the
    grid so output rasters inherit it (SURVEY.md §2: outputs are written on
    the input grid).
    """

    years: np.ndarray
    dn_bands: dict[str, np.ndarray]
    qa: np.ndarray
    geo: GeoMeta | None = None

    @property
    def shape(self) -> tuple[int, int]:
        return self.qa.shape[1], self.qa.shape[2]

    @property
    def n_years(self) -> int:
        return int(self.years.shape[0])


def load_stack_dir(path: str, pattern: str = r"\.tif$") -> RasterStack:
    """Load a directory of per-year multi-band GeoTIFFs.

    Expects one file per year whose name contains the 4-digit year (the
    layout :func:`land_trendr_tpu.io.synthetic.write_stack` produces, and
    the common convention for annual composites), bands ordered
    ``blue, green, red, nir, swir1, swir2, QA_PIXEL``.
    """
    names = sorted(n for n in os.listdir(path) if re.search(pattern, n))
    if not names:
        raise FileNotFoundError(f"no rasters matching {pattern!r} in {path}")
    entries = []
    for n in names:
        ms = _YEAR_RE.findall(n)
        if not ms:
            raise ValueError(f"cannot parse a plausible 4-digit year from {n!r}")
        entries.append((int(ms[-1]), os.path.join(path, n)))
    entries.sort()
    years = np.array([y for y, _ in entries], dtype=np.int32)
    if len(np.unique(years)) != len(years):
        raise ValueError(f"duplicate years in {path}: {years.tolist()}")

    dn_bands: dict[str, list[np.ndarray]] = {b: [] for b in BANDS}
    qa_list = []
    geo = None
    shape = None
    for year, fp in entries:
        img, g, _info = read_geotiff(fp)
        if img.ndim == 2:
            img = img[None]
        if img.shape[0] < len(BANDS) + 1:
            raise ValueError(
                f"{fp}: expected {len(BANDS) + 1} bands "
                f"({', '.join(BANDS)}, QA_PIXEL); got {img.shape[0]}"
            )
        if shape is None:
            shape, geo = img.shape[1:], g
        elif img.shape[1:] != shape:
            raise ValueError(f"{fp}: raster size {img.shape[1:]} != {shape}")
        for i, b in enumerate(BANDS):
            dn_bands[b].append(img[i].astype(np.int16, copy=False))
        qa_list.append(img[len(BANDS)].astype(np.uint16, copy=False))

    return RasterStack(
        years=years,
        dn_bands={b: np.stack(v) for b, v in dn_bands.items()},
        qa=np.stack(qa_list),
        geo=geo,
    )


def stack_from_synthetic(stack: SyntheticStack, geo: GeoMeta | None = None) -> RasterStack:
    """Adapt an in-memory synthetic stack (tests / benchmarks) to the
    driver's feed layout without a disk round-trip."""
    return RasterStack(
        years=stack.years.astype(np.int32),
        dn_bands={b: stack.dn(b) for b in BANDS},
        qa=stack.qa.astype(np.uint16),
        geo=geo,
    )
