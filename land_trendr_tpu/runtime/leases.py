"""Elastic tile lease queue over the shared-filesystem manifest.

The pod's tile distribution used to be a static split: each process took
its :func:`~land_trendr_tpu.parallel.host_share` of the tile list, so one
slow or dead host stranded its whole share — exactly the straggler /
partial-failure regime *Massively-Parallel Break Detection for Satellite
Data* (PAPERS.md, arXiv:1807.01751) reports dominating continent-scale
runs.  This module replaces the split with a **lease queue** coordinated
through the one piece of shared state the pod already trusts: the
append-only tile manifest on the shared filesystem.

Protocol (append-only records in ``manifest.jsonl``; every append is one
``os.write`` on an ``O_APPEND`` descriptor, atomic per line like the
event log, so all readers agree on ONE record order):

* ``kind="lease"`` — a claim on ``tile_id`` at generation ``gen`` by
  ``owner`` (a ``host:pid:token`` identity — a restarted process is a
  NEW generation of the same host, never a resumed owner), carrying
  ``ttl_s`` and ``t_wall``.  **Log order is the arbiter**: for each
  ``(tile, gen)`` the FIRST lease record in the file wins; later records
  at the same generation lost the race and their writers observe that on
  re-read.  A further record from the *winning* owner at the same
  generation is a **renewal** — it pushes the expiry to its own
  ``t_wall + ttl_s``.
* ``kind="lease_release"`` — the owner relinquishes an unfinished claim
  (abort/cancel unwind), making the tile immediately claimable at the
  next generation instead of after a TTL.
* ``kind="lease_flag"`` — the owner's live
  :class:`~land_trendr_tpu.obs.spans.StragglerDetector` flagged the tile
  while in flight: an advertisement that idle peers may *speculatively*
  re-lease it (generation + 1) even though the lease has not expired.
* ``kind="tile"`` (the existing done record) stays the ONE durability
  signal: it supersedes every lease.  ``kind="tile_failed"`` appended
  DURING this run marks the tile quarantined run-wide (a resume
  re-attempts it, exactly as before — historical failure records from a
  previous scope do not block claims).

Safety does **not** depend on the lease: a lost/duplicated lease record
at worst re-executes a tile, and the tile artifact path is already
idempotent — deterministic bytes through an atomic tmp+rename, with the
done-record set deduplicated at :meth:`TileManifest.open`.  So an
expired-lease steal racing an owner that was merely slow (not dead), or
a speculative duplicate of a straggler, both resolve to byte-identical
artifacts; the first durable done record is the winner for accounting
(``spec_wins``) and the loser's write lands as an identical no-op.
Clocks: expiry compares the reader's ``time.time()`` against the
record's ``t_wall + ttl_s``, so the TTL must comfortably exceed both the
slowest tile and the pod's worst wall-clock skew (the default 30s does,
for NTP-disciplined fleets; it is a throughput knob, never a correctness
one).

Thread-safety: driver thread (acquire/renew/release) plus the flight
sampler thread (:meth:`flag`, via the straggler callback).  The internal
lock guards pure state only — file reads and appends happen outside it,
so a slow shared filesystem never blocks a lock holder (LT007).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
import uuid
from typing import Iterable

from land_trendr_tpu.runtime import faults

__all__ = ["Lease", "LeaseQueue"]

log = logging.getLogger("land_trendr_tpu.runtime.leases")

#: acquisition modes, as recorded in the lease record's ``mode`` field
#: and returned by :meth:`LeaseQueue.acquire`
MODES = ("claim", "steal", "spec", "renew")


class Lease:
    """The current (highest-generation, first-writer) lease of one tile.

    ``prev_owner`` is the owner a successor generation displaced (None at
    generation 0) — the ``from_owner`` attribution steal/speculation
    events carry.
    """

    __slots__ = (
        "gen", "owner", "expiry", "mode", "flagged", "released",
        "prev_owner",
    )

    def __init__(
        self,
        gen: int,
        owner: str,
        expiry: float,
        mode: str,
        prev_owner: "str | None" = None,
    ) -> None:
        self.gen = gen
        self.owner = owner
        self.expiry = expiry
        self.mode = mode
        self.flagged = False
        self.released = False
        self.prev_owner = prev_owner


class LeaseQueue:
    """One process's view of (and hand in) the shared tile lease log.

    ``done0`` is the artifact-verified done set from
    :meth:`TileManifest.open` — historical ``kind="tile"`` records (those
    already in the file at construction) are trusted only when their
    artifact verified, so a torn-artifact resume recomputes exactly what
    the manifest's own readability check said to recompute.  Records
    appended after construction are this run's live traffic and are
    trusted as written.
    """

    def __init__(
        self,
        path: str,
        tile_ids: Iterable[int],
        *,
        ttl_s: float = 30.0,
        done0: "set[int] | None" = None,
        owner: "str | None" = None,
    ) -> None:
        if ttl_s <= 0:
            raise ValueError(f"ttl_s={ttl_s} must be > 0")
        self.path = path
        self.ttl_s = float(ttl_s)
        #: (host, pid, generation) identity: the uuid token IS the
        #: process generation — a restarted pid can never impersonate
        #: its predecessor's leases
        self.owner = (
            owner
            if owner is not None
            else f"{socket.gethostname()}:{os.getpid()}:{uuid.uuid4().hex[:8]}"
        )
        self._all = set(int(t) for t in tile_ids)
        self._lock = threading.Lock()
        self._leases: "dict[int, Lease]" = {}
        self._done: "set[int]" = set(done0 or ())
        self._failed: "set[int]" = set()
        self._held: "set[int]" = set()
        self._my_spec: "set[int]" = set()
        self._first_done_owner: "dict[int, str | None]" = {}
        self._offset = 0
        self._partial = b""
        self._bootstrapped = False
        self._boot_done0 = set(done0 or ())
        self._last_renew = 0.0
        self._malformed = 0
        self._stats = {
            "acquired": 0, "stolen": 0, "speculated": 0,
            "renewals": 0, "released": 0, "flags": 0,
        }
        # bootstrap NOW, not at the first acquire: the historical/live
        # trust boundary must sit at construction (as documented above),
        # or sibling done records appended during this process's warmup
        # would be misread as unverified history and re-executed
        self.refresh()

    # -- log I/O (always OUTSIDE the state lock) ---------------------------
    def _append(self, records: "list[dict]") -> None:
        """Append records, one atomic ``os.write`` per line (the same
        per-line atomicity contract the event log and the manifest's own
        appends rely on)."""
        if not records:
            return
        fd = os.open(self.path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
        try:
            for rec in records:
                os.write(
                    fd, (json.dumps(rec, separators=(",", ":")) + "\n").encode()
                )
        finally:
            os.close(fd)

    def _read_new(self) -> "list[dict]":
        """Read and parse every COMPLETE line appended since the last
        read.  A trailing fragment (a peer's append in progress) is
        carried to the next read; a complete line that does not parse —
        a torn tail later buried by further appends — is skipped and
        counted, never fatal (the blockstore GC's tolerant-reader
        posture; a lost done record at worst re-executes an idempotent
        tile)."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self._offset:
            # the manifest was rewritten under us (resume=False races are
            # documented single-process; be safe, re-read from scratch)
            self._offset = 0
            self._partial = b""
        if size <= self._offset:
            return []
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            data = f.read()
        self._offset += len(data)
        buf = self._partial + data
        lines = buf.split(b"\n")
        self._partial = lines.pop()
        out: "list[dict]" = []
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                self._malformed += 1
                continue
            if isinstance(rec, dict):
                out.append(rec)
            else:
                self._malformed += 1
        return out

    # -- state fold (under the lock; pure) ---------------------------------
    def _apply_locked(self, records: "list[dict]", bootstrap: bool) -> None:
        for rec in records:
            kind = rec.get("kind")
            try:
                if kind == "tile":
                    tid = int(rec["tile_id"])
                    if bootstrap and tid not in self._boot_done0:
                        # historical record whose artifact did NOT verify
                        # (torn-artifact resume): the tile recomputes
                        continue
                    if tid not in self._first_done_owner:
                        self._first_done_owner[tid] = rec.get("owner")
                    self._done.add(tid)
                    self._held.discard(tid)
                elif kind == "lease":
                    tid, gen = int(rec["tile_id"]), int(rec["gen"])
                    owner = str(rec.get("owner", ""))
                    expiry = float(rec.get("t_wall", 0.0)) + float(
                        rec.get("ttl_s", self.ttl_s)
                    )
                    cur = self._leases.get(tid)
                    if cur is None or gen > cur.gen:
                        self._leases[tid] = Lease(
                            gen, owner, expiry, str(rec.get("mode", "claim")),
                            prev_owner=cur.owner if cur is not None else None,
                        )
                    elif gen == cur.gen and owner == cur.owner:
                        # renewal from the winning owner
                        cur.expiry = max(cur.expiry, expiry)
                    # same-gen different-owner: a lost race, ignored
                elif kind == "lease_release":
                    tid, gen = int(rec["tile_id"]), int(rec["gen"])
                    cur = self._leases.get(tid)
                    if (
                        cur is not None
                        and cur.gen == gen
                        and cur.owner == rec.get("owner")
                    ):
                        cur.released = True
                elif kind == "lease_flag":
                    tid, gen = int(rec["tile_id"]), int(rec["gen"])
                    cur = self._leases.get(tid)
                    if cur is not None and cur.gen == gen:
                        cur.flagged = True
                elif kind == "tile_failed":
                    if not bootstrap:
                        # quarantined DURING this run: terminal run-wide
                        # (a resume re-attempts it — historical failures
                        # never block a fresh scope's claims)
                        tid = int(rec["tile_id"])
                        self._failed.add(tid)
                        self._held.discard(tid)
            except (KeyError, TypeError, ValueError):
                self._malformed += 1

    def refresh(self) -> None:
        """Fold newly-appended records into this process's view."""
        bootstrap = not self._bootstrapped
        records = self._read_new()
        with self._lock:
            self._apply_locked(records, bootstrap)
        self._bootstrapped = True

    # -- claims ------------------------------------------------------------
    def _claimable_locked(
        self, now: float, speculate: bool
    ) -> "tuple[list[tuple[int, str, int]], list[tuple[int, int]]]":
        """Candidates ``(tile, mode, next_gen)`` in priority order —
        never-leased first, then released/expired (steals), then (only
        when asked) flagged unexpired foreign leases (speculation) —
        plus the ``blocked`` list of live foreign leases, which the
        caller runs past the ``lease.expire`` fault seam (a firing
        invocation forces that lease to read as expired, so soaks drive
        the steal-while-owner-lives double-execution race on demand)."""
        fresh: "list[tuple[int, str, int]]" = []
        steals: "list[tuple[int, str, int]]" = []
        specs: "list[tuple[int, str, int]]" = []
        blocked: "list[tuple[int, int]]" = []
        for tid in sorted(self._all - self._done - self._failed - self._held):
            cur = self._leases.get(tid)
            if cur is None:
                fresh.append((tid, "claim", 0))
            elif cur.owner == self.owner:
                # our own lease outside _held: a claim we lost track of
                # (e.g. after an abort); reclaimable once released/expired
                if cur.released or now > cur.expiry:
                    steals.append((tid, "steal", cur.gen + 1))
            elif cur.released:
                fresh.append((tid, "claim", cur.gen + 1))
            elif now > cur.expiry:
                steals.append((tid, "steal", cur.gen + 1))
            else:
                if speculate and cur.flagged:
                    specs.append((tid, "spec", cur.gen + 1))
                blocked.append((tid, cur.gen))
        return fresh + steals + specs, blocked

    def acquire(
        self, n: int, speculate: bool = False
    ) -> "list[tuple[int, str, Lease]]":
        """Claim up to ``n`` tiles; returns the claims WON as
        ``(tile_id, mode, lease)`` — mode ``"claim"`` (never leased, or
        cleanly released), ``"steal"`` (TTL-expired lease of a dead or
        wedged peer), or ``"spec"`` (speculative duplicate of a flagged
        straggler; at most one per call, and only when nothing else was
        claimable).  Raises ``OSError``/``RuntimeError`` on the
        ``lease.acquire`` / ``lease.steal`` fault seams or a genuinely
        failing shared filesystem — callers back off and retry, the run
        does not die with the filesystem blip."""
        faults.check("lease.acquire")
        self.refresh()
        now = time.time()
        with self._lock:
            candidates, blocked = self._claimable_locked(now, speculate)
        # the lease.expire behavioral seam: a firing invocation forces a
        # live foreign lease to read as expired — the deterministic
        # steal-under-a-living-owner soak (first durable write wins,
        # artifacts byte-identical).  Checked OUTSIDE the state lock, in
        # tile order, so invocation indices replay across runs.
        forced = [
            (tid, "steal", gen + 1)
            for tid, gen in blocked
            if faults.fired("lease.expire")
        ]
        if forced:
            # forced steals outrank speculation, exactly like real expiries
            forced_ids = {t for t, _, _ in forced}
            regular = [
                c for c in candidates
                if c[1] != "spec" and c[0] not in forced_ids
            ]
            specs = [
                c for c in candidates
                if c[1] == "spec" and c[0] not in forced_ids
            ]
            candidates = regular + forced + specs
        picked: "list[tuple[int, str, int]]" = []
        for tid, mode, gen in candidates:
            if mode == "spec":
                # duplicate work is a targeted tool, not a firehose: one
                # speculative claim per acquisition, and only for an
                # otherwise-idle host (nothing regular was claimable)
                if picked:
                    continue
            picked.append((tid, mode, gen))
            if len(picked) >= max(n, 1):
                break
        if not picked:
            return []
        if any(mode == "steal" for _, mode, _ in picked):
            faults.check("lease.steal")
        t_wall = time.time()
        self._append(
            [
                {
                    "kind": "lease",
                    "tile_id": tid,
                    "gen": gen,
                    "owner": self.owner,
                    "host": socket.gethostname(),
                    "pid": os.getpid(),
                    "ttl_s": self.ttl_s,
                    "t_wall": t_wall,
                    "mode": mode,
                }
                for tid, mode, gen in picked
            ]
        )
        self.refresh()
        won: "list[tuple[int, str, Lease]]" = []
        with self._lock:
            for tid, mode, gen in picked:
                cur = self._leases.get(tid)
                if (
                    cur is not None
                    and cur.gen == gen
                    and cur.owner == self.owner
                    and tid not in self._done
                    and tid not in self._failed
                ):
                    self._held.add(tid)
                    if mode == "spec":
                        self._my_spec.add(tid)
                    won.append((tid, mode, cur))
            self._stats["acquired"] += len(won)
            self._stats["stolen"] += sum(1 for _, m, _ in won if m == "steal")
            self._stats["speculated"] += sum(
                1 for _, m, _ in won if m == "spec"
            )
        return won

    def renew(self, min_interval: "float | None" = None) -> int:
        """Extend held, unfinished leases (rate-limited to ``ttl/3`` by
        default).  Returns the number of renewal records appended.  A
        failed renewal is logged and retried next tick — the worst case
        is a sibling stealing a tile we then both finish, byte-identically."""
        interval = self.ttl_s / 3.0 if min_interval is None else min_interval
        now = time.monotonic()
        if now - self._last_renew < interval:
            return 0
        self._last_renew = now
        with self._lock:
            held = sorted(self._held - self._done - self._failed)
            gens = {
                t: self._leases[t].gen for t in held if t in self._leases
            }
        if not held:
            return 0
        t_wall = time.time()
        try:
            self._append(
                [
                    {
                        "kind": "lease",
                        "tile_id": t,
                        "gen": gens.get(t, 0),
                        "owner": self.owner,
                        "ttl_s": self.ttl_s,
                        "t_wall": t_wall,
                        "mode": "renew",
                    }
                    for t in held
                ]
            )
        except OSError as e:
            log.warning("lease renewal append failed (%s); will retry", e)
            return 0
        with self._lock:
            for t in held:
                cur = self._leases.get(t)
                if cur is not None and cur.owner == self.owner:
                    cur.expiry = max(cur.expiry, t_wall + self.ttl_s)
            self._stats["renewals"] += len(held)
        return len(held)

    def flag(self, tile_id: int) -> bool:
        """Advertise a held tile as a straggler (the StragglerDetector
        verdict hook): idle peers may then speculatively re-lease it.
        Safe from any thread; returns True when the flag was appended."""
        with self._lock:
            if tile_id not in self._held or tile_id in self._done:
                return False
            cur = self._leases.get(tile_id)
            gen = cur.gen if cur is not None and cur.owner == self.owner else 0
        self._append(
            [
                {
                    "kind": "lease_flag",
                    "tile_id": int(tile_id),
                    "gen": gen,
                    "owner": self.owner,
                    "t_wall": time.time(),
                }
            ]
        )
        with self._lock:
            cur = self._leases.get(tile_id)
            if cur is not None and cur.gen == gen:
                cur.flagged = True
            self._stats["flags"] += 1
        return True

    def release_held(self, reason: str = "released") -> int:
        """Relinquish every held, unfinished lease (abort/cancel unwind):
        siblings may claim immediately instead of waiting out the TTL.
        Best-effort — a failed release just means TTL-paced stealing."""
        with self._lock:
            held = sorted(self._held - self._done)
            gens = {
                t: self._leases[t].gen for t in held if t in self._leases
            }
            self._held.clear()
        if not held:
            return 0
        try:
            self._append(
                [
                    {
                        "kind": "lease_release",
                        "tile_id": t,
                        "gen": gens.get(t, 0),
                        "owner": self.owner,
                        "t_wall": time.time(),
                        "reason": reason,
                    }
                    for t in held
                ]
            )
        except OSError as e:
            log.warning(
                "lease release append failed (%s); peers steal after TTL", e
            )
            return 0
        with self._lock:
            self._stats["released"] += len(held)
        return len(held)

    # -- run state ---------------------------------------------------------
    def run_complete(self) -> bool:
        """True once every tile is durably done (or quarantined this
        run) — the elastic loop's exit condition."""
        self.refresh()
        with self._lock:
            return not (self._all - self._done - self._failed)

    def undone(self) -> "set[int]":
        with self._lock:
            return set(self._all - self._done - self._failed)

    def held(self) -> "set[int]":
        with self._lock:
            return set(self._held)

    def stats(self) -> dict:
        """Point-in-time lease counters (plus the speculative-win count:
        tiles WE speculated whose first durable done record is ours)."""
        with self._lock:
            wins = sum(
                1
                for t in self._my_spec
                if self._first_done_owner.get(t) == self.owner
            )
            return {
                **self._stats,
                "spec_wins": wins,
                "held": len(self._held),
                "done": len(self._done),
                "failed": len(self._failed),
                "malformed_lines": self._malformed,
            }
