"""Persistent XLA compilation cache wiring (VERDICT r3 next-round item #1).

Round 3's one ~20-minute TPU availability window was mostly burned on
first-compile and bench misfires (``TPU_PROBE_r03.md``): every cold process
paid the full XLA compile again, and the window closed before the rebuilt
chain-mode bench could compile+run.  With a persistent on-disk cache
(``jax_compilation_cache_dir``), compilation work done by ANY process —
including an attempt that later dies at readback, the observed round-3
failure mode — survives to the next attempt, so a reopened window spends
its seconds executing instead of compiling.

Every entry point that might run inside a TPU window calls
:func:`enable_persistent_cache` before touching a device: ``bench.py``
(child process), the CLI driver, ``tools/parity_f32.py``,
``tools/profile_stages.py``, and ``__graft_entry__``.  The watchers
(``tools/bench_watch.sh`` / ``tools/tpu_followup.sh``) inherit it through
``bench.py``/``parity_f32.py``.

Knobs (all env-overridable so the watchers and ad-hoc shells agree):

* ``LT_COMPILE_CACHE`` — cache directory (default
  ``<repo>/.jax_compile_cache``); ``0``/``off`` disables entirely.
* min-compile-time / min-entry-size thresholds are forced to 0 so even
  sub-second helper jits (pad/gather/stack ops) are cached: on this box a
  cold CPU process accumulates tens of small compiles around the two big
  kernel compiles, and the point is time-to-first-timed-rep, not disk.

The cache key includes backend + topology, so CPU-mesh test runs, the
single-chip bench, and the 8-device dryrun each get distinct entries in
the same directory without interference.  Proof artifact:
``tools/cache_proof.py`` (CACHE_r04.json) measures a cold process
reaching its first timed bench rep with a warm cache.
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_CACHE_DIR = os.path.join(_REPO_ROOT, ".jax_compile_cache")

_enabled_dir: str | None = None


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a shared on-disk dir.

    Idempotent; safe to call before or after backend init (jax.config
    updates apply to subsequent compilations).  Returns the directory in
    use, or ``None`` when disabled via ``LT_COMPILE_CACHE=0``.
    """
    global _enabled_dir
    env = os.environ.get("LT_COMPILE_CACHE", "").strip()
    if env.lower() in ("0", "off", "none", "disable"):
        return None
    cache_dir = cache_dir or env or DEFAULT_CACHE_DIR
    if _enabled_dir == cache_dir:
        return _enabled_dir

    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_enable_compilation_cache", True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache EVERYTHING: the helper jits around the main kernel are
    # individually cheap but collectively tens of seconds on a cold start
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled_dir = cache_dir
    return cache_dir
