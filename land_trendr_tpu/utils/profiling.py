"""Tracing and profiling harness (SURVEY.md §5 "Tracing/profiling").

The reference's only observability is Hadoop job counters and task logs;
the TPU-native answer is device-level traces plus stage attribution:

* the segmentation kernel's stages are wrapped in ``jax.named_scope``
  (``lt_despike``, ``lt_vertex_search``, ``lt_angle_cull``,
  ``lt_model_family``, ``lt_model_select`` — :mod:`land_trendr_tpu.ops.
  segment`), so compiled-HLO op metadata and profiler timelines attribute
  time to algorithm stages, not fused-op soup;
* :func:`trace` wraps ``jax.profiler.trace`` — the resulting logdir opens
  in TensorBoard's profile plugin or Perfetto;
* :func:`profile_op` is the one-call version: warm up (compile), then
  trace N steady-state iterations;
* :class:`StageTimer` is the host-side complement for driver-loop phases —
  the runtime driver wraps feed / compute / write with it and merges the
  totals into its run summary (``stage_s`` key), where a device trace
  can't see Python time.

Nothing here is TPU-only; the same calls profile the CPU backend.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Callable, Iterator

import jax

__all__ = [
    "trace",
    "profile_op",
    "capture_profile",
    "StageTimer",
    "STAGE_SCOPES",
]

#: named_scope labels emitted by the segmentation kernel, in pipeline order.
#: Single source of truth — :mod:`land_trendr_tpu.ops.segment` imports these.
SCOPE_DESPIKE = "lt_despike"
SCOPE_VERTEX_SEARCH = "lt_vertex_search"
SCOPE_ANGLE_CULL = "lt_angle_cull"
SCOPE_MODEL_FAMILY = "lt_model_family"
SCOPE_MODEL_SELECT = "lt_model_select"
STAGE_SCOPES = (
    SCOPE_DESPIKE,
    SCOPE_VERTEX_SEARCH,
    SCOPE_ANGLE_CULL,
    SCOPE_MODEL_FAMILY,
    SCOPE_MODEL_SELECT,
)


@contextlib.contextmanager
def trace(
    logdir: str, *, perfetto: bool = False, perfetto_link: bool = False
) -> Iterator[str]:
    """Capture a device+host profiler trace under ``logdir``.

    Thin wrapper over ``jax.profiler.trace`` that creates the directory and
    yields its path; view with ``tensorboard --logdir <logdir>`` (profile
    plugin).  ``perfetto=True`` additionally writes a ``*.perfetto-trace``
    file loadable in ui.perfetto.dev; ``perfetto_link=True`` also blocks at
    exit printing a clickable link (interactive use only).
    """
    os.makedirs(logdir, exist_ok=True)
    with jax.profiler.trace(
        logdir,
        create_perfetto_trace=perfetto or perfetto_link,
        create_perfetto_link=perfetto_link,
    ):
        yield logdir


def profile_op(
    fn: Callable[..., Any],
    *args: Any,
    logdir: str,
    iters: int = 3,
    **kwargs: Any,
) -> dict[str, float]:
    """Warm up ``fn`` (one untraced call — compilation stays out of the
    trace), then trace ``iters`` steady-state calls.

    Returns ``{"wall_s_per_iter": ..., "logdir_bytes": ...}`` so callers can
    sanity-check that the trace actually captured something;
    ``logdir_bytes`` counts only bytes written by *this* trace (a reused
    logdir's stale files are excluded).
    """

    def _tree_bytes() -> int:
        return sum(
            os.path.getsize(os.path.join(root, f))
            for root, _, files in os.walk(logdir)
            for f in files
        )

    out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    before = _tree_bytes() if os.path.isdir(logdir) else 0
    with trace(logdir):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
        dt = time.perf_counter() - t0
    return {
        "wall_s_per_iter": dt / iters,
        "logdir_bytes": float(_tree_bytes() - before),
    }


# one capture at a time: jax's profiler session is a process-global
# singleton — a second concurrent start_trace raises deep inside it.
# The flag flips under a lock; the capture itself (a multi-second sleep)
# runs OUTSIDE any lock.
_capture_active = False
_capture_flag_lock = threading.Lock()


def _capture_begin() -> None:
    global _capture_active
    with _capture_flag_lock:
        if _capture_active:
            raise RuntimeError(
                "a profiler capture is already in flight (the jax profiler "
                "is process-global; retry when it finishes)"
            )
        _capture_active = True


def _capture_end() -> None:
    global _capture_active
    with _capture_flag_lock:
        _capture_active = False


def capture_profile(logdir: str, duration_s: float) -> dict:
    """On-demand, duration-bounded device+host capture of a LIVE run.

    The ``POST /debug/profile`` workhorse: opens a ``jax.profiler``
    trace under ``logdir`` and holds it open for ``duration_s`` —
    whatever the process's other threads (the serve dispatcher, the tile
    pipeline, transfer waits) do in that window is what the trace shows.
    Returns ``{"path", "duration_s", "bytes"}`` (``bytes`` counts only
    this capture's output — a sanity check that the profiler actually
    wrote something).  Raises ``RuntimeError`` when a capture is already
    in flight, and ``ValueError`` on a non-positive duration.
    """
    if duration_s <= 0:
        raise ValueError(f"duration_s={duration_s} must be > 0")
    _capture_begin()
    try:
        t0 = time.perf_counter()

        def _tree_bytes() -> int:
            return sum(
                os.path.getsize(os.path.join(root, f))
                for root, _, files in os.walk(logdir)
                for f in files
            )

        before = _tree_bytes() if os.path.isdir(logdir) else 0
        with trace(logdir):
            time.sleep(duration_s)
        return {
            "path": logdir,
            "duration_s": round(time.perf_counter() - t0, 6),
            "bytes": int(_tree_bytes() - before),
        }
    finally:
        _capture_end()


class StageTimer:
    """Accumulating wall-clock timer for host-side driver phases.

    The runtime driver wraps its feed / compute / write phases so the run
    summary reports where host time went — the host-side complement to the
    device trace (device kernels show up there, Python/NumPy time here).
    Thread-safe: accumulation holds a lock, so concurrent writers (the
    driver's ``write_workers`` pool) may share one stage name; their
    accumulated seconds then sum ACROSS threads and can exceed wall time.

    >>> timer = StageTimer()
    >>> with timer.stage("feed"):
    ...     pass
    >>> timer.totals()["feed"] >= 0.0
    True
    """

    def __init__(self) -> None:
        self._acc: dict[str, float] = {}
        self._n: dict[str, int] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._acc[name] = self._acc.get(name, 0.0) + dt
                self._n[name] = self._n.get(name, 0) + 1

    def totals(self) -> dict[str, float]:
        """Stage → accumulated seconds.

        Locked like the accumulators: a ``dict()`` copy racing a stage
        exit in a writer/feeder thread is a ``dictionary changed size
        during iteration`` crash, not just a stale read (LT001).
        """
        with self._lock:
            return dict(self._acc)

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._n)

    def summary(self) -> dict[str, float]:
        """Flat ``{stage}_s`` dict, rounded — ready to merge into run logs."""
        with self._lock:
            return {f"{k}_s": round(v, 4) for k, v in self._acc.items()}
