"""Autotuned execution profiles: probe, persist, resolve.

The engine's knob space — ``tile_size``, ``chunk_px``, ``fetch_depth``,
``upload_depth``, ``feed_workers``, ``decode_workers``,
``feed_cache_mb`` — shipped hardcoded defaults tuned once by hand on one
host.  This module closes ROADMAP item 4's autotuning half:

* :func:`autotune` runs the staged calibration probes
  (:mod:`~land_trendr_tpu.tune.probes`, one short probe per knob group,
  coordinate-wise with median-of-reps timing and early cutoff) and
  persists the winning profile to the on-disk
  :class:`~land_trendr_tpu.tune.store.TuningStore` keyed by
  ``(device_kind, backend, scene shape class, TUNE_SCHEMA)``.  A key
  already in the store is **reloaded on sight with ZERO probes**
  (``tune_profile`` event ``source="store"``, ``probes=0``); only a key
  miss or ``retune=True`` probes again.
* :func:`resolve_config` makes the knobs *resolve*: ``RunConfig`` fields
  set to the ``"auto"`` sentinel pull their value from the loaded
  profile at ``Run`` construction.  Explicit values ALWAYS win; with no
  store (or no profile for the key) every ``"auto"`` resolves to the
  hardcoded default — byte-identical to the pre-autotuner behavior.
  Resolution never probes and never writes: it is a deterministic store
  read, so two resolutions of the same key give identical knob values.

Fault semantics (the ``tune.probe`` seam, :mod:`land_trendr_tpu.runtime.
faults`): a probe failure — injected or real — skips THAT knob group
(its knobs fall back to defaults, the ``tune_probe`` event carries
``ok=false``) and never fails the tuner or skews the run behind it.

Observability: ``telemetry`` (a :class:`~land_trendr_tpu.obs.telemetry.
Telemetry`) receives one ``tune_probe`` event per probed group and one
terminal ``tune_profile`` event per autotune/resolution, and advances
the ``lt_tune_*`` instruments; ``None`` keeps the tuner silent.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

from land_trendr_tpu.tune.store import (
    TUNE_SCHEMA,
    TuningStore,
    profile_key,
    shape_class,
)

__all__ = [
    "AUTO",
    "KNOB_DEFAULTS",
    "TUNABLE_KNOBS",
    "autotune",
    "device_identity",
    "resolve_config",
]

#: the RunConfig sentinel ``"auto"`` fields resolve through the profile
AUTO = "auto"

#: every RunConfig field the tuner may own (the ISSUE's knob space minus
#: the packed on/off strategies, which already carry their own "auto"
#: backend resolution in runtime/feed + runtime/fetch)
TUNABLE_KNOBS = (
    "tile_size",
    "chunk_px",
    "fetch_depth",
    "upload_depth",
    "feed_workers",
    "decode_workers",
    "feed_cache_mb",
)

#: the hardcoded RunConfig defaults — what ``"auto"`` means with no
#: profile.  Mirrors the dataclass defaults; ``tests/test_tune.py``
#: asserts the two cannot drift (the config module cannot be imported
#: here: runtime/driver imports this module for resolution).
KNOB_DEFAULTS: dict[str, Any] = {
    "tile_size": 256,
    "chunk_px": 262_144,
    "fetch_depth": 2,
    "upload_depth": 2,
    "feed_workers": 1,
    "decode_workers": 0,
    "feed_cache_mb": 256,
}


def device_identity() -> "tuple[str, str]":
    """``(device_kind, backend)`` of this process's default JAX device —
    the hardware half of the store key.  Imported lazily: resolution with
    no ``"auto"`` fields (every pre-existing config) must not initialise
    a backend as a side effect."""
    import jax

    backend = jax.default_backend()
    try:
        kind = jax.local_devices()[0].device_kind
    except Exception:
        kind = backend
    return str(kind), str(backend)


def autotune(
    store_dir: str,
    *,
    height: int,
    width: int,
    n_years: int,
    groups: "tuple[str, ...] | None" = None,
    reps: int = 3,
    smoke: bool = False,
    retune: bool = False,
    persist: bool = True,
    telemetry=None,
    device_kind: "str | None" = None,
    backend: "str | None" = None,
) -> dict:
    """Probe (or reload) the profile for this device + scene class.

    Returns the profile dict; ``profile["probes"] == 0`` means a store
    hit served it without running anything.  ``persist=False`` is the
    ``lt tune --dry-run`` contract: probe and report, write nothing.
    ``groups`` restricts probing to a subset (unnamed groups keep their
    default knobs); ``smoke`` shrinks every probe workload to seconds
    scale.  ``device_kind``/``backend`` override the JAX identity — the
    testing seam key-miss re-probe rides on.
    """
    from land_trendr_tpu.runtime import faults
    from land_trendr_tpu.tune import probes as probemod

    if device_kind is None or backend is None:
        dk, be = device_identity()
        device_kind = device_kind or dk
        backend = backend or be
    shape_cls = shape_class(height, width, n_years)
    key = profile_key(device_kind, backend, shape_cls)
    store = TuningStore(store_dir)

    if not retune:
        profile = store.load(device_kind, backend, shape_cls)
        if profile is not None:
            if telemetry is not None:
                telemetry.tune_profile(
                    key=key,
                    source="store",
                    probes=0,
                    age_s=max(0.0, time.time() - float(profile["created_t"])),
                    knobs=dict(profile["knobs"]),
                    groups=len(profile.get("groups", {})),
                )
            # "source" is EPHEMERAL caller information (store hit = zero
            # probes ran), never persisted — stored bytes stay canonical
            return {**profile, "source": "store", "key": key}

    group_names = tuple(groups) if groups is not None else tuple(
        probemod.PROBE_GROUPS
    )
    unknown = [g for g in group_names if g not in probemod.PROBE_GROUPS]
    if unknown:
        raise ValueError(
            f"unknown probe group(s) {unknown}; choose from "
            f"{tuple(probemod.PROBE_GROUPS)}"
        )

    knobs = dict(KNOB_DEFAULTS)
    group_reports: dict[str, dict] = {}
    total_probes = 0
    for group in group_names:
        t0 = time.perf_counter()
        try:
            # the tune.probe fault seam: an injected (or real) probe
            # failure skips THIS group — defaults survive, the tuner and
            # the run behind it live
            faults.check("tune.probe")
            best, report = probemod.probe_group(
                group, reps=reps, smoke=smoke, defaults=KNOB_DEFAULTS
            )
        except Exception as e:
            wall = time.perf_counter() - t0
            group_reports[group] = {
                "ok": False,
                "probes": 0,
                "error": str(e),
                "wall_s": round(wall, 6),
            }
            if telemetry is not None:
                telemetry.tune_probe(
                    group=group, ok=False, probes=0, wall_s=wall, error=str(e)
                )
            continue
        wall = time.perf_counter() - t0
        knobs.update(best)
        total_probes += int(report.get("probes", 0))
        group_reports[group] = {
            "ok": True,
            "knobs": best,
            "wall_s": round(wall, 6),
            **report,
        }
        if telemetry is not None:
            telemetry.tune_probe(
                group=group,
                ok=True,
                probes=int(report.get("probes", 0)),
                wall_s=wall,
                speedup=report.get("speedup"),
                knobs=dict(best),
            )

    profile = {
        "schema": TUNE_SCHEMA,
        "device_kind": device_kind,
        "backend": backend,
        "shape_class": shape_cls,
        "created_t": time.time(),
        "probes": total_probes,
        "knobs": knobs,
        "groups": group_reports,
    }
    if persist:
        store.save(profile)
    if telemetry is not None:
        telemetry.tune_profile(
            key=key,
            source="probed",
            probes=total_probes,
            age_s=0.0,
            knobs=dict(knobs),
            groups=len(group_reports),
        )
    return {**profile, "source": "probed", "key": key}


def resolve_config(cfg, scene_shape: "tuple[int, int, int] | None" = None):
    """Resolve a RunConfig's ``"auto"`` knobs; returns ``(cfg, info)``.

    ``scene_shape`` is ``(height, width, n_years)`` — the shape-class
    half of the store key.  With no ``"auto"`` field the config passes
    through untouched (``info=None``, zero overhead, no JAX or store
    access).  Otherwise each ``"auto"`` field takes the loaded profile's
    value (store hit) or the hardcoded default (no store configured, key
    miss, or no shape to key on) — explicit values always win by
    construction, since only ``"auto"`` fields are replaced.  ``info``
    is the ``tune_profile`` event payload (``probes`` is always 0 here:
    resolution never probes).
    """
    auto_fields = [f for f in TUNABLE_KNOBS if getattr(cfg, f) == AUTO]
    if not auto_fields:
        return cfg, None
    profile = None
    key = ""
    if cfg.tune_store_dir and scene_shape is not None:
        device_kind, backend = device_identity()
        shape_cls = shape_class(*scene_shape)
        key = profile_key(device_kind, backend, shape_cls)
        profile = TuningStore(cfg.tune_store_dir).load(
            device_kind, backend, shape_cls
        )
    knobs = {
        f: (
            profile["knobs"].get(f, KNOB_DEFAULTS[f])
            if profile is not None
            else KNOB_DEFAULTS[f]
        )
        for f in auto_fields
    }
    info: dict[str, Any] = {
        "key": key,
        "source": "store" if profile is not None else "defaults",
        "probes": 0,
        "knobs": knobs,
    }
    if profile is not None:
        info["age_s"] = round(
            max(0.0, time.time() - float(profile["created_t"])), 3
        )
    return dataclasses.replace(cfg, **knobs), info
