"""Autotuned execution profiles (ROADMAP item 4's autotuning half).

Per-device calibration probes (:mod:`~land_trendr_tpu.tune.probes`), a
persisted tuning store keyed by ``(device_kind, backend, scene shape
class, schema)`` (:mod:`~land_trendr_tpu.tune.store`), and auto-resolved
run knobs (:func:`~land_trendr_tpu.tune.autotune.resolve_config` — the
``RunConfig`` ``"auto"`` sentinel's engine).
"""

from land_trendr_tpu.tune.autotune import (
    AUTO,
    KNOB_DEFAULTS,
    TUNABLE_KNOBS,
    autotune,
    device_identity,
    resolve_config,
)
from land_trendr_tpu.tune.store import (
    TUNE_SCHEMA,
    TuningStore,
    profile_key,
    shape_class,
)

__all__ = [
    "AUTO",
    "KNOB_DEFAULTS",
    "TUNABLE_KNOBS",
    "TUNE_SCHEMA",
    "TuningStore",
    "autotune",
    "device_identity",
    "profile_key",
    "resolve_config",
    "shape_class",
]
