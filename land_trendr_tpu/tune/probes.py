"""Staged calibration probes, one per knob group.

Each probe measures a short synthetic workload shaped like the real
subsystem it tunes — the threaded tile gather for ``feed_workers``, the
windowed deflate decode through the real :mod:`~land_trendr_tpu.io.
blockcache` for ``decode_workers``/``feed_cache_mb``, the packed
host↔device transfer pipelines for ``upload_depth``/``fetch_depth``, and
the host per-tile pipeline overhead for ``tile_size`` (with a sliced
segment-kernel sweep for ``chunk_px`` in full mode) — and returns the
winning knob values plus a report.  The search is **coordinate-wise**
within a group (later knobs sweep with earlier winners held), each
candidate is timed **median-of-reps**, and a candidate whose FIRST rep
already exceeds :data:`CUTOFF` × the best median so far is cut off early
(no point confirming a clear loser to three decimals).

Contract with the autotuner:

* every candidate set CONTAINS the hardcoded default, and ``default_s``
  is that candidate's median — so ``best_s <= default_s`` holds by
  construction (a probe can only match or beat the default, never
  regress it), which is what lets the perf gate pin "tuned ≥ default"
  structurally.
* probes never skew the run that follows: anything process-global they
  touch (the decoded-block cache configuration) is snapshotted and
  restored in a ``finally``, and all probe inputs are synthetic
  temporaries.
* probes are honest about scale: they calibrate *balance points* (worker
  counts, depths, granularity), not absolute throughput — the knobs
  whose right values the paper's continental runs show dominate
  end-to-end wall (arXiv:1807.01751), not kernel FLOPs.
"""

from __future__ import annotations

import os
import shutil
import statistics
import tempfile
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

import numpy as np

__all__ = ["CUTOFF", "PROBE_GROUPS", "probe_group"]

#: early-cutoff factor: a candidate whose first rep exceeds this multiple
#: of the best median so far skips its remaining reps
CUTOFF = 1.5


def _median_reps(
    fn: Callable[[], None], reps: int, best_so_far: "float | None"
) -> "tuple[float, int]":
    """(median seconds, reps actually run) with the early cutoff."""
    times: list[float] = []
    for i in range(max(1, reps)):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
        if i == 0 and best_so_far is not None and times[0] > CUTOFF * best_so_far:
            break
    return statistics.median(times), len(times)


def _sweep(
    candidates: list, make_fn: Callable, reps: int, default
) -> "tuple[object, dict]":
    """Time every candidate; return (winner, report).

    ``make_fn(candidate)`` returns the zero-arg workload to time.  The
    winner is the min median; ``default_s`` is the default candidate's
    median (always measured in full — the cutoff never skips it, since a
    skipped default would leave ``best_s <= default_s`` unprovable).
    """
    best_val, best_s, default_s = None, None, None
    probes = 0
    timings: dict[str, float] = {}
    order = [default] + [c for c in candidates if c != default]
    for cand in order:
        fn = make_fn(cand)
        cutoff_ref = None if cand == default else best_s
        med, n = _median_reps(fn, reps, cutoff_ref)
        probes += n
        timings[str(cand)] = round(med, 6)
        if cand == default:
            default_s = med
        if best_s is None or med < best_s:
            best_val, best_s = cand, med
    return best_val, {
        "probes": probes,
        "timings": timings,
        "default_s": round(default_s, 6),
        "best_s": round(best_s, 6),
        "speedup": round(default_s / best_s, 3) if best_s > 0 else 1.0,
    }


# -- feed group: the threaded tile gather ---------------------------------

def probe_feed(reps: int, smoke: bool, defaults: dict) -> "tuple[dict, dict]":
    """``feed_workers``: threaded native/NumPy tile gather throughput.

    The gather releases the GIL (threaded C++ codec; NumPy copies mostly
    do too), so worker count tracks real cores — HOSTPATH_r03.json's
    4.1M px/s/core budget is exactly what this probe localizes.
    """
    from land_trendr_tpu.io import native

    ny = 8 if smoke else 16
    size = 384 if smoke else 768
    t_sz = 128
    rng = np.random.default_rng(7)
    cube = rng.integers(0, 1000, (ny, size, size), dtype=np.int16)
    tiles = [(y, x) for y in range(0, size, t_sz) for x in range(0, size, t_sz)]

    def gather(t: "tuple[int, int]") -> np.ndarray:
        y, x = t
        if native.available():
            try:
                return native.gather_tile(cube, y, x, t_sz, t_sz)
            except native.NativeCodecError:
                pass
        win = cube[:, y : y + t_sz, x : x + t_sz]
        return np.ascontiguousarray(win.reshape(ny, t_sz * t_sz).T)

    cpus = os.cpu_count() or 1
    cands = sorted({1, 2, min(4, cpus + 1), defaults["feed_workers"]})

    def make_fn(workers: int):
        def run() -> None:
            with ThreadPoolExecutor(workers) as ex:
                deque(ex.map(gather, tiles), maxlen=0)
        return run

    make_fn(1)()  # warm: page the cube in before anything is timed
    best, report = _sweep(cands, make_fn, reps, defaults["feed_workers"])
    return {"feed_workers": int(best)}, report


# -- decode group: the real blockcache path -------------------------------

def probe_decode(reps: int, smoke: bool, defaults: dict) -> "tuple[dict, dict]":
    """``decode_workers`` + ``feed_cache_mb`` over the real windowed
    deflate decode (:func:`~land_trendr_tpu.io.geotiff.
    read_geotiff_window` through the process blockcache).

    Coordinate-wise: the worker sweep runs cache-off (pure decode cost),
    then the cache sweep replays a revisit-heavy window pattern with the
    chosen workers.  The process cache configuration is snapshotted and
    restored whatever happens — a probe must never skew the run behind
    it.
    """
    from land_trendr_tpu.io import blockcache
    from land_trendr_tpu.io.geotiff import read_geotiff_window
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack

    size = 128 if smoke else 256
    ny = 3 if smoke else 6
    tmp = tempfile.mkdtemp(prefix="lt_tune_decode_")
    snap = blockcache.config_snapshot()
    try:
        paths = write_stack(
            tmp,
            make_stack(SceneSpec(
                width=size, height=size,
                year_start=2000, year_end=2000 + ny - 1,
            )),
            tile=64,
        )
        win = size - 96
        windows = [(0, 0), (32, 32), (win, 0), (0, win), (win, win)]

        def read_all() -> None:
            for p in paths:
                for y, x in windows:
                    read_geotiff_window(p, y, x, 96, 96)

        cpus = os.cpu_count() or 1
        w_cands = sorted({0, 1, min(2, cpus), defaults["decode_workers"]})

        def make_workers_fn(workers: int):
            def run() -> None:
                blockcache.configure(budget_bytes=0, workers=workers)
                read_all()
            return run

        make_workers_fn(0)()  # warm: the OS file cache, untimed
        best_w, w_report = _sweep(
            w_cands, make_workers_fn, reps, defaults["decode_workers"]
        )

        c_cands = sorted({0, defaults["feed_cache_mb"]})

        def make_cache_fn(mb: int):
            def run() -> None:
                blockcache.configure(budget_bytes=mb << 20, workers=best_w)
                read_all()  # cold pass populates (or not)
                read_all()  # revisit pass: the cache's whole case
            return run

        best_c, c_report = _sweep(
            c_cands, make_cache_fn, reps, defaults["feed_cache_mb"]
        )
        report = {
            "probes": w_report["probes"] + c_report["probes"],
            "timings": {
                **{f"workers={k}": v for k, v in w_report["timings"].items()},
                **{f"cache_mb={k}": v for k, v in c_report["timings"].items()},
            },
            "default_s": round(
                w_report["default_s"] + c_report["default_s"], 6
            ),
            "best_s": round(w_report["best_s"] + c_report["best_s"], 6),
            "speedup": round(
                (w_report["default_s"] + c_report["default_s"])
                / max(w_report["best_s"] + c_report["best_s"], 1e-9), 3,
            ),
        }
        return (
            {"decode_workers": int(best_w), "feed_cache_mb": int(best_c)},
            report,
        )
    finally:
        blockcache.configure(**snap)
        blockcache.cache_clear()
        shutil.rmtree(tmp, ignore_errors=True)


# -- upload / fetch groups: the packed-transfer pipelines ------------------

def _transfer_tiles(smoke: bool) -> "tuple[dict, np.ndarray, int]":
    px = 64 * 64 if smoke else 128 * 128
    ny = 8 if smoke else 16
    rng = np.random.default_rng(11)
    dn = {
        "nir": rng.integers(0, 30000, (px, ny), dtype=np.int16),
        "swir2": rng.integers(0, 30000, (px, ny), dtype=np.int16),
    }
    qa = rng.integers(0, 2, (px, ny), dtype=np.uint16)
    return dn, qa, (4 if smoke else 8)


def probe_upload(reps: int, smoke: bool, defaults: dict) -> "tuple[dict, dict]":
    """``upload_depth``: the packed host→device pipeline at each depth.

    One packed ``device_put`` per tile with up to ``depth`` transfers in
    flight (the driver's exact double-buffering shape, minus the kernel);
    a tiny device op stands in for the overlapped compute.  On backends
    where the transfer is not a real wire (CPU) every depth ties and the
    default survives — exactly the right answer there.
    """
    import jax
    import jax.numpy as jnp

    from land_trendr_tpu.runtime import feed as feedmod

    dn, qa, k_tiles = _transfer_tiles(smoke)
    plan = feedmod.build_plan(dn, qa)
    packed = feedmod.pack_inputs(dn, qa, plan=plan)

    def make_fn(depth: int):
        # the unusable-donation warning (CPU) is filtered once at
        # runtime/feed.py import — nothing to suppress per sweep
        def run() -> None:
            inflight: deque = deque()
            for _ in range(k_tiles):
                inflight.append(jax.device_put(packed))
                while len(inflight) >= depth:
                    words = inflight.popleft()
                    out, _qa = feedmod.unpack_inputs(words, plan=plan)
                    jax.block_until_ready(jnp.sum(out["nir"]))
            while inflight:
                out, _qa = feedmod.unpack_inputs(
                    inflight.popleft(), plan=plan
                )
                jax.block_until_ready(jnp.sum(out["nir"]))
        return run

    cands = sorted({1, 2, 4, defaults["upload_depth"]})
    # warm the unpack + reduce compiles OUTSIDE the sweep: the first
    # timed candidate must not carry the jit compile every other one
    # skips (that asymmetry fabricated a 15x "speedup" in review)
    make_fn(cands[0])()
    best, report = _sweep(cands, make_fn, reps, defaults["upload_depth"])
    return {"upload_depth": int(best)}, report


def probe_fetch(reps: int, smoke: bool, defaults: dict) -> "tuple[dict, dict]":
    """``fetch_depth``: the device→host readback pipeline at each depth —
    one async ``device_get``-shaped landing per tile with up to ``depth``
    in flight while a stand-in compute runs ahead."""
    import jax
    import jax.numpy as jnp

    px = 64 * 64 if smoke else 128 * 128
    k_tiles = 4 if smoke else 8
    base = jax.device_put(np.arange(px, dtype=np.float32))
    step = jax.jit(lambda a, i: a * (1.0 + i))
    jax.block_until_ready(step(base, 1.0))

    def make_fn(depth: int):
        def run() -> None:
            inflight: deque = deque()
            for i in range(k_tiles):
                out = step(base, float(i))
                inflight.append(out)
                while len(inflight) >= depth:
                    np.asarray(inflight.popleft())
            while inflight:
                np.asarray(inflight.popleft())
        return run

    cands = sorted({1, 2, 4, defaults["fetch_depth"]})
    make_fn(cands[0])()  # warm outside the sweep, like probe_upload
    best, report = _sweep(cands, make_fn, reps, defaults["fetch_depth"])
    return {"fetch_depth": int(best)}, report


# -- dispatch group: tile granularity + chunking --------------------------

def probe_dispatch(reps: int, smoke: bool, defaults: dict) -> "tuple[dict, dict]":
    """``tile_size`` (+ ``chunk_px`` in full mode).

    ``tile_size`` is probed through the host per-tile pipeline cost —
    gather + pack for a FIXED total pixel budget cut at each granularity
    (smaller tiles pay per-tile overhead more often; larger tiles
    amortize it) — the cheap, safe signal; kernel px/s is roughly
    granularity-invariant.  ``chunk_px`` (full mode only) times the
    sliced segment kernel against the candidate chunk sizes on a small
    batch; candidates stay within the default HBM bound, because the
    knob is a memory bound first and a perf knob second.
    """
    from land_trendr_tpu.io import native
    from land_trendr_tpu.runtime import feed as feedmod

    ny = 8 if smoke else 16
    total = 256 if smoke else 512  # total scene edge the budget covers
    rng = np.random.default_rng(13)
    cube = rng.integers(0, 30000, (ny, total, total), dtype=np.int16)
    qa_cube = rng.integers(0, 2, (ny, total, total), dtype=np.uint16)

    def make_fn(t_sz: int):
        def run() -> None:
            plan = None
            for y in range(0, total, t_sz):
                for x in range(0, total, t_sz):
                    if native.available():
                        nir = native.gather_tile(cube, y, x, t_sz, t_sz)
                        qa = native.gather_tile(qa_cube, y, x, t_sz, t_sz)
                    else:
                        nir = np.ascontiguousarray(
                            cube[:, y : y + t_sz, x : x + t_sz]
                            .reshape(ny, t_sz * t_sz).T
                        )
                        qa = np.ascontiguousarray(
                            qa_cube[:, y : y + t_sz, x : x + t_sz]
                            .reshape(ny, t_sz * t_sz).T
                        )
                    dn = {"nir": nir}
                    if plan is None or plan.px != nir.shape[0]:
                        plan = feedmod.build_plan(dn, qa)
                    feedmod.pack_inputs(dn, qa, plan=plan)
        return run

    cands = sorted({64, 128, 256, 512, defaults["tile_size"]})
    cands = [c for c in cands if c <= total]
    best_t, report = _sweep(cands, make_fn, reps, defaults["tile_size"])
    knobs = {"tile_size": int(best_t), "chunk_px": defaults["chunk_px"]}
    if not smoke:
        chunk_knob, chunk_report = _probe_chunk(reps, defaults)
        knobs["chunk_px"] = chunk_knob
        report = {
            "probes": report["probes"] + chunk_report["probes"],
            "timings": {
                **{f"tile_size={k}": v for k, v in report["timings"].items()},
                **{
                    f"chunk_px={k}": v
                    for k, v in chunk_report["timings"].items()
                },
            },
            "default_s": round(
                report["default_s"] + chunk_report["default_s"], 6
            ),
            "best_s": round(report["best_s"] + chunk_report["best_s"], 6),
            "speedup": round(
                (report["default_s"] + chunk_report["default_s"])
                / max(report["best_s"] + chunk_report["best_s"], 1e-9), 3,
            ),
        }
    return knobs, report


def _probe_chunk(reps: int, defaults: dict) -> "tuple[int, dict]":
    """Sliced segment-kernel sweep for ``chunk_px`` (full mode only).

    Times the kernel over a fixed pixel batch executed in candidate-sized
    slices — the ``lax.map``-over-chunks cost shape of the real chunked
    kernel, at probe scale.  Candidates are scaled stand-ins; the winner
    maps back to the real knob domain (never above the default bound:
    the probe tunes the perf side of the knob, the operator owns the
    memory side).
    """
    import jax

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.ops.segment import jax_segment_pixels

    px, ny = 2048, 16
    rng = np.random.default_rng(17)
    years = np.arange(2000, 2000 + ny, dtype=np.int32)
    values = rng.normal(0.4, 0.1, (px, ny)).astype(np.float32)
    mask = np.ones((px, ny), dtype=bool)
    params = LTParams(max_segments=4, vertex_count_overshoot=1)
    # scaled slice candidates; "1" = one slice (unchunked shape)
    slice_cands = [1, 2, 4]
    default_slices = 1  # the default bound never engages at probe scale

    def make_fn(n_slices: int):
        step = px // n_slices

        def run() -> None:
            outs = []
            for s in range(n_slices):
                outs.append(
                    jax_segment_pixels(
                        years,
                        values[s * step : (s + 1) * step],
                        mask[s * step : (s + 1) * step],
                        params,
                    )
                )
            jax.block_until_ready(outs)
        return run

    # warm the compiles outside the timed reps
    for n in slice_cands:
        make_fn(n)()
    best, report = _sweep(slice_cands, make_fn, reps, default_slices)
    # map: slicing never helped -> keep the default bound; slicing helped
    # -> halve the bound (a finer chunk at real scale), floor 64k
    default_chunk = defaults["chunk_px"]
    if best == 1 or default_chunk is None:
        return default_chunk, report
    return max(65536, int(default_chunk) // int(best)), report


#: group name → (probe fn, knob names) — the autotuner's schedule.  Order
#: matters only for reporting; groups are independent by construction.
PROBE_GROUPS: dict = {
    "feed": (probe_feed, ("feed_workers",)),
    "decode": (probe_decode, ("decode_workers", "feed_cache_mb")),
    "upload": (probe_upload, ("upload_depth",)),
    "fetch": (probe_fetch, ("fetch_depth",)),
    "dispatch": (probe_dispatch, ("tile_size", "chunk_px")),
}


def probe_group(
    group: str, reps: int, smoke: bool, defaults: dict
) -> "tuple[dict, dict]":
    """Run one group's probe; returns ``(best knob values, report)``."""
    fn, _knobs = PROBE_GROUPS[group]
    return fn(reps=reps, smoke=smoke, defaults=defaults)
