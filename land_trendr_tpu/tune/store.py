"""On-disk tuning store: calibration profiles keyed per device + scene class.

The autotuner (:mod:`land_trendr_tpu.tune.autotune`) spends seconds of
probe time discovering the right host-pipeline knob values for one
``(device kind, backend, scene shape class)`` — spending them once per
*fleet* instead of once per run is the whole point.  This module is the
persistence quarter: one JSON profile file per key under a store
directory, written **tmp + atomic rename** (the manifest/blockstore/
publish discipline — a reader never sees a torn file from a healthy
writer, so a torn file MEANS a crash and is dropped + re-probed), and
reloaded on sight by every consumer (``lt tune``, ``Run`` construction's
``"auto"`` resolution, serve replicas at job time).

Key semantics (the cache-correctness contract):

* ``device_kind`` + ``backend`` — knob values tuned on a TPU v5 lite do
  not transfer to a CPU host or a GPU; each device class probes its own.
* ``shape_class`` — the balance points depend on scene shape (tile
  granularity vs per-tile overhead, cache budget vs working set), but
  only coarsely: pixels are bucketed by powers of four and years to the
  next multiple of eight, so a 1024² and a 1400² scene share a profile
  while a 256² thumbnail and a gigapixel mosaic do not (buckets have
  edges: an AOI sitting just under a power of four keys differently
  from one just over it, and simply re-probes once).
* ``schema`` (:data:`TUNE_SCHEMA`) — the repo's perf-schema version.  A
  profile written by an older schema describes knobs/probes that may no
  longer exist; it is dropped (``stale_dropped``) and the key re-probes,
  exactly like the event stream's ``SCHEMA_VERSION`` contract.

Corruption follows the PR-5 ``drop_corrupt`` contract: unparseable or
key-mismatched files are deleted (best-effort) and counted
(``corrupt_dropped``), never crashed on — the caller re-probes.
Stdlib-only and jax-free, like every persistence module here.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time

__all__ = [
    "TUNE_SCHEMA",
    "TuningStore",
    "profile_key",
    "shape_class",
]

#: bump when a profile's REQUIRED fields or a knob's meaning changes —
#: older profiles are then stale by definition and re-probe on sight
TUNE_SCHEMA = 1

#: fields every stored profile must carry to be loadable
_REQUIRED = ("schema", "device_kind", "backend", "shape_class", "knobs", "created_t")


def shape_class(height: int, width: int, n_years: int) -> str:
    """Coarse scene-shape bucket (see module docstring).

    Pixels bucket by powers of FOUR (``log4`` of the pixel count) and
    years to the next multiple of 8 — wide enough that jittered AOIs
    share a profile, narrow enough that a thumbnail and a gigapixel
    mosaic never do.
    """
    px = max(1, int(height) * int(width))
    ny = max(1, int(n_years))
    return f"px4e{int(math.log2(px)) // 2}_ny{((ny + 7) // 8) * 8}"


def profile_key(device_kind: str, backend: str, shape_cls: str) -> str:
    """The store key string (also what ``tune_profile`` events carry)."""
    return f"{device_kind}|{backend}|{shape_cls}"


def _fname(key: str) -> str:
    """Stable per-key filename (keys carry spaces/slashes on real TPUs)."""
    return f"profile-{hashlib.sha1(key.encode()).hexdigest()[:16]}.json"


class TuningStore:
    """One tuning-store directory (see module docstring).

    Thread-safe: one lock guards the counters; file operations rely on
    atomic rename (writers) and whole-file reads (readers), so concurrent
    processes sharing a store directory — a serving fleet's replicas —
    never see torn state and last-probe-wins is the (correct) answer for
    a re-probed key.
    """

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._stats = {
            "hits": 0,
            "misses": 0,
            "stale_dropped": 0,
            "corrupt_dropped": 0,
            "saves": 0,
        }

    # -- internals ---------------------------------------------------------
    def path_for(self, key: str) -> str:
        return os.path.join(self.root, _fname(key))

    def _count(self, name: str) -> None:
        with self._lock:
            self._stats[name] += 1

    def _drop(self, path: str, counter: str) -> None:
        """Delete a bad profile file (best-effort) and count why."""
        try:
            os.unlink(path)
        except OSError:
            pass  # a racing sibling already dropped it — same outcome
        self._count(counter)

    # -- the public contract ----------------------------------------------
    def load(self, device_kind: str, backend: str, shape_cls: str) -> "dict | None":
        """The profile for this key, or ``None`` (= probe).

        ``None`` covers: no file (miss), torn/unparseable file (dropped,
        ``corrupt_dropped``), a file whose embedded key does not match
        its name's key (dropped — hash collision or a copied-in foreign
        file), and a stale ``schema`` (dropped, ``stale_dropped``).
        """
        key = profile_key(device_kind, backend, shape_cls)
        path = self.path_for(key)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            self._count("misses")
            return None
        except OSError:
            self._drop(path, "corrupt_dropped")
            return None
        try:
            profile = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError):
            self._drop(path, "corrupt_dropped")
            return None
        if not isinstance(profile, dict) or any(
            k not in profile for k in _REQUIRED
        ) or not isinstance(profile.get("knobs"), dict):
            self._drop(path, "corrupt_dropped")
            return None
        if profile["schema"] != TUNE_SCHEMA:
            self._drop(path, "stale_dropped")
            return None
        if (
            profile["device_kind"] != device_kind
            or profile["backend"] != backend
            or profile["shape_class"] != shape_cls
        ):
            self._drop(path, "corrupt_dropped")
            return None
        self._count("hits")
        return profile

    def save(self, profile: dict) -> str:
        """Persist one profile (tmp + atomic rename); returns the path.

        The serialisation is canonical (sorted keys, fixed separators),
        so save → load → save round-trips byte-identically — the
        perf-gate's byte-stability invariant.
        """
        missing = [k for k in _REQUIRED if k not in profile]
        if missing:
            raise ValueError(f"profile missing required fields {missing}")
        key = profile_key(
            profile["device_kind"], profile["backend"], profile["shape_class"]
        )
        path = self.path_for(key)
        data = json.dumps(profile, sort_keys=True, separators=(",", ":"))
        tmp = f"{path}.{os.getpid()}.{time.monotonic_ns()}.tmp"
        with open(tmp, "w") as f:
            f.write(data)
        os.replace(tmp, path)
        self._count("saves")
        return path

    def profiles(self) -> list[dict]:
        """Every loadable profile in the store (for reports / ``lt tune``
        listings / the serve ``/healthz`` surface).  Bad files are left
        for their own keyed :meth:`load` to drop — a listing is a
        read-only observer."""
        out = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        for name in names:
            if not (name.startswith("profile-") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    p = json.load(f)
            except (OSError, json.JSONDecodeError, UnicodeDecodeError):
                continue
            if isinstance(p, dict) and all(k in p for k in _REQUIRED):
                out.append(p)
        return out

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)
