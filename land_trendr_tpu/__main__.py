"""``python -m land_trendr_tpu`` entry point."""

import sys

from land_trendr_tpu.cli import run

sys.exit(run())
