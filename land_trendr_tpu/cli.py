"""Command-line driver — the framework's L5 entry point.

Mirrors the reference's CLI/driver layer (SURVEY.md §2 L5: "parse config &
CLI flags, enumerate input Landsat stack, launch the job, write segment
rasters"), minus the Hadoop submission: ``segment`` runs the whole
stacks-in / rasters-out pipeline in-process on the local TPU (or CPU).

Commands
--------
``segment``   stack directory → segment rasters (the main pipeline)
``pixel``     segment ONE time series through the CPU oracle and/or the JAX
              kernel — the single-pixel debug/parity path (SURVEY.md §4
              call stack (4): construct the segmenter directly, bypassing
              the job machinery)
``params``    print the default algorithm parameters as JSON (a template
              for ``--params-json``)
``synth``     materialise a synthetic Landsat stack (fixtures / demos)

Algorithm flags mirror the reference's parameter names (SURVEY.md §3.1
table — config parity requirement from §5), e.g. ``--max-segments`` ↔
``max_segments``.  ``--params-json`` loads a full :class:`LTParams` JSON
first; individual flags then override.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import sys

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops.indices import DEFAULT_QA_REJECT, INDEX_NAMES
from land_trendr_tpu.runtime.manifest import ARTIFACT_COMPRESS

__all__ = ["main", "build_parser"]


def _auto_int(s: str):
    """Tunable-knob flag values: an integer or the 'auto' sentinel (the
    tuning-store resolution — README §Autotuning)."""
    return s if s == "auto" else int(s)


def _sigterm_to_interrupt(signum, frame):
    """SIGTERM → KeyboardInterrupt: the long-running servers drain on
    an orchestrator stop exactly like Ctrl-C (`lt route` writes its
    journal clean-shutdown marker on this path)."""
    raise KeyboardInterrupt


def _add_param_flags(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("algorithm parameters (reference names)")
    g.add_argument("--params-json", type=str, default=None,
                   help="path to an LTParams JSON file (flags override it)")
    for f in dataclasses.fields(LTParams):
        flag = "--" + f.name.replace("_", "-")
        if f.type == "bool":
            g.add_argument(flag, type=lambda s: s.lower() in ("1", "true", "yes"),
                           default=None, metavar="BOOL")
        else:
            g.add_argument(flag, type=int if f.type == "int" else float, default=None)


def _params_from_args(args: argparse.Namespace) -> LTParams:
    base = {}
    if args.params_json:
        with open(args.params_json) as f:
            base = json.load(f)
    for f in dataclasses.fields(LTParams):
        v = getattr(args, f.name, None)
        if v is not None:
            base[f.name] = v
    return LTParams.from_dict(base)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="land_trendr_tpu",
        description="TPU-native LandTrendr temporal segmentation",
    )
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument(
        "--platform",
        default=os.environ.get("LT_PLATFORM"),
        help="force the JAX platform (e.g. 'cpu', 'tpu'); defaults to the "
        "LT_PLATFORM env var, else JAX's own selection.  Needed because an "
        "interpreter boot hook may pin jax_platforms programmatically, "
        "which outranks the JAX_PLATFORMS env var — without this a CPU run "
        "on a machine whose TPU is unreachable hangs in backend init",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    seg = sub.add_parser("segment", help="segment a Landsat stack directory")
    seg.add_argument("stack_dir", help="directory of per-year multi-band GeoTIFFs")
    seg.add_argument("--index", default="nbr", choices=INDEX_NAMES,
                     help="index driving the segmentation")
    seg.add_argument("--ftv", default="", help="comma-separated FTV indices")
    seg.add_argument("--tile-size", type=_auto_int, default=512,
                     help="tile edge in pixels, or 'auto' (resolve "
                     "through --tune-store-dir's profile; with no "
                     "profile, 'auto' falls back to the LIBRARY default "
                     "256, not this flag's 512)")
    seg.add_argument("--workdir", default="lt_work")
    seg.add_argument("--out-dir", default="lt_out")
    seg.add_argument("--no-resume", action="store_true",
                     help="discard any existing workdir manifest")
    seg.add_argument("--products", default=None, metavar="P1,P2,...",
                     help="segmentation products to checkpoint + assemble "
                          "(default: all); a subset cuts manifest/output/"
                          "fetch bytes proportionally — the gigapixel knob")
    seg.add_argument("--fetch-f16", action="store_true",
                     help="fetch float products from the device as float16 "
                          "(halves device->host bytes; opt-in lossy packing "
                          "within the f32 tolerance contract)")
    seg.add_argument("--no-packed-fetch", action="store_true",
                     help="force the per-product synchronous device->host "
                          "fetch (default 'auto' packs every tile's "
                          "products into ONE async transfer on "
                          "accelerator backends; artifacts are "
                          "byte-identical either way)")
    seg.add_argument("--packed-fetch", action="store_true",
                     help="force the packed fetch path even on CPU "
                          "backends (where np.asarray is zero-copy and "
                          "auto keeps the per-product path)")
    seg.add_argument("--fetch-depth", type=_auto_int, default=2,
                     help="bound on in-flight async packed fetches: tile "
                          "i's readback lands while tiles up to "
                          "i+fetch_depth compute (raise on high-latency "
                          "links; memory grows one packed tile + one fed "
                          "input per step)")
    seg.add_argument("--no-packed-upload", action="store_true",
                     help="force the per-array synchronous host->device "
                          "dispatch (default 'auto' packs every tile's "
                          "fed band/QA arrays into ONE async device_put "
                          "on accelerator backends; artifacts are "
                          "byte-identical either way)")
    seg.add_argument("--packed-upload", action="store_true",
                     help="force the packed upload path even on CPU "
                          "backends (where device_put is near zero-copy "
                          "and auto keeps the per-array path); "
                          "incompatible with --mesh")
    seg.add_argument("--upload-depth", type=_auto_int, default=2,
                     help="bound on in-flight async packed uploads: up "
                          "to this many fed tiles cross the link while "
                          "the tile ahead computes (raise on "
                          "high-latency links; memory grows one packed "
                          "buffer + one fed input per step)")
    seg.add_argument("--ingest-store-mb", type=int, default=0,
                     help="persistent decoded-block store budget (MiB) "
                          "under the workdir: decoded TIFF blocks spill "
                          "to a memory-mapped on-disk store so a rerun "
                          "over the same stacks skips decode entirely "
                          "(ingest once, serve many); 0 = off")
    seg.add_argument("--ingest-store-dir", default=None, metavar="DIR",
                     help="store directory override (default "
                          "WORKDIR/ingest_store) — share one store "
                          "across runs/workdirs over the same stacks")
    seg.add_argument("--lazy", action="store_true",
                     help="windowed file-backed ingest (C2 per-band layout "
                          "only): no input cube in host RAM — for scenes "
                          "larger than memory")
    seg.add_argument("--write-fitted", action="store_true",
                     help="also write the full fitted-trajectory raster")
    seg.add_argument("--out-compress", default="deflate",
                     choices=("deflate", "lzw", "none"),
                     help="output raster compression")
    seg.add_argument("--manifest-compress", default="none",
                     choices=ARTIFACT_COMPRESS,
                     help="per-tile checkpoint artifact compression: 'none' "
                     "(fastest; default) or 'deflate' (zlib-1, smaller "
                     "workdir)")
    seg.add_argument("--write-workers", type=int, default=1,
                     help="background tile-writer threads (scale up on "
                     "device-rate hosts; memory stays bounded at "
                     "write_workers+2 live tiles)")
    seg.add_argument("--impl", default="auto", choices=("auto", "pallas", "xla"),
                     help="segmentation kernel: auto picks the Pallas "
                          "family kernel on TPU backends (round-4 measured "
                          "default), XLA elsewhere")
    seg.add_argument("--feed-workers", type=_auto_int, default=1,
                     help="background tile-feed threads over the threaded "
                     "native gather (~4.1M px/s each; ~3 sustain the 10M "
                     "px/s target); prefetch depth is feed_workers+1")
    seg.add_argument("--feed-cache-mb", type=_auto_int, default=256,
                     help="decoded-block cache budget (MiB) for the "
                     "windowed feed path: tile windows that revisit a "
                     "compressed TIFF block (tile edges, --lazy re-reads, "
                     "resume passes) decode it once; 0 disables the cache "
                     "and reproduces the uncached codec byte for byte")
    seg.add_argument("--decode-workers", type=_auto_int, default=0,
                     help="feed-decode threads (native codec AND the NumPy "
                     "fallback share this knob): 0 = codec auto-threading, "
                     "1 = serial, N = N threads, 'auto' = tuning-store "
                     "resolution")
    seg.add_argument("--no-feed-readahead", action="store_true",
                     help="disable the feed pool's next-tile block-decode "
                     "hint (only meaningful with --lazy and a non-zero "
                     "--feed-cache-mb)")
    seg.add_argument("--change", action="store_true",
                     help="fuse on-device change-map selection into every "
                     "tile's program; change_*.tif rasters assemble "
                     "alongside the segment products (one pass, no "
                     "post-hoc raster reads — the `change` command remains "
                     "for mapping already-written segment rasters)")
    seg.add_argument("--change-kind", default="disturbance",
                     choices=("disturbance", "recovery"))
    seg.add_argument("--change-sort", default="greatest",
                     choices=("greatest", "newest", "oldest"))
    seg.add_argument("--change-min-mag", type=float, default=0.0)
    seg.add_argument("--change-min-dur", type=float, default=0.0)
    seg.add_argument("--change-max-dur", type=float, default=float("inf"))
    seg.add_argument("--change-min-preval", type=float, default=float("-inf"))
    seg.add_argument("--change-max-p", type=float, default=1.0)
    seg.add_argument("--change-year-min", type=float, default=float("-inf"))
    seg.add_argument("--change-year-max", type=float, default=float("inf"))
    seg.add_argument("--change-mmu", type=int, default=1,
                     help="minimum mapping unit (pixels) applied to the "
                     "assembled change mask — spatial, so it runs after "
                     "assembly, not on device")
    seg.add_argument("--composite", default=None, choices=("medoid",),
                     help="collapse multi-acquisition years in a C2 "
                     "per-band archive to per-pixel QA-masked medoid "
                     "composites (default: require one acquisition/year). "
                     "NOTE: medoid distance uses only the bands this run "
                     "loads (e.g. nir+swir2 for NBR), not the standard "
                     "6-band medoid — so the chosen acquisition can differ "
                     "between runs with different --index/--ftv selections")
    seg.add_argument("--out-overviews", default=0,
                     type=lambda s: s if s == "auto" else int(s),
                     help="overview pyramid levels on output rasters: an "
                     "integer or 'auto' (until the smaller dimension "
                     "drops under 256); default 0 = none")
    seg.add_argument("--trace", default=None, metavar="LOGDIR",
                     help="capture a jax.profiler device+host trace of the "
                     "run under LOGDIR (open with TensorBoard's profile "
                     "plugin, or feed to tools/profile_stages.py)")
    seg.add_argument("--telemetry", action="store_true",
                     help="run-wide telemetry: schema-versioned "
                     "events.jsonl (run/tile lifecycle, retries, backlog "
                     "depths; one file per process under multihost) and a "
                     "Prometheus metrics.prom exposition, both refreshed "
                     "in flight under --workdir; fold with "
                     "tools/obs_report.py")
    seg.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                     help="with --telemetry: serve a live /metrics "
                     "endpoint on PORT (0 = ephemeral; reported in the "
                     "run summary) so the run is scrapeable in flight")
    seg.add_argument("--metrics-host", default="", metavar="HOST",
                     help="bind address for --metrics-port (default: all "
                     "interfaces; pass 127.0.0.1 to keep the "
                     "unauthenticated endpoint off the network)")
    seg.add_argument("--flight", action="store_true",
                     help="with --telemetry: flight recorder — a bounded "
                     "in-memory ring mirroring every telemetry emit plus "
                     "a periodic resource sampler (flight_sample events: "
                     "RSS, fds, threads, backlogs, cache occupancy, HBM "
                     "watermark), dumped to WORKDIR/flight.jsonl at run "
                     "end (success and abort — the post-mortem window)")
    seg.add_argument("--flight-ring-events", type=int, default=2048,
                     metavar="N",
                     help="flight-ring capacity in events (the 'last N "
                     "events' window a dump shows); 0 disables the ring "
                     "and the sampler, as in serve mode")
    seg.add_argument("--sampler-interval-s", type=float, default=5.0,
                     metavar="SEC",
                     help="flight resource-sampler period in seconds")
    seg.add_argument("--publish", action="store_true",
                     help="with --telemetry: fleet telemetry publish — "
                     "periodically snapshot this process's metrics + "
                     "live progress into an atomic "
                     "TELEMETRY_DIR/<host>.<pid>.snap.json, the "
                     "per-process feed tools/lt_fleet.py and 'lt top "
                     "--dir' fold into one pod view")
    seg.add_argument("--publish-interval-s", type=float, default=5.0,
                     metavar="SEC",
                     help="fleet snapshot refresh period in seconds")
    seg.add_argument("--telemetry-dir", default=None, metavar="DIR",
                     help="shared telemetry directory for --publish "
                     "(default WORKDIR/telemetry); point a pod's "
                     "processes at one DIR to aggregate them")
    seg.add_argument("--max-retries", type=int, default=2)
    seg.add_argument("--retry-backoff-s", type=float, default=0.5,
                     metavar="SEC",
                     help="base of the exponential per-tile retry backoff "
                     "(attempt n sleeps ~SEC*2^(n-1), ±50%% jitter, capped "
                     "at 30s); 0 retries immediately")
    seg.add_argument("--quarantine-tiles", action="store_true",
                     help="a tile that exhausts --max-retries is recorded "
                     "as failed in the manifest and the run CONTINUES "
                     "(tiles are independent); the run summary lists "
                     "tiles_quarantined, the exit code is 3, assembly is "
                     "skipped, and a resume re-attempts the tiles")
    seg.add_argument("--stall-timeout-s", type=float, default=None,
                     metavar="SEC",
                     help="abort (exit 4) after SEC without tile progress "
                     "— a hung device wait is otherwise an infinite hang; "
                     "set well above the first tile's compile time "
                     "(default: no watchdog)")
    seg.add_argument("--merge-timeout-s", type=float, default=None,
                     metavar="SEC",
                     help="multihost only: bound on the primary's wait for "
                     "straggler peers' run_done during the event-log merge "
                     "(default: derived from this run's wall time)")
    seg.add_argument("--straggler-k", type=float, default=4.0, metavar="K",
                     help="live straggler threshold: a tile in flight "
                     "longer than K x the rolling median of recent tile "
                     "durations emits a tile_straggler event and counts "
                     "in lt_stragglers_total (observability only — the "
                     "tile keeps running); must be >= 1")
    seg.add_argument("--straggler-min-tiles", type=int, default=5,
                     metavar="N",
                     help="no straggler verdicts until N tiles completed "
                     "(the first tile carries the jit compile and must "
                     "never false-positive)")
    seg.add_argument("--lease-batch", type=int, default=0, metavar="N",
                     help="elastic pod scheduling: replace the static "
                     "host_share tile split with the shared-manifest "
                     "lease queue — this process claims N tiles at a "
                     "time, renews leases on progress, and steals tiles "
                     "whose leases expired (dead/slow peer) or were "
                     "never claimed, so hosts may join/leave mid-run; "
                     "0 (default) keeps the static split.  Artifacts "
                     "are byte-identical either way")
    seg.add_argument("--lease-ttl-s", type=float, default=30.0,
                     metavar="SEC",
                     help="lease time-to-live: a lease not renewed "
                     "within SEC is stealable by siblings.  Size it "
                     "above the slowest tile and the pod's clock skew "
                     "(a short TTL only costs benign duplicate work, "
                     "never correctness)")
    seg.add_argument("--speculate", action="store_true",
                     help="with --lease-batch: straggler-steered "
                     "speculative execution — an idle host re-leases a "
                     "tile the owner's live straggler detector flagged; "
                     "first durable write wins, the loser lands as an "
                     "identical no-op")
    seg.add_argument("--fault-schedule", default=None, metavar="SPEC",
                     help="deterministic fault injection for test/soak "
                     "runs (land_trendr_tpu.runtime.faults), e.g. "
                     "'seed=7,dispatch@1,fetch.wait@0*2=io'; production "
                     "runs leave this unset")
    seg.add_argument("--reject-bits", type=lambda s: int(s, 0),
                     default=DEFAULT_QA_REJECT, metavar="MASK",
                     help="QA_PIXEL bitmask of rejected observation classes "
                     "(decimal or 0x hex; default: the C2 fill/cloud/shadow "
                     f"set, 0x{DEFAULT_QA_REJECT:x})")
    seg.add_argument("--chunk-px", default=262_144, metavar="N",
                     type=lambda s: (
                         None if s.lower() == "none"
                         else s if s == "auto" else int(s)
                     ),
                     help="transient-HBM bound: tiles with more pixels run "
                     "the segmentation through the chunked kernel; 'none' "
                     "disables chunking (the kernel working set then grows "
                     "with the full tile); 'auto' resolves through the "
                     "tuning store")
    seg.add_argument("--tune-store-dir", default=None, metavar="DIR",
                     help="on-disk tuning store (lt tune's output) the "
                     "'auto' knob values resolve through at run start; "
                     "key miss or no DIR = hardcoded defaults, "
                     "byte-identical behavior (README §Autotuning)")
    seg.add_argument("--metrics-interval-s", type=float, default=5.0,
                     metavar="SEC",
                     help="with --telemetry: metrics.prom refresh period "
                     "in seconds")
    seg.add_argument(
        "--mesh",
        action="store_true",
        help="shard every tile's pixel axis over ALL local devices "
        "(jax.sharding 1-D mesh, zero cross-pixel collectives); default "
        "runs on the single default device",
    )
    seg.add_argument("--scale", type=float, default=2.75e-5,
                     help="DN→reflectance scale (C2 default)")
    seg.add_argument("--offset", type=float, default=-0.2,
                     help="DN→reflectance offset (C2 default)")
    _add_param_flags(seg)

    pix = sub.add_parser(
        "pixel", help="segment one series (single-pixel debug/parity path)"
    )
    pix.add_argument(
        "series",
        nargs="?",
        default=None,
        help="JSON file with {years: [...], values: [...], mask?: [...]}; "
        "'-' reads stdin; values use the index's natural sign with "
        "--index, or are taken as-is (disturbance-positive) without it. "
        "Omit when using --from-stack.",
    )
    pix.add_argument("--from-stack", default=None, metavar="DIR",
                     help="pull the series from a stack directory instead "
                     "of JSON: computes --index at pixel (--x, --y) with "
                     "the standard QA+range masking (debug a suspicious "
                     "pixel of a real scene)")
    pix.add_argument("--x", type=int, default=None, help="column (with --from-stack)")
    pix.add_argument("--y", type=int, default=None, help="row (with --from-stack)")
    pix.add_argument("--scale", type=float, default=2.75e-5,
                     help="DN→reflectance scale for --from-stack (C2 default)")
    pix.add_argument("--offset", type=float, default=-0.2,
                     help="DN→reflectance offset for --from-stack (C2 default)")
    pix.add_argument("--engine", choices=("oracle", "jax", "both"),
                     default="both")
    pix.add_argument("--index", default=None, choices=INDEX_NAMES,
                     help="flip sign per this index's disturbance "
                     "convention; with --from-stack it also selects the "
                     "index to compute (defaulting to nbr)")
    _add_param_flags(pix)

    chg = sub.add_parser(
        "change",
        help="derive change maps (yod/mag/dur/rate/preval/dsnr) from "
        "segment rasters — the standard LandTrendr post-processing layer "
        "(an extension beyond the reference's segment-raster surface)",
    )
    chg.add_argument("seg_dir", help="out-dir of a finished `segment` run")
    chg.add_argument("--dest", default="lt_change", help="output directory")
    chg.add_argument("--index", default="nbr", choices=INDEX_NAMES,
                     help="index the segmentation ran on (sets the "
                     "disturbance direction)")
    chg.add_argument("--kind", default="disturbance",
                     choices=("disturbance", "recovery"))
    chg.add_argument("--sort", default="greatest",
                     choices=("greatest", "newest", "oldest"),
                     help="which qualifying segment becomes the map")
    chg.add_argument("--min-mag", type=float, default=0.0,
                     help="minimum |magnitude| in index units")
    chg.add_argument("--min-dur", type=float, default=0.0)
    chg.add_argument("--max-dur", type=float, default=float("inf"),
                     help="maximum duration in years (classic fast-"
                     "disturbance filter: 4)")
    chg.add_argument("--min-preval", type=float, default=float("-inf"),
                     help="minimum fitted value at the segment start")
    chg.add_argument("--max-p", type=float, default=1.0,
                     help="extra p-of-F cap on top of the run's threshold")
    chg.add_argument("--year-min", type=float, default=float("-inf"))
    chg.add_argument("--year-max", type=float, default=float("inf"))
    chg.add_argument("--mmu", type=int, default=1,
                     help="minimum mapping unit: drop 4-connected changed "
                     "patches smaller than this many pixels")

    srv = sub.add_parser(
        "serve",
        help="long-lived segmentation server: warm compiled programs, a "
        "bounded job queue over a loopback HTTP JSON API + filesystem "
        "drop-box, admission control with per-tenant caps, and "
        "request-scoped telemetry (README §Service mode)",
    )
    srv.add_argument("--workdir", default="lt_serve",
                     help="server root: the server's events/metrics "
                     "stream, default per-job jobs/<id>/{work,out} "
                     "directories, and the shared ingest store")
    srv.add_argument("--serve-port", type=int, default=0, metavar="PORT",
                     help="loopback HTTP JSON API port (0 = ephemeral, "
                     "reported in the startup line)")
    srv.add_argument("--serve-host", default="127.0.0.1", metavar="HOST",
                     help="bind address for the job API — loopback ONLY "
                     "(127.0.0.1, localhost or ::1): the API is an "
                     "unauthenticated control surface; front it with an "
                     "authenticated proxy or use --dropbox-dir for "
                     "remote batch submission")
    srv.add_argument("--serve-queue-depth", type=int, default=16,
                     help="admission control: submissions past this "
                     "queue depth are rejected with HTTP 429 instead of "
                     "building unbounded backlog")
    srv.add_argument("--tenant-max-inflight", type=int, default=4,
                     help="admission control: per-tenant bound on "
                     "queued+running jobs (429 at the cap; other "
                     "tenants' traffic proceeds)")
    srv.add_argument("--job-timeout-s", type=float, default=None,
                     metavar="SEC",
                     help="default per-job wall bound, submit to "
                     "terminal: an over-budget job is cancelled through "
                     "the run's cancel event and reported 'stalled' "
                     "(the exit-4 analog; manifest stays resumable). "
                     "Jobs may override per request")
    srv.add_argument("--dropbox-dir", default=None, metavar="DIR",
                     help="filesystem drop-box: job-request JSON files "
                     "under DIR are claimed atomically, run through the "
                     "same admission control as HTTP, and answered with "
                     ".rejected.json/.result.json sidecars")
    srv.add_argument("--dropbox-poll-s", type=float, default=1.0,
                     metavar="SEC", help="drop-box scan period")
    srv.add_argument("--max-jobs", type=int, default=None, metavar="N",
                     help="drain N jobs to a terminal state then shut "
                     "down cleanly (bench/CI mode; default: serve "
                     "forever)")
    srv.add_argument("--feed-cache-mb", type=int, default=256,
                     help="process-wide decoded-block cache budget "
                     "(MiB) shared by every job — the server owns the "
                     "cache configuration")
    srv.add_argument("--decode-workers", type=int, default=0,
                     help="shared feed-decode threads: 0 = auto, "
                     "1 = serial, N = N threads")
    srv.add_argument("--ingest-store-mb", type=int, default=0,
                     help="shared persistent ingest store budget (MiB): "
                     "decoded blocks from every job spill to one store "
                     "under the server workdir, so a warm job over "
                     "already-ingested stacks skips TIFF decode "
                     "entirely; 0 = off")
    srv.add_argument("--ingest-store-dir", default=None, metavar="DIR",
                     help="store directory override (default "
                     "WORKDIR/ingest_store)")
    srv.add_argument("--tune-store-dir", default=None, metavar="DIR",
                     help="shared tuning store (lt tune's output): every "
                     "job's 'auto' knobs resolve through it, so the "
                     "whole replica runs tuned; per-job explicit knobs "
                     "still win (README §Autotuning)")
    srv.add_argument("--no-telemetry", action="store_true",
                     help="disable the server events/metrics stream AND "
                     "per-job run telemetry (on by default in serve "
                     "mode — the observability is the point)")
    srv.add_argument("--metrics-port", type=int, default=None,
                     metavar="PORT",
                     help="serve the lt_serve_* registry's live "
                     "/metrics on PORT (0 = ephemeral); the job API "
                     "serves GET /metrics regardless")
    srv.add_argument("--metrics-host", default="", metavar="HOST",
                     help="bind address for --metrics-port (the scrape "
                     "endpoint is read-only and may be non-loopback)")
    srv.add_argument("--metrics-interval-s", type=float, default=5.0,
                     metavar="SEC", help="metrics.prom refresh period")
    srv.add_argument("--fault-schedule", default=None, metavar="SPEC",
                     help="deterministic fault injection for soak runs "
                     "(one process-wide plan shared by every job, incl. "
                     "the serve.submit/serve.job seams); production "
                     "servers leave this unset")
    srv.add_argument("--no-debug-endpoints", action="store_true",
                     help="disable the live /debug surface "
                     "(/debug/flight, /debug/stacks, /debug/jobs, POST "
                     "/debug/profile — loopback-only like the job API; "
                     "on by default)")
    srv.add_argument("--flight-ring-events", type=int, default=2048,
                     metavar="N",
                     help="flight-recorder ring capacity in events: the "
                     "/debug/flight window over server AND job events, "
                     "dumped to WORKDIR/flight.jsonl at shutdown; 0 "
                     "disables the ring and the resource sampler")
    srv.add_argument("--sampler-interval-s", type=float, default=5.0,
                     metavar="SEC",
                     help="flight resource-sampler period (flight_sample "
                     "events: RSS, fds, threads, queue depth, backlogs, "
                     "cache occupancy)")
    srv.add_argument("--request-ring", type=int, default=64, metavar="N",
                     help="request-tracing recency bound: how many "
                     "recent terminal requests (trace id + latency "
                     "split) GET /debug/requests serves slowest-first; "
                     "0 disables the ring")
    srv.add_argument("--publish", action="store_true",
                     help="fleet telemetry plane: publish this replica's "
                     "snapshot under TELEMETRY_DIR, fold every snapshot "
                     "there into one pod view each beat, retain the "
                     "timeline in the on-disk history ring, and evaluate "
                     "the alert rules over it (alert events, lt_alerts_* "
                     "metrics, active alerts on /healthz and lt top)")
    srv.add_argument("--publish-interval-s", type=float, default=5.0,
                     metavar="SEC",
                     help="fleet beat period (snapshot + fold + alert "
                     "evaluation)")
    srv.add_argument("--telemetry-dir", default=None, metavar="DIR",
                     help="shared telemetry directory for --publish "
                     "(default WORKDIR/telemetry); point N replicas at "
                     "one DIR to aggregate the fleet")
    srv.add_argument("--alert-rules", default=None, metavar="FILE",
                     help="alert-rules JSON for the fleet loop "
                     "(land_trendr_tpu.obs.alerts); default: built-in "
                     "host-staleness + SLO-burn rules")
    srv.add_argument("--batch", default="auto",
                     choices=("auto", "on", "off"),
                     help="cross-job continuous batching: coalesce "
                     "queued same-affinity jobs behind one shared "
                     "launch and demux byte-identical artifacts to "
                     "each (README §Continuous batching); 'auto' "
                     "resolves through --tune-store-dir, defaulting on")
    srv.add_argument("--batch-window-ms", type=float, default=50.0,
                     metavar="MS",
                     help="how long the dispatcher holds a batch window "
                     "open for same-affinity stragglers; closes early "
                     "when a non-matching job reaches the queue front "
                     "or the queue is empty (0 = batch only what is "
                     "already queued)")
    srv.add_argument("--batch-max-tiles", type=int, default=0,
                     metavar="N",
                     help="batch size bound in total coalesced tiles "
                     "(jobs x tiles per job); members past the bound "
                     "run solo in their normal queue turn (0 = "
                     "unbounded)")

    rte = sub.add_parser(
        "route",
        help="serving-fleet router: one loopback front door over N "
        "lt-serve replicas (spawned or adopted) with warm-affinity "
        "routing, per-tenant quotas + weighted fair share, "
        "retry-on-replica-death re-routing, and SLO-burn-driven "
        "autoscaling (README §Serving fleet)",
    )
    rte.add_argument("--workdir", default="lt_route",
                     help="router root: its events/metrics stream, the "
                     "pinned per-job jobs/<id>/{work,out} dirs every "
                     "replica resumes from, and spawned replica workdirs")
    rte.add_argument("--route-port", type=int, default=0, metavar="PORT",
                     help="loopback HTTP JSON API port of the front door "
                     "(0 = ephemeral, reported in the startup line)")
    rte.add_argument("--route-host", default="127.0.0.1", metavar="HOST",
                     help="bind address — loopback ONLY (the router "
                     "submits arbitrary work to the whole fleet; front "
                     "it with an authenticated proxy)")
    rte.add_argument("--replica", action="append", default=[],
                     metavar="BASE", dest="replicas",
                     help="ADOPT an already-running replica by base URL "
                     "(http://127.0.0.1:PORT; repeatable) — "
                     "health-checked and routed to, never spawned or "
                     "killed")
    rte.add_argument("--spawn-replicas", type=int, default=0, metavar="N",
                     help="SPAWN N replicas via the lt-serve CLI under "
                     "WORKDIR/replicas (ephemeral ports; the "
                     "autoscaler's pool)")
    rte.add_argument("--replica-args", default="", metavar="FLAGS",
                     help="extra lt-serve flags for every spawned "
                     "replica, space-separated (e.g. "
                     "'--ingest-store-mb 256')")
    rte.add_argument("--replica-inflight", type=int, default=2,
                     help="per-replica in-flight bound at the router "
                     "(queued+running routed jobs one replica holds "
                     "before the router looks elsewhere)")
    rte.add_argument("--route-queue-depth", type=int, default=64,
                     help="router-wide queue bound: submissions past it "
                     "are throttled 429 + Retry-After")
    rte.add_argument("--tenant-quota", type=int, default=16,
                     help="per-tenant bound on queued+routed jobs; at "
                     "the quota the tenant is throttled 429 + "
                     "Retry-After while others' traffic proceeds")
    rte.add_argument("--tenant-weights", default=None, metavar="SPEC",
                     help="weighted fair share, 'tenant=weight,...' — "
                     "deficit round-robin gives each tenant bandwidth "
                     "proportional to its weight (unnamed tenants "
                     "weigh 1)")
    rte.add_argument("--no-affinity", action="store_true",
                     help="disable warm-affinity routing (pure "
                     "least-loaded — the fleet_bench baseline)")
    rte.add_argument("--route-retries", type=int, default=2,
                     help="re-routes per job after a dead replica or "
                     "failed forward before the job goes terminal")
    rte.add_argument("--health-interval-s", type=float, default=1.0,
                     metavar="SEC",
                     help="health-probe + job-poll period")
    rte.add_argument("--unhealthy-after", type=int, default=3,
                     help="consecutive failed health probes before a "
                     "replica is marked unready (its accepted jobs are "
                     "never failed by a probe)")
    rte.add_argument("--autoscale", action="store_true",
                     help="SLO-driven autoscaling of the SPAWNED pool: "
                     "fold the shared telemetry dir for the pod "
                     "lt_slo_burn_rate and scale between "
                     "--min-replicas/--max-replicas with hold-down and "
                     "drain-before-kill")
    rte.add_argument("--min-replicas", type=int, default=1,
                     help="autoscaler floor (spawned replicas)")
    rte.add_argument("--max-replicas", type=int, default=4,
                     help="autoscaler ceiling (spawned replicas)")
    rte.add_argument("--scale-up-burn", type=float, default=0.5,
                     metavar="RATE",
                     help="scale up when the pod burn rate holds at or "
                     "above RATE")
    rte.add_argument("--scale-down-burn", type=float, default=0.05,
                     metavar="RATE",
                     help="scale down when the pod burn rate holds at "
                     "or below RATE and the router queue is empty")
    rte.add_argument("--scale-for-s", type=float, default=0.0,
                     metavar="SEC",
                     help="the burn condition must hold SEC before a "
                     "scale action (transients don't scale)")
    rte.add_argument("--scale-hold-s", type=float, default=30.0,
                     metavar="SEC",
                     help="hold-down between scale actions (no "
                     "flapping)")
    rte.add_argument("--no-telemetry", action="store_true",
                     help="disable the router events/metrics stream "
                     "(on by default)")
    rte.add_argument("--telemetry-dir", default=None, metavar="DIR",
                     help="shared fleet telemetry directory (default "
                     "WORKDIR/telemetry): spawned replicas publish "
                     "here, the autoscaler folds it, lt_fleet/lt top "
                     "--dir render it")
    rte.add_argument("--metrics-interval-s", type=float, default=5.0,
                     metavar="SEC",
                     help="router metrics.prom refresh period")
    rte.add_argument("--request-ring", type=int, default=64, metavar="N",
                     help="request-tracing recency bound: how many "
                     "recent terminal requests (trace id, router blame "
                     "split, hops) GET /debug/requests serves "
                     "slowest-first; 0 disables the ring")
    rte.add_argument("--no-journal", action="store_true",
                     help="disable the write-ahead admission journal "
                     "(WORKDIR/journal/): no crash recovery, no "
                     "idempotent resubmission — bench baselines only")
    rte.add_argument("--journal-segment-mb", type=int, default=4,
                     metavar="MB",
                     help="journal segment rotation size; rotation "
                     "compacts the fully-terminal segment prefix so "
                     "replay cost stays bounded by the live working set")
    rte.add_argument("--decision-log", action="store_true",
                     help="record every dispatcher/autoscaler decision "
                     "to WORKDIR/decisions.jsonl — the capacity "
                     "planner's offline-replay source (soak/bench runs; "
                     "grows with traffic)")
    rte.add_argument("--fault-schedule", default=None, metavar="SPEC",
                     help="deterministic fault injection for soak runs "
                     "(router.forward / replica.health seams); "
                     "production routers leave this unset")

    lod = sub.add_parser(
        "load",
        help="load-generation rig: drive a running lt-route front door "
        "with a seeded deterministic trace — open- or closed-loop "
        "arrivals, heavy-tailed tenant mix, diurnal rate schedule — "
        "and report every request's pinned trace id (README §Capacity "
        "planning)",
    )
    lod.add_argument("--router-url", required=True, metavar="BASE",
                     help="front-door base URL of the running router "
                     "(http://127.0.0.1:PORT)")
    lod.add_argument("--stack-dir", required=True, metavar="DIR",
                     help="Landsat stack directory every submitted job "
                     "segments (lt synth writes one)")
    lod.add_argument("--tile-size", type=int, default=32,
                     help="tile size of the submitted jobs")
    lod.add_argument("--mode", default="closed",
                     choices=["open", "closed"],
                     help="arrival process: open (seeded Poisson "
                     "schedule, offered rate independent of "
                     "completions) or closed (submit → await terminal "
                     "→ think → repeat)")
    lod.add_argument("--duration-s", type=float, default=10.0,
                     metavar="SEC", help="run length")
    lod.add_argument("--qps", type=float, default=2.0, metavar="RATE",
                     help="open-loop mean offered rate, requests/s "
                     "(the diurnal wave modulates around it)")
    lod.add_argument("--requests", type=int, default=0, metavar="N",
                     help="total request budget; 0 = unbounded within "
                     "--duration-s")
    lod.add_argument("--workers", type=int, default=2, metavar="N",
                     help="closed-loop virtual clients / open-loop "
                     "dispatch width")
    lod.add_argument("--seed", type=int, default=0,
                     help="trace seed: the same seed+config "
                     "regenerates the same arrivals, tenants and "
                     "trace ids byte for byte")
    lod.add_argument("--tenants", type=int, default=3, metavar="N",
                     help="tenant population size (t0..tN-1)")
    lod.add_argument("--tenant-skew", type=float, default=1.0,
                     metavar="EXP",
                     help="heavy-tail exponent of the tenant mix "
                     "(weight of the k-th tenant is 1/k**EXP; 0 = "
                     "uniform)")
    lod.add_argument("--wave-amp", type=float, default=0.0,
                     metavar="AMP",
                     help="diurnal-wave amplitude in [0,1): rate is "
                     "qps*(1+AMP*sin(2*pi*t/period)); 0 = flat")
    lod.add_argument("--wave-period-s", type=float, default=60.0,
                     metavar="SEC", help="diurnal-wave period")
    lod.add_argument("--think-s", type=float, default=0.0, metavar="SEC",
                     help="closed-loop think time between a completion "
                     "and the next submission")
    lod.add_argument("--timeout-s", type=float, default=120.0,
                     metavar="SEC",
                     help="per-request patience: a job not terminal "
                     "after SEC counts failed")
    lod.add_argument("--out", default=None, metavar="PATH",
                     help="also write the full per-request outcome "
                     "report JSON here")

    tun = sub.add_parser(
        "tune",
        help="autotune the execution knobs: run short per-device "
        "calibration probes (feed/decode/upload/fetch/dispatch groups), "
        "persist the winning profile to the on-disk tuning store keyed "
        "by (device kind, backend, scene shape class), and report it; a "
        "key already in the store reloads with ZERO probes "
        "(README §Autotuning)",
    )
    tun.add_argument("--store-dir", default="lt_tune_store", metavar="DIR",
                     help="tuning-store directory the profile persists "
                     "to / reloads from (point runs and serve replicas "
                     "at it via --tune-store-dir)")
    tun.add_argument("--shape", default="512,512,40", metavar="H,W,NY",
                     help="scene shape class to tune for (height, width, "
                     "years — bucketed coarsely, so a representative "
                     "scene stands in for the fleet's workload)")
    tun.add_argument("--groups", default=None, metavar="G1,G2,...",
                     help="probe only these knob groups (feed, decode, "
                     "upload, fetch, dispatch); unnamed groups keep "
                     "their default knobs")
    tun.add_argument("--reps", type=int, default=3,
                     help="timing reps per candidate (median taken; a "
                     "clearly-losing candidate is cut off after one)")
    tun.add_argument("--smoke", action="store_true",
                     help="seconds-scale probe workloads (CI tier)")
    tun.add_argument("--retune", action="store_true",
                     help="probe even when the store already holds this "
                     "key's profile (and overwrite it)")
    tun.add_argument("--dry-run", action="store_true",
                     help="probe and report, write NOTHING to the store")
    tun.add_argument("--workdir", default=None, metavar="DIR",
                     help="also write tune telemetry (events.jsonl with "
                     "tune_probe/tune_profile, lt_tune_* metrics) under "
                     "DIR")

    par = sub.add_parser("params", help="print default LTParams JSON")
    _add_param_flags(par)

    syn = sub.add_parser("synth", help="write a synthetic Landsat stack")
    syn.add_argument("out_dir")
    syn.add_argument("--size", type=int, default=256)
    syn.add_argument("--year-start", type=int, default=1984)
    syn.add_argument("--year-end", type=int, default=2023)
    syn.add_argument("--seed", type=int, default=20260729)

    inf = sub.add_parser(
        "info",
        help="inspect rasters header-only (the gdalinfo seam): shape, "
        "dtype, layout, compression, georeferencing — no pixel decode, "
        "O(tags) even on a multi-GB mosaic",
    )
    inf.add_argument("paths", nargs="+", help="GeoTIFF file(s)")
    inf.add_argument("--window", default=None, metavar="Y0,X0,H,W",
                     help="also decode this window and report value stats "
                     "(min/max/mean over finite samples) — a bounded-memory "
                     "spot check on rasters too big to read whole")
    return p


#: value-carrying fields that flip with the index's disturbance sign —
#: must match the driver's raster convention (runtime/driver.py _tile_arrays)
_SIGNED_FIELDS = (
    "vertex_src_vals", "vertex_fit_vals", "seg_magnitude", "seg_rate",
    "fitted", "despiked",
)


def _result_to_dict(res, sign: float = 1.0) -> dict:
    """SegmentationResult / one-pixel SegOutputs → plain-JSON dict.

    ``sign`` undoes the disturbance-positive input flip so printed values
    match the index's natural orientation — the same convention the
    segment pipeline's rasters use.
    """
    import numpy as np

    out = {}
    for name in (
        "n_vertices", "vertex_indices", "vertex_years", "vertex_src_vals",
        "vertex_fit_vals", "seg_magnitude", "seg_duration", "seg_rate",
        "rmse", "p_of_f", "model_valid", "fitted", "despiked",
    ):
        v = np.asarray(getattr(res, name))
        if name in _SIGNED_FIELDS:
            v = sign * v
        out[name] = v.item() if v.ndim == 0 else v.tolist()
    out["model_valid"] = bool(out["model_valid"])
    out["n_vertices"] = int(out["n_vertices"])
    return out


def _pixel_from_stack(args: argparse.Namespace):
    """(years, natural-orientation series, mask) for one stack pixel,
    through the SAME index/masking path the tile feed applies."""
    import numpy as np

    from land_trendr_tpu.ops import indices as idx
    from land_trendr_tpu.runtime import load_stack_dir

    if args.x is None or args.y is None:
        raise SystemExit("--from-stack needs --x and --y")
    index = (args.index or "nbr").lower()
    stack = load_stack_dir(args.from_stack, bands=idx.required_bands(index))
    h, w = stack.shape
    if not (0 <= args.y < h and 0 <= args.x < w):
        raise SystemExit(f"pixel ({args.x}, {args.y}) outside raster {w}x{h}")
    dn = {
        b: a[:, args.y, args.x] for b, a in stack.dn_bands.items()
    }  # (NY,) per band
    sr = {b: idx.scale_sr(v, args.scale, args.offset) for b, v in dn.items()}
    qa = stack.qa[:, args.y, args.x]
    mask = np.asarray(idx.qa_valid_mask(qa)) & np.asarray(idx.sr_valid_mask(sr))
    # NATURAL orientation here: _run_pixel's shared sign handling applies
    # the disturbance-positive flip exactly once, like the JSON path
    series = np.asarray(
        idx.compute_index(index, sr, disturbance_positive=False),
        dtype=np.float64,
    )
    return stack.years, series, mask, index


def _run_pixel(args: argparse.Namespace) -> int:
    """Single-pixel debug path: one series through oracle and/or kernel."""
    import numpy as np

    if (args.series is None) == (args.from_stack is None):
        raise SystemExit("pass exactly one of SERIES or --from-stack DIR")
    if args.from_stack:
        years, values, mask, index = _pixel_from_stack(args)
        args.index = index  # sign handling below follows the JSON path
    else:
        if args.series == "-":
            payload = json.load(sys.stdin)
        else:
            with open(args.series) as f:
                payload = json.load(f)
        years = np.asarray(payload["years"], dtype=np.int32)
        values = np.asarray(payload["values"], dtype=np.float64)
        mask = (
            np.asarray(payload["mask"], dtype=bool)
            if "mask" in payload
            else np.isfinite(values)
        )
    if years.shape != values.shape or years.shape != mask.shape:
        raise SystemExit("years/values/mask must have identical lengths")
    sign = 1.0
    if args.index:
        from land_trendr_tpu.ops.indices import DISTURBANCE_SIGN

        sign = DISTURBANCE_SIGN[args.index.lower()]
        values = sign * values
    params = _params_from_args(args)

    result: dict = {"params": params.to_dict()}
    if args.engine in ("oracle", "both"):
        from land_trendr_tpu.models.oracle import PixelSegmenter

        result["oracle"] = _result_to_dict(
            PixelSegmenter(params).segment(years, values, mask), sign
        )
    if args.engine in ("jax", "both"):
        from land_trendr_tpu.ops.segment import jax_segment_pixels

        out = jax_segment_pixels(years, values[None, :], mask[None, :], params)
        result["jax"] = _result_to_dict(
            type(out)(*(np.asarray(f)[0] for f in out)), sign
        )
        result["jax"]["dtype"] = str(np.asarray(out.fitted).dtype)
    if args.engine == "both":
        o, j = result["oracle"], result["jax"]
        result["parity"] = {
            "vertex_indices_equal": o["vertex_indices"] == j["vertex_indices"],
            "model_valid_equal": o["model_valid"] == j["model_valid"],
            "max_abs_fitted_delta": float(
                np.max(np.abs(np.asarray(o["fitted"]) - np.asarray(j["fitted"])))
            ),
            "kernel_dtype": j["dtype"],
        }
        if j["dtype"] != "float64":
            # exact vertex parity is a float64 contract (ops/segment.py
            # docstring); f32 knife-edges may pick equivalent models
            result["parity"]["note"] = (
                "kernel ran in float32 (JAX_ENABLE_X64 unset): expect "
                "~1e-6 fitted deltas and possible equivalent-model vertex "
                "differences; exact parity requires x64"
            )
    print(json.dumps(result, indent=2))
    return 0


def _change_filter_from_args(args, prefix: str = ""):
    """One ChangeFilter construction for both the `change` subcommand
    (bare arg names) and `segment --change` (change_-prefixed) — a field
    added to ChangeFilter shows up in both paths or neither."""
    from land_trendr_tpu.ops.change import ChangeFilter

    def g(name):
        return getattr(args, prefix + name)

    return ChangeFilter(
        kind=g("kind"),
        sort=g("sort"),
        min_mag=g("min_mag"),
        min_dur=g("min_dur"),
        max_dur=g("max_dur"),
        min_preval=g("min_preval"),
        max_p=g("max_p"),
        year_min=g("year_min"),
        year_max=g("year_max"),
    )


def _run_tune(args: argparse.Namespace) -> int:
    """``lt tune``: probe (or reload), persist unless --dry-run, report."""
    import time as _time

    from land_trendr_tpu.tune import TuningStore, autotune

    try:
        h, w, ny = (int(v) for v in args.shape.split(","))
    except ValueError:
        print(f"error: --shape {args.shape!r} is not H,W,NY", file=sys.stderr)
        return 2
    groups = (
        tuple(g.strip() for g in args.groups.split(",") if g.strip())
        if args.groups else None
    )
    telemetry = None
    if args.workdir:
        from land_trendr_tpu.obs import Telemetry

        telemetry = Telemetry(args.workdir, fingerprint="tune")
    t0 = _time.perf_counter()
    status = "aborted"
    try:
        if telemetry is not None:
            # the stream contract: every scope opens with run_start — a
            # tune scope is a zero-tile run (impl "tune" names it)
            telemetry.run_start(
                fingerprint="tune", process_index=0, process_count=1,
                tiles_total=0, tiles_todo=0, tiles_skipped_resume=0,
                mesh_devices=1, impl="tune",
            )
        try:
            profile = autotune(
                args.store_dir,
                height=h, width=w, n_years=ny,
                groups=groups,
                reps=args.reps,
                smoke=args.smoke,
                retune=args.retune,
                persist=not args.dry_run,
                telemetry=telemetry,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        status = "ok"
    finally:
        if telemetry is not None:
            wall = _time.perf_counter() - t0
            try:
                telemetry.run_done(
                    status, tiles_done=0, pixels=0,
                    wall_s=round(wall, 3), px_per_s=0.0, fit_rate=0.0,
                )
            finally:
                # a failed terminal emit (full disk) must not leak the
                # exporter thread / event fd
                telemetry.close()
    report = {
        "key": profile["key"],
        "source": profile["source"],
        "probes": 0 if profile["source"] == "store" else profile["probes"],
        "knobs": profile["knobs"],
        "groups": {
            g: {
                k: r[k]
                for k in ("ok", "probes", "default_s", "best_s", "speedup",
                          "error", "knobs")
                if k in r
            }
            for g, r in profile.get("groups", {}).items()
        },
        "store_dir": args.store_dir,
        "persisted": not args.dry_run and profile["source"] == "probed",
    }
    if not args.dry_run and profile["source"] == "probed":
        report["profile_path"] = TuningStore(args.store_dir).path_for(
            profile["key"]
        )
    print(json.dumps(report, indent=2))
    return 0


def _run_info(args) -> int:
    """Header-only raster inspection; one JSON document for all paths."""
    import numpy as np

    from land_trendr_tpu.io.geotiff import read_geotiff_info, read_geotiff_window

    win = None
    if args.window:
        try:
            y0, x0, h, w = (int(v) for v in args.window.split(","))
        except ValueError:
            print(f"--window {args.window!r} is not Y0,X0,H,W", file=sys.stderr)
            return 2
        win = (y0, x0, h, w)

    out = {}
    for path in args.paths:
        geo, info = read_geotiff_info(path)
        rec = {
            "height": info.height,
            "width": info.width,
            "bands": info.bands,
            "dtype": str(info.dtype),
            "layout": "tiled" if info.tiled else "strips",
            "compression": info.compression_name(),
            "bigtiff": info.big,
            "file_bytes": os.path.getsize(path),
            "geotransform": geo.geotransform(),
            "nodata": geo.nodata,
        }
        if win is not None:
            a = np.asarray(read_geotiff_window(path, *win), dtype=np.float64)
            finite = a[np.isfinite(a)]
            rec["window"] = {
                "y0_x0_h_w": list(win),
                "min": float(finite.min()) if finite.size else None,
                "max": float(finite.max()) if finite.size else None,
                "mean": float(finite.mean()) if finite.size else None,
                "finite_frac": round(float(finite.size / a.size), 6) if a.size else None,
            }
        out[path] = rec
    print(json.dumps(out, indent=2))
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
        stream=sys.stderr,
    )

    if args.platform:
        # must land before any jax.devices() call anywhere below
        import jax

        jax.config.update("jax_platforms", args.platform)

    # persistent compile cache shared with bench/parity/watchers so a CLI
    # run inside a TPU window never pays an already-paid compile
    from land_trendr_tpu.utils.compilation_cache import enable_persistent_cache

    enable_persistent_cache()

    if args.cmd == "params":
        print(_params_from_args(args).to_json())
        return 0

    if args.cmd == "serve":
        from land_trendr_tpu.serve import SegmentationServer, ServeConfig

        try:
            scfg = ServeConfig(
                workdir=args.workdir,
                serve_port=args.serve_port,
                serve_host=args.serve_host,
                serve_queue_depth=args.serve_queue_depth,
                tenant_max_inflight=args.tenant_max_inflight,
                job_timeout_s=args.job_timeout_s,
                dropbox_dir=args.dropbox_dir,
                dropbox_poll_s=args.dropbox_poll_s,
                max_jobs=args.max_jobs,
                feed_cache_mb=args.feed_cache_mb,
                decode_workers=args.decode_workers,
                ingest_store_mb=args.ingest_store_mb,
                ingest_store_dir=args.ingest_store_dir,
                tune_store_dir=args.tune_store_dir,
                telemetry=not args.no_telemetry,
                metrics_port=args.metrics_port,
                metrics_host=args.metrics_host,
                metrics_interval_s=args.metrics_interval_s,
                fault_schedule=args.fault_schedule,
                debug_endpoints=not args.no_debug_endpoints,
                flight_ring_events=args.flight_ring_events,
                sampler_interval_s=args.sampler_interval_s,
                request_ring=args.request_ring,
                publish=args.publish,
                publish_interval_s=args.publish_interval_s,
                telemetry_dir=args.telemetry_dir,
                alert_rules=args.alert_rules,
                batch=(
                    "auto" if args.batch == "auto"
                    else args.batch == "on"
                ),
                batch_window_ms=args.batch_window_ms,
                batch_max_tiles=args.batch_max_tiles,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        # probe the API port NOW (REUSEADDR-matched, like the
        # --metrics-port preflight): the real bind happens inside the
        # server constructor, where a busy port is a raw OSError
        if scfg.serve_port:
            import socket

            try:
                with socket.socket() as s:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    s.bind((scfg.serve_host, scfg.serve_port))
            except OSError as e:
                print(
                    f"error: --serve-port {scfg.serve_port} unusable: {e}",
                    file=sys.stderr,
                )
                return 2
        try:
            # the server owns its whole teardown: serve_forever's finally
            # runs _shutdown_shared on every exit path (Ctrl-C included)
            # and a failed constructor unwinds itself, so no stop() call
            # exists at this layer by design
            # lt: noqa[LT008]
            server = SegmentationServer(scfg)
        except OSError as e:
            print(f"error: server startup failed: {e}", file=sys.stderr)
            return 2
        # machine-readable startup line (the ephemeral-port contract):
        # clients read the bound port from here
        print(
            json.dumps(
                {"serving": True, "port": server.port,
                 "workdir": scfg.workdir}
            ),
            flush=True,
        )
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            # Ctrl-C is the documented way to stop an unbounded server:
            # drain state is already durable, exit clean
            pass
        return 0

    if args.cmd == "route":
        from land_trendr_tpu.fleet import FleetRouter, RouterConfig

        try:
            rcfg = RouterConfig(
                workdir=args.workdir,
                route_port=args.route_port,
                route_host=args.route_host,
                replicas=tuple(args.replicas),
                spawn_replicas=args.spawn_replicas,
                replica_args=tuple(args.replica_args.split()),
                replica_inflight=args.replica_inflight,
                route_queue_depth=args.route_queue_depth,
                tenant_quota=args.tenant_quota,
                tenant_weights=args.tenant_weights,
                affinity=not args.no_affinity,
                route_retries=args.route_retries,
                health_interval_s=args.health_interval_s,
                unhealthy_after=args.unhealthy_after,
                autoscale=args.autoscale,
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                scale_up_burn=args.scale_up_burn,
                scale_down_burn=args.scale_down_burn,
                scale_for_s=args.scale_for_s,
                scale_hold_s=args.scale_hold_s,
                telemetry=not args.no_telemetry,
                telemetry_dir=args.telemetry_dir,
                metrics_interval_s=args.metrics_interval_s,
                request_ring=args.request_ring,
                journal=not args.no_journal,
                journal_segment_mb=args.journal_segment_mb,
                decision_log=args.decision_log,
                fault_schedule=args.fault_schedule,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        # probe the front-door port NOW (the serve-port preflight)
        if rcfg.route_port:
            import socket

            try:
                with socket.socket() as s:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    s.bind((rcfg.route_host, rcfg.route_port))
            except OSError as e:
                print(
                    f"error: --route-port {rcfg.route_port} unusable: {e}",
                    file=sys.stderr,
                )
                return 2
        try:
            # the router owns its whole teardown: serve_forever's
            # finally runs _shutdown on every exit path (Ctrl-C
            # included) and a failed constructor unwinds itself
            # lt: noqa[LT008]
            router = FleetRouter(rcfg)
        except (OSError, RuntimeError) as e:
            print(f"error: router startup failed: {e}", file=sys.stderr)
            return 2
        print(
            json.dumps(
                {"routing": True, "port": router.port,
                 "workdir": rcfg.workdir,
                 "replicas": len(router.pool)}
            ),
            flush=True,
        )
        # SIGTERM (the orchestrator's stop signal) drains exactly like
        # Ctrl-C: serve_forever's finally runs _shutdown, which writes
        # the journal's clean marker after a full drain — a SIGTERM'd
        # router restarts without reconciliation probes
        import signal as _signal

        _signal.signal(_signal.SIGTERM, _sigterm_to_interrupt)
        try:
            router.serve_forever()
        except KeyboardInterrupt:
            pass
        return 0

    if args.cmd == "load":
        from land_trendr_tpu.loadgen import (
            HttpClient,
            LoadConfig,
            LoadRunner,
        )
        from land_trendr_tpu.loadgen.trace import SHAPE_PARAMS

        try:
            lcfg = LoadConfig(
                mode=args.mode,
                duration_s=args.duration_s,
                qps=args.qps,
                requests=args.requests,
                workers=args.workers,
                seed=args.seed,
                tenants=args.tenants,
                tenant_skew=args.tenant_skew,
                wave_amp=args.wave_amp,
                wave_period_s=args.wave_period_s,
                think_s=args.think_s,
                timeout_s=args.timeout_s,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

        def _load_payload(req) -> dict:
            return {
                "stack_dir": args.stack_dir,
                "tile_size": args.tile_size,
                "tenant": req.tenant,
                "params": dict(SHAPE_PARAMS[req.shape]),
                "trace_id": req.trace_id,
                "run_overrides": {"retry_backoff_s": 0.0},
            }

        runner = LoadRunner(
            lcfg, HttpClient(args.router_url), _load_payload
        )
        report = runner.run(phase="load")
        summary = {
            "mode": report.mode,
            "offered": report.offered,
            "done": report.done,
            "failed": report.failed,
            "rejected": report.rejected,
            "wall_s": round(report.wall_s, 3),
            "trace_ids": report.trace_ids,
        }
        if args.out:
            payload = {
                **summary,
                "outcomes": [
                    {
                        "trace_id": o.trace_id,
                        "tenant": o.tenant,
                        "shape": o.shape,
                        "outcome": o.outcome,
                        "reason": o.reason,
                        "latency_s": o.latency_s,
                    }
                    for o in report.outcomes
                ],
            }
            # tmp + os.replace: a SIGKILL mid-dump must not tear the
            # report an operator's tooling then reads
            tmp = args.out + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=2)
            os.replace(tmp, args.out)
        print(json.dumps(summary))
        return 0 if report.failed == 0 else 1

    if args.cmd == "tune":
        return _run_tune(args)

    if args.cmd == "info":
        return _run_info(args)

    if args.cmd == "pixel":
        return _run_pixel(args)

    if args.cmd == "synth":
        from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack

        spec = SceneSpec(
            width=args.size, height=args.size,
            year_start=args.year_start, year_end=args.year_end, seed=args.seed,
        )
        paths = write_stack(args.out_dir, make_stack(spec))
        print(json.dumps({"files": len(paths), "out_dir": args.out_dir}))
        return 0

    if args.cmd == "change":
        from land_trendr_tpu.ops.change import ChangeFilter, write_change_maps

        filt = _change_filter_from_args(args)
        paths = write_change_maps(
            args.seg_dir, args.dest, index=args.index, filt=filt, mmu=args.mmu
        )
        print(json.dumps({"outputs": paths}, indent=2))
        return 0

    if args.cmd == "segment":
        # deferred: importing jax before arg validation makes --help slow
        from land_trendr_tpu.runtime import (
            Run,
            RunConfig,
            StallError,
            TileRetriesExhausted,
            assemble_outputs,
            load_stack_dir,
        )

        ftv = tuple(s for s in args.ftv.split(",") if s)
        change_filt = None
        if args.change:
            change_filt = _change_filter_from_args(args, prefix="change_")
        else:
            from land_trendr_tpu.ops.change import ChangeFilter

            if (
                _change_filter_from_args(args, prefix="change_")
                != ChangeFilter()
                or args.change_mmu != 1
            ):
                print(
                    "error: --change-* options require --change (without "
                    "it no change rasters are produced)",
                    file=sys.stderr,
                )
                return 2
        if args.no_packed_fetch and args.packed_fetch:
            print(
                "error: --packed-fetch conflicts with --no-packed-fetch",
                file=sys.stderr,
            )
            return 2
        if args.no_packed_upload and args.packed_upload:
            print(
                "error: --packed-upload conflicts with --no-packed-upload",
                file=sys.stderr,
            )
            return 2
        try:
            cfg = RunConfig(
                index=args.index,
                ftv_indices=ftv,
                params=_params_from_args(args),
                tile_size=args.tile_size,
                workdir=args.workdir,
                out_dir=args.out_dir,
                resume=not args.no_resume,
                max_retries=args.max_retries,
                write_fitted=args.write_fitted,
                products=(
                    tuple(x.strip() for x in args.products.split(","))
                    if args.products else None
                ),
                fetch_f16=args.fetch_f16,
                fetch_packed=(
                    False if args.no_packed_fetch
                    else True if args.packed_fetch else "auto"
                ),
                fetch_depth=args.fetch_depth,
                upload_packed=(
                    False if args.no_packed_upload
                    else True if args.packed_upload else "auto"
                ),
                upload_depth=args.upload_depth,
                ingest_store_mb=args.ingest_store_mb,
                ingest_store_dir=args.ingest_store_dir,
                scale=args.scale,
                offset=args.offset,
                out_compress=args.out_compress,
                manifest_compress=args.manifest_compress,
                write_workers=args.write_workers,
                feed_workers=args.feed_workers,
                feed_cache_mb=args.feed_cache_mb,
                decode_workers=args.decode_workers,
                tune_store_dir=args.tune_store_dir,
                feed_readahead=not args.no_feed_readahead,
                reject_bits=args.reject_bits,
                chunk_px=args.chunk_px,
                retry_backoff_s=args.retry_backoff_s,
                quarantine_tiles=args.quarantine_tiles,
                stall_timeout_s=args.stall_timeout_s,
                merge_timeout_s=args.merge_timeout_s,
                straggler_k=args.straggler_k,
                straggler_min_tiles=args.straggler_min_tiles,
                lease_batch=args.lease_batch,
                lease_ttl_s=args.lease_ttl_s,
                speculate=args.speculate,
                fault_schedule=args.fault_schedule,
                metrics_interval_s=args.metrics_interval_s,
                impl=args.impl,
                change_filt=change_filt,
                out_overviews=args.out_overviews,
                telemetry=args.telemetry,
                metrics_port=args.metrics_port,
                metrics_host=args.metrics_host,
                flight=args.flight,
                flight_ring_events=args.flight_ring_events,
                sampler_interval_s=args.sampler_interval_s,
                publish=args.publish,
                publish_interval_s=args.publish_interval_s,
                telemetry_dir=args.telemetry_dir,
            )
        except ValueError as e:
            # argument errors (bad --products name, out-of-range workers…)
            # exit like every other CLI argument conflict — a clean message
            # and code 2, not a RunConfig traceback (ADVICE round 5)
            print(f"error: {e}", file=sys.stderr)
            return 2
        if cfg.metrics_port is not None:
            # probe the scrape port NOW, before the stack open / resume
            # scan: the real bind happens deep inside run_stack, where a
            # busy port would surface as a raw OSError traceback minutes in
            import socket

            try:
                with socket.socket() as s:
                    # match the real server's bind semantics
                    # (http.server sets allow_reuse_address) — without
                    # this the probe rejects a port merely in TIME_WAIT
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    s.bind((cfg.metrics_host, cfg.metrics_port))
            except OSError as e:
                print(
                    f"error: --metrics-port {cfg.metrics_port} "
                    f"unusable: {e}",
                    file=sys.stderr,
                )
                return 2
        mesh = None
        if args.mesh:
            import jax

            from land_trendr_tpu.parallel import make_mesh

            # local devices only: tiles are the cross-host unit (run_stack
            # rejects non-addressable meshes)
            mesh = make_mesh(jax.local_devices())
        # load only the cubes this run's index selection needs (e.g. NBR:
        # nir+swir2+QA = 3 cubes instead of 7 — ~2.3× less host memory;
        # the C2 per-band layout also skips decoding the unused files)
        from land_trendr_tpu.ops.indices import required_bands

        if args.lazy:
            if args.composite is not None:
                raise SystemExit(
                    "--lazy cannot composite (one acquisition per year); "
                    "pre-composite or drop --lazy"
                )
            from land_trendr_tpu.runtime.stack import open_stack_dir_c2_lazy

            stack = open_stack_dir_c2_lazy(
                args.stack_dir, bands=required_bands(args.index, ftv)
            )
        else:
            stack = load_stack_dir(
                args.stack_dir,
                bands=required_bands(args.index, ftv),
                composite=args.composite,
                # composite validity masks must match the run's own masking
                reject_bits=cfg.reject_bits,
                scale=cfg.scale,
                offset=cfg.offset,
            )
        # exit-code contract (README §Failure semantics — orchestrators
        # branch on these): 2 config/usage error, 3 tile(s) exhausted
        # retries / quarantined (retryable: resume re-attempts exactly the
        # failed tiles), 4 stall-watchdog abort (investigate the device)
        # an explicit Run (not the run_stack one-shot): its RESOLVED
        # config — "auto" knobs pulled from the tuning store exactly once
        # at construction — is what assembly below must reuse, so a store
        # re-probed mid-run cannot re-resolve the sentinels differently
        run = Run(stack, cfg, mesh=mesh)
        try:
            if args.trace:
                from land_trendr_tpu.utils.profiling import trace

                with trace(args.trace):
                    summary = run.execute()
            else:
                summary = run.execute()
        except StallError as e:
            print(f"error: {e}", file=sys.stderr)
            return 4
        except TileRetriesExhausted as e:
            print(f"error: {e} (re-run to resume from the manifest)",
                  file=sys.stderr)
            return 3
        if summary.get("tiles_quarantined"):
            # incomplete manifest: assembly would fail on the missing
            # tiles — report what finished, exit retryable
            print(json.dumps({"summary": summary, "outputs": None}, indent=2))
            print(
                f"error: {len(summary['tiles_quarantined'])} tile(s) "
                "quarantined after exhausting retries; outputs NOT "
                "assembled (re-run to resume the quarantined tiles)",
                file=sys.stderr,
            )
            return 3
        paths = assemble_outputs(stack, run.cfg)
        if change_filt is not None and args.change_mmu > 1:
            from land_trendr_tpu.ops.change import sieve_change_rasters

            sieve_change_rasters(run.cfg.out_dir, args.change_mmu)
        print(json.dumps({"summary": summary, "outputs": paths}, indent=2))
        return 0

    raise AssertionError(f"unhandled command {args.cmd!r}")


def run() -> int:
    """Console entry; exits quietly when stdout is a closed pipe (head, less)."""
    try:
        return main()
    except BrokenPipeError:
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(run())
