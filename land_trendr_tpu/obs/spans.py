"""Pod-wide span model: per-tile spans, cross-host trace assembly,
critical-path attribution, and live straggler detection.

The event stream (:mod:`land_trendr_tpu.obs.events`) answers "what
happened on this host"; nothing so far answered the questions the
*Massively-Parallel Break Detection* paper (PAPERS.md, arXiv:1807.01751)
says dominate continent-scale runs — *which host is behind, which stage
bounds the wall clock, and which tiles are the stragglers*.  This module
is the span half of the obs subsystem:

* **Span model** — every tile's pipeline passage decomposes into named
  stages (:data:`SPAN_STAGES`).  Three are *explicit* ``span`` events
  the driver emits (``feed``, ``upload``, ``fetch`` — host-blocking
  work no existing event pair covers); the rest are *derived* from the
  lifecycle events already in the stream (``compute`` from
  ``tile_done.compute_s``, ``write`` from ``write_done.record_s``,
  ``attempt`` from ``tile_start``/``tile_retry``/``tile_done`` pairs).
  Every span carries the correlation IDs of its scope: ``run_id`` /
  ``job_id`` (serve mode) / ``host`` / ``tile`` / ``attempt``.  The
  ``decode`` stage has no per-tile span of its own — block decode runs
  in a shared pool where per-tile attribution would be a lie; it rides
  inside ``feed`` and the ``feed_cache`` rollup carries its split.

* **Cross-host clock alignment** — each host's ``run_start`` records a
  ``(anchor_wall, anchor_mono)`` pair sampled together (see
  :meth:`~land_trendr_tpu.obs.events.EventLog.run_start`).  The pod
  assembler (:func:`assemble_pod_trace`) maps every host's monotonic
  clock onto ONE pod timeline whose origin is each scope's
  ``run_start`` — the distributed-init barrier means hosts enter
  ``run_stack`` together, so aligning on ``run_start`` removes wall
  skew between hosts *by construction* (a host whose NTP is an hour off
  assembles exactly like a synchronized one).  The apparent wall skew
  the alignment removed is reported per host (``wall_skew_s``), never
  trusted.  Caveat: genuine start stagger beyond the barrier (sub-second
  in practice) is folded into the alignment.

* **Critical-path attribution** (:func:`critical_path`) — a
  pipeline-aware wall decomposition: per host, stage totals from the
  assembled spans bound the wall two ways (removing stage X saves at
  most its own seconds — the serial view — and the wall cannot drop
  below the next-binding stage's total — the pipeline view), so
  ``est_wall_without[X] = max(wall - stage_s[X], max(other stage_s))``
  and ``faster_pct`` answers "if stage X were free, the run would be Y%
  faster".  Pod-wide the run ends with its last host, so the pod
  estimate is the max of the per-host estimates.

* **Live straggler detection** (:class:`StragglerDetector`) — the
  driver registers every dispatched tile and checks completions (and,
  from the sampler thread, in-flight tiles) against ``k ×`` the rolling
  median of recent tile durations.  A flagged tile emits
  ``tile_straggler``, bumps ``lt_stragglers_total``, and shows in
  ``/debug/jobs`` / ``lt top``.  No verdicts until ``min_tiles`` tiles
  completed (the first tile carries the compile and must never
  false-positive); each tile flags at most once.

Everything here is stdlib-only and jax-free, like the rest of
:mod:`land_trendr_tpu.obs`.  Consumers: ``tools/lt_trace.py`` (pod
Chrome trace + imbalance report), ``tools/obs_report.py`` (per-host
rollups), the runtime driver (detector + span emits).
"""

from __future__ import annotations

import json
import statistics
import threading
import time
from typing import Any, Callable

__all__ = [
    "SPAN_STAGES",
    "StragglerDetector",
    "assemble_pod_trace",
    "busy_union_s",
    "critical_path",
    "scope_anchor",
    "tail_ratio",
]

#: the span vocabulary — stage names of one tile's pipeline passage, in
#: pipeline order.  ``feed``/``upload``/``fetch`` are explicit ``span``
#: events; ``compute``/``write``/``attempt`` are derived from lifecycle
#: events; ``decode`` rides inside ``feed`` (see module doc).
SPAN_STAGES = (
    "feed", "decode", "upload", "compute", "fetch", "write", "attempt",
)

#: stages that enter critical-path stage totals.  ``attempt`` spans
#: OVERLAP the others (an attempt contains its compute), so counting
#: them would double-book the wall.
_PATH_STAGES = ("feed", "upload", "compute", "fetch", "write")


def scope_anchor(run_start: dict) -> "tuple[float, float]":
    """One scope's ``(wall, monotonic)`` clock anchor.

    Prefers the explicit ``anchor_wall``/``anchor_mono`` pair (sampled
    together by :meth:`EventLog.run_start`); streams from before the
    anchors existed fall back to the record's own ``t_wall``/``t_mono``
    (also sampled together, by ``emit``).
    """
    w = run_start.get("anchor_wall", run_start.get("t_wall", 0.0))
    m = run_start.get("anchor_mono", run_start.get("t_mono", 0.0))
    return float(w), float(m)


def busy_union_s(intervals: "list[tuple[float, float]]") -> float:
    """Total covered seconds of a set of (start, end) intervals.

    The host-busy measure behind the idle-gap report: spans from
    overlapped pipeline stages double-cover time, so the UNION (not the
    sum) is what "the host was doing something" means.
    """
    if not intervals:
        return 0.0
    total = 0.0
    cur_s = cur_e = None
    for s, e in sorted(intervals):
        if e < s:
            s, e = e, s
        if cur_e is None:
            cur_s, cur_e = s, e
        elif s <= cur_e:
            cur_e = max(cur_e, e)
        else:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
    total += cur_e - cur_s
    return total


def _quantile(sorted_vals: "list[float]", p: float) -> float:
    """The same nearest-rank convention as ``obs_report._stats``."""
    return sorted_vals[min(len(sorted_vals) - 1, int(p * len(sorted_vals)))]


def tail_ratio(durations: "list[float]") -> "float | None":
    """p95 / p50 of a duration population — the per-host tail-imbalance
    number ("how much worse is a bad tile than a typical one").  None
    when fewer than 2 samples or the median is 0."""
    if len(durations) < 2:
        return None
    v = sorted(durations)
    p50 = _quantile(v, 0.50)
    if p50 <= 0:
        return None
    return round(_quantile(v, 0.95) / p50, 3)


def critical_path(stage_s: "dict[str, float]", wall_s: float) -> "dict | None":
    """Pipeline-aware "which stage bounds this wall" attribution.

    ``stage_s`` maps stage name → total seconds (span sums); ``wall_s``
    is the observed wall.  For each stage X the estimated wall with X
    free is ``max(wall_s - stage_s[X], max(stage_s[Y] for Y != X))`` —
    removing X can save at most its own seconds, and a pipelined run
    cannot finish faster than its next-binding stage's total.
    ``bound_stage`` is the stage whose removal saves the most (ties
    break lexicographically, deterministically).
    """
    stages = {
        k: float(v) for k, v in stage_s.items()
        if k not in ("attempt", "decode") and v is not None
    }
    if not stages or not wall_s or wall_s <= 0:
        return None
    out: dict = {"wall_s": round(wall_s, 4), "if_free": {}}
    best: "tuple[float, str] | None" = None
    for x in sorted(stages):
        rest = max((v for k, v in stages.items() if k != x), default=0.0)
        est = max(wall_s - stages[x], rest, 0.0)
        est = min(est, wall_s)
        saved = wall_s - est
        out["if_free"][x] = {
            "stage_s": round(stages[x], 4),
            "est_wall_s": round(est, 4),
            "saved_s": round(saved, 4),
            "faster_pct": round(100.0 * saved / wall_s, 2),
        }
        if best is None or saved > best[0]:
            best = (saved, x)
    out["bound_stage"] = best[1]
    return out


# ---------------------------------------------------------------------------
# pod-trace assembly
# ---------------------------------------------------------------------------


def _num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _last_scope(path: str) -> "tuple[list[dict], int]":
    """The LAST run scope of one per-process event file (records after
    its final ``run_start``, inclusive) plus a malformed-line count.

    The pod trace describes the run the workdir currently holds — a
    resumed file's aborted earlier scope belongs to a different wall
    clock and must not fold in (the same "most recent run" semantics as
    ``summarize_events_file``).
    """
    scope: "list[dict]" = []
    opened = False
    malformed = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                malformed += 1
                continue
            if not isinstance(rec, dict) or not isinstance(rec.get("ev"), str):
                malformed += 1
                continue
            if rec["ev"] == "run_start":
                scope = [rec]
                opened = True
            elif opened:
                scope.append(rec)
            else:
                # events before any run_start: a torn/foreign stream head
                malformed += 1
    return scope, malformed


def _fold_host_scope(
    scope: "list[dict]", fileno: int, path: str
) -> "tuple[dict, list[dict], list[dict]]":
    """One host's last scope → (host summary, spans, markers).

    Span/marker times are POD-RELATIVE seconds: 0 at this host's
    ``run_start`` (its scope anchor).  The caller owns cross-host
    concerns (skew report, ordering, pod rollups).
    """
    host: dict = {
        "events_file": path,
        "file": fileno,
        "host": None,
        "process_index": fileno,
        "pid": None,
        "run_id": None,
        "status": None,
        "wall_s": None,
        "px_per_s": None,
        "pixels": 0,
        "tiles_done": 0,
        "stragglers": 0,
        "retries": 0,
        # elastic pod scheduling (runtime/leases): acquisitions this
        # host won, split by kind — the steal/speculation imbalance view
        "tiles_leased": 0,
        "tiles_stolen": 0,
        "tiles_speculated": 0,
    }
    spans: "list[dict]" = []
    markers: "list[dict]" = []
    if not scope:
        return host, spans, markers
    rs = scope[0]
    aw, am = scope_anchor(rs)
    host.update(
        host=rs.get("host"),
        process_index=(
            rs["process_index"]
            if isinstance(rs.get("process_index"), int)
            else fileno
        ),
        pid=rs.get("pid"),
        run_id=rs.get("run_id"),
        anchor_wall=aw,
        anchor_mono=am,
    )
    compute_durs: "list[float]" = []
    #: tile -> (pod start, attempt) for the open attempt span
    open_attempt: "dict[int, tuple[float, int]]" = {}
    t_max = 0.0

    def _pod(rec: dict) -> "float | None":
        m = rec.get("t_mono")
        return (m - am) if _num(m) else None

    def _add(
        name: str, tile: Any, t0: float, dur: float, rec: dict,
        attempt: "int | None" = None,
    ) -> None:
        nonlocal t_max
        dur = max(float(dur), 0.0)
        t0 = float(t0)
        span = {
            "name": name,
            "tile": tile,
            "t0": round(t0, 6),
            "dur": round(dur, 6),
            "file": fileno,
            "process_index": host["process_index"],
            "host": host["host"],
            "run_id": host["run_id"],
        }
        if attempt is not None:
            span["attempt"] = attempt
        if rec.get("job_id") is not None:
            span["job_id"] = rec["job_id"]
        spans.append(span)
        t_max = max(t_max, t0 + dur)

    for rec in scope[1:]:
        ev = rec.get("ev")
        t = _pod(rec)
        if t is None:
            continue
        t_max = max(t_max, t)
        try:
            if ev == "span":
                name, tile = rec["name"], rec["tile_id"]
                s0, s1 = rec["start"], rec["end"]
                if not (_num(s0) and _num(s1)):
                    continue
                _add(
                    str(name), tile, s0 - am, s1 - s0, rec,
                    attempt=rec.get("attempt"),
                )
            elif ev == "tile_start":
                tile = rec["tile_id"]
                open_attempt[tile] = (t, int(rec.get("attempt", 1)))
            elif ev == "tile_retry":
                tile = rec["tile_id"]
                host["retries"] += 1
                if tile in open_attempt:
                    t0, att = open_attempt.pop(tile)
                    _add("attempt", tile, t0, t - t0, rec, attempt=att)
            elif ev == "tile_done":
                tile, c_s = rec["tile_id"], rec["compute_s"]
                if not _num(c_s):
                    continue
                host["tiles_done"] += 1
                host["pixels"] += int(rec.get("px", 0) or 0)
                compute_durs.append(float(c_s))
                _add("compute", tile, t - c_s, c_s, rec)
                if tile in open_attempt:
                    t0, att = open_attempt.pop(tile)
                    _add("attempt", tile, t0, t - t0, rec, attempt=att)
            elif ev == "write_done":
                tile, r_s = rec["tile_id"], rec["record_s"]
                if not _num(r_s):
                    continue
                _add("write", tile, t - r_s, r_s, rec)
            elif ev == "tile_straggler":
                host["stragglers"] += 1
                markers.append({
                    "name": "straggler",
                    "tile": rec["tile_id"],
                    "t0": round(t, 6),
                    "file": fileno,
                    "host": host["host"],
                    "duration_s": rec.get("duration_s"),
                    "threshold_s": rec.get("threshold_s"),
                })
            elif ev == "tile_leased":
                host["tiles_leased"] += 1
            elif ev in ("lease_stolen", "tile_speculated"):
                # steals and speculative re-leases are the elastic
                # scheduler ACTING — instants on the trace, like the
                # straggler verdicts that steered them
                host["tiles_leased"] += 1
                key = (
                    "tiles_stolen" if ev == "lease_stolen"
                    else "tiles_speculated"
                )
                host[key] += 1
                markers.append({
                    "name": "steal" if ev == "lease_stolen" else "speculate",
                    "tile": rec["tile_id"],
                    "t0": round(t, 6),
                    "file": fileno,
                    "host": host["host"],
                    "gen": rec.get("gen"),
                })
            elif ev == "run_done":
                host["status"] = rec.get("status")
                if _num(rec.get("wall_s")):
                    host["wall_s"] = float(rec["wall_s"])
                if _num(rec.get("px_per_s")):
                    host["px_per_s"] = rec["px_per_s"]
        except (KeyError, TypeError):
            continue

    # host facts derived from the folded spans
    if host["wall_s"] is None and t_max > 0:
        host["wall_s"] = round(t_max, 4)
    intervals = [(s["t0"], s["t0"] + s["dur"]) for s in spans]
    busy = busy_union_s(intervals)
    host["busy_s"] = round(busy, 4)
    if host["wall_s"] is not None:
        host["idle_gap_s"] = round(max(host["wall_s"] - busy, 0.0), 4)
    host["tail_ratio"] = tail_ratio(compute_durs)
    stage_sums: "dict[str, float]" = {}
    for s in spans:
        stage_sums[s["name"]] = stage_sums.get(s["name"], 0.0) + s["dur"]
    host["stage_s"] = {k: round(v, 4) for k, v in sorted(stage_sums.items())}
    host["critical_path"] = critical_path(stage_sums, host["wall_s"] or 0.0)
    return host, spans, markers


def assemble_pod_trace(paths: "list[str]") -> dict:
    """Fold N per-host event files into one offset-corrected pod trace.

    Each file contributes its LAST run scope, aligned on the pod
    timeline (``t=0`` at every host's ``run_start`` — the clock-skew
    removal documented in the module header).  Returns::

        {
          "files": N, "malformed": n,
          "hosts":  [per-host summary: wall/busy/idle/tail/stragglers,
                     stage seconds, per-host critical path, wall_skew_s],
          "spans":  [{name, tile, t0, dur, file, process_index, host,
                      run_id, attempt?, job_id?}, ...]  # sorted, stable
          "markers": [straggler instants],
          "pod":    {wall_s, stage_s, critical_path, host_imbalance,
                     tail_ratio, stragglers, pixels, px_per_s},
        }

    Deterministic and byte-stable: the same input files produce the
    identical structure (and identical ``json.dumps``) on every fold —
    spans sort by ``(t0, file, name, tile, attempt)`` with rounding
    applied before the sort.
    """
    hosts: "list[dict]" = []
    all_spans: "list[dict]" = []
    all_markers: "list[dict]" = []
    malformed = 0
    for fileno, path in enumerate(paths):
        scope, bad = _last_scope(path)
        malformed += bad
        host, spans, markers = _fold_host_scope(scope, fileno, path)
        hosts.append(host)
        all_spans.extend(spans)
        all_markers.extend(markers)

    # apparent wall skew the run_start alignment removed, per host
    anchors = [h.get("anchor_wall") for h in hosts if h.get("anchor_wall")]
    origin = min(anchors) if anchors else 0.0
    for h in hosts:
        if h.get("anchor_wall") is not None:
            h["wall_skew_s"] = round(h["anchor_wall"] - origin, 6)

    all_spans.sort(
        key=lambda s: (
            s["t0"], s["file"], s["name"],
            s["tile"] if isinstance(s["tile"], int) else -1,
            s.get("attempt") or 0,
        )
    )
    all_markers.sort(key=lambda m: (m["t0"], m["file"]))

    pod_stage: "dict[str, float]" = {}
    for h in hosts:
        for k, v in (h.get("stage_s") or {}).items():
            pod_stage[k] = pod_stage.get(k, 0.0) + v
    walls = [h["wall_s"] for h in hosts if h.get("wall_s")]
    pod_wall = max(walls) if walls else 0.0
    pod: dict = {
        "wall_s": round(pod_wall, 4),
        "stage_s": {k: round(v, 4) for k, v in sorted(pod_stage.items())},
        "stragglers": sum(h["stragglers"] for h in hosts),
        "tiles_leased": sum(h["tiles_leased"] for h in hosts),
        "tiles_stolen": sum(h["tiles_stolen"] for h in hosts),
        "tiles_speculated": sum(h["tiles_speculated"] for h in hosts),
        "pixels": sum(h["pixels"] for h in hosts),
        "px_per_s": (
            round(sum(h["pixels"] for h in hosts) / pod_wall, 1)
            if pod_wall else None
        ),
        "host_imbalance": (
            round(max(walls) / (sum(walls) / len(walls)), 3)
            if walls and sum(walls) else None
        ),
        "tail_ratio": tail_ratio(
            [s["dur"] for s in all_spans if s["name"] == "compute"]
        ),
    }
    # pod critical path: the run ends with its last host, so the pod
    # estimate for "stage X free" is the max of the per-host estimates
    if pod_wall:
        if_free: dict = {}
        stages = sorted(
            {
                k
                for h in hosts
                for k in (h.get("stage_s") or {})
                if k not in ("attempt", "decode")
            }
        )
        for x in stages:
            ests = []
            for h in hosts:
                cp = h.get("critical_path")
                if cp is None:
                    continue
                fx = cp["if_free"].get(x)
                ests.append(
                    fx["est_wall_s"] if fx is not None else cp["wall_s"]
                )
            if not ests:
                continue
            est = max(ests)
            est = min(est, pod_wall)
            if_free[x] = {
                "stage_s": round(pod_stage.get(x, 0.0), 4),
                "est_wall_s": round(est, 4),
                "saved_s": round(pod_wall - est, 4),
                "faster_pct": round(100.0 * (pod_wall - est) / pod_wall, 2),
            }
        if if_free:
            bound = max(
                sorted(if_free), key=lambda k: if_free[k]["saved_s"]
            )
            pod["critical_path"] = {
                "wall_s": round(pod_wall, 4),
                "bound_stage": bound,
                "if_free": if_free,
            }
    return {
        "files": len(paths),
        "malformed": malformed,
        "hosts": hosts,
        "spans": all_spans,
        "markers": all_markers,
        "pod": pod,
    }


# ---------------------------------------------------------------------------
# live straggler detection
# ---------------------------------------------------------------------------


class StragglerDetector:
    """Rolling-median straggler verdicts over in-flight tile durations.

    The driver calls :meth:`start` when a tile's attempt dispatches and
    :meth:`finish` when the tile completes (fetch landed); the finish
    checks the completed duration against ``k × median`` of the last
    ``window`` completions *before* folding it into the window, so a
    straggler never dilutes the very median that judges it.
    :meth:`scan` applies the same verdict to still-in-flight tiles — the
    liveness half, callable from the flight sampler thread while the
    driver is blocked inside the straggler's own device wait.

    Rules, pinned by ``tests/test_spans.py``:

    * no verdicts until ``min_tiles`` tiles have completed (the first
      tile carries the jit compile; it must never false-positive);
    * each tile flags at most once (finish after a scan-flag is silent);
    * a retried attempt restarts the tile's in-flight clock;
    * ``drop`` forgets a quarantined/failed tile without a verdict.

    ``on_straggler(tile_id, duration_s, threshold_s, median_s,
    in_flight, attempt)`` fires OUTSIDE the lock; exceptions propagate
    to the caller (the driver treats telemetry-emit failures the same
    everywhere).  Thread-safe; the lock guards pure bookkeeping only.
    """

    def __init__(
        self,
        k: float = 4.0,
        min_tiles: int = 5,
        window: int = 64,
        on_straggler: "Callable[..., None] | None" = None,
        clock: "Callable[[], float]" = time.monotonic,
    ) -> None:
        if k < 1.0:
            raise ValueError(
                f"k={k} must be >= 1.0 (a threshold below the median "
                "would flag typical tiles)"
            )
        if min_tiles < 1:
            raise ValueError(f"min_tiles={min_tiles} must be >= 1")
        if window < 1:
            raise ValueError(f"window={window} must be >= 1")
        self.k = float(k)
        self.min_tiles = int(min_tiles)
        self.window = int(window)
        self.on_straggler = on_straggler
        self._clock = clock
        self._lock = threading.Lock()
        self._inflight: "dict[int, tuple[float, int]]" = {}
        self._done: "list[float]" = []  # bounded at window, FIFO
        self._completed = 0
        self._flagged: "set[int]" = set()

    # -- internal (callers hold the lock) ----------------------------------
    def _threshold_locked(self) -> "tuple[float | None, float | None]":
        if self._completed < self.min_tiles or not self._done:
            return None, None
        med = float(statistics.median(self._done))
        if med <= 0:
            return med, None
        return med, self.k * med

    def _flag_locked(
        self, tile_id: int, dur: float, in_flight: bool
    ) -> "tuple | None":
        med, thr = self._threshold_locked()
        if thr is None or dur <= thr or tile_id in self._flagged:
            return None
        self._flagged.add(tile_id)
        att = self._inflight.get(tile_id, (0.0, 1))[1]
        return (tile_id, dur, thr, med, in_flight, att)

    def _fire(self, verdict: "tuple | None") -> None:
        if verdict is None or self.on_straggler is None:
            return
        try:
            self.on_straggler(*verdict)
        except BaseException:
            # the verdict never landed (telemetry emit failed): un-flag so
            # a still-in-flight tile gets retried by a later scan instead
            # of being silently verdict-less forever — the sampler thread
            # swallows probe exceptions, so this is the only retry path
            with self._lock:
                self._flagged.discard(verdict[0])
            raise

    # -- driver hooks ------------------------------------------------------
    def start(self, tile_id: int, attempt: int = 1) -> None:
        """Register a dispatched attempt (re-registering restarts the
        tile's in-flight clock — a retry is a fresh attempt)."""
        with self._lock:
            self._inflight[tile_id] = (self._clock(), int(attempt))

    def drop(self, tile_id: int) -> None:
        """Forget a tile without a verdict (quarantine/failure path —
        the failure events already tell that story)."""
        with self._lock:
            self._inflight.pop(tile_id, None)

    def finish(self, tile_id: int) -> "float | None":
        """Complete a tile: returns its in-flight duration (None for an
        unregistered tile) after checking it against the threshold and
        folding it into the rolling window."""
        now = self._clock()
        with self._lock:
            ent = self._inflight.get(tile_id)
            if ent is None:
                return None
            dur = now - ent[0]
            verdict = self._flag_locked(tile_id, dur, in_flight=False)
            self._inflight.pop(tile_id, None)
            self._done.append(dur)
            if len(self._done) > self.window:
                del self._done[0]
            self._completed += 1
        self._fire(verdict)
        return dur

    def scan(self, now: "float | None" = None) -> "list[int]":
        """Flag in-flight tiles already over the threshold; returns the
        tile ids flagged by THIS scan.  Safe from any thread."""
        now = self._clock() if now is None else now
        verdicts = []
        with self._lock:
            _, thr = self._threshold_locked()
            if thr is not None:
                for tid, (t0, _att) in list(self._inflight.items()):
                    v = self._flag_locked(tid, now - t0, in_flight=True)
                    if v is not None:
                        verdicts.append(v)
        for v in verdicts:
            self._fire(v)
        return [v[0] for v in verdicts]

    def stats(self) -> dict:
        """Point-in-time counters for progress dicts / sampler probes."""
        with self._lock:
            med, thr = self._threshold_locked()
            return {
                "stragglers": len(self._flagged),
                "completed": self._completed,
                "in_flight": len(self._inflight),
                "median_s": med,
                "threshold_s": thr,
            }
