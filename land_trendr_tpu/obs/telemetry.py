"""The run-wide telemetry bundle the runtime driver reports through.

One :class:`Telemetry` per ``run_stack`` call ties the two halves of
:mod:`land_trendr_tpu.obs` together and owns their lifecycles:

* the per-process :class:`~land_trendr_tpu.obs.events.EventLog`
  (``<workdir>/events.jsonl``, ``events.p<i>.jsonl`` under multihost);
* a :class:`~land_trendr_tpu.obs.metrics.MetricsRegistry` pre-populated
  with the driver instrument set (the ``lt_*`` names documented in
  README.md §Observability), its :class:`PromFileExporter` refreshing
  ``<workdir>/metrics.prom``, and — when ``metrics_port`` is set — the
  in-flight ``/metrics`` HTTP endpoint.

The driver calls the ``tile_*`` / ``run_*`` hooks; the tile manifest calls
:meth:`write_done` from inside :meth:`TileManifest.record` (writer-pool
threads — every path here is thread-safe).  Deliberately **jax-free**:
device facts (mesh size, resolved impl, HBM live bytes) are plain values
passed in by the driver, so the subsystem tests run without a backend and
the import cost is stdlib-only.
"""

from __future__ import annotations

import os
from typing import Any, Mapping

from land_trendr_tpu.obs.events import EventLog, events_path
from land_trendr_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsHTTPServer,
    MetricsRegistry,
    PromFileExporter,
)

__all__ = ["Telemetry", "metrics_path"]

#: px/s histogram buckets: log-spaced from one-core-CPU (~2e4) past the
#: 10M px/s north star
_PXS_BUCKETS = (1e4, 3e4, 1e5, 3e5, 1e6, 3e6, 1e7, 3e7, 1e8)


def metrics_path(workdir: str, process_index: int = 0, process_count: int = 1) -> str:
    """Per-process ``.prom`` path (mirrors :func:`events_path` naming)."""
    if process_count <= 1:
        return os.path.join(workdir, "metrics.prom")
    return os.path.join(workdir, f"metrics.p{process_index}.prom")


class Telemetry:
    """Event log + metrics registry + exporters for one driver run."""

    def __init__(
        self,
        workdir: str,
        *,
        fingerprint: str = "",
        process_index: int = 0,
        process_count: int = 1,
        metrics_port: int | None = None,
        metrics_host: str = "",
        metrics_interval_s: float = 5.0,
        job_id: str | None = None,
        trace_id: str | None = None,
        flight=None,
        publish_dir: str | None = None,
        publish_interval_s: float = 5.0,
        publish_probes=None,
    ) -> None:
        os.makedirs(workdir, exist_ok=True)
        # fleet publish (obs/publish): with ``publish_dir``, a daemon
        # thread snapshots this registry + the host's ``publish_probes``
        # state into <publish_dir>/<host>.<pid>.snap.json every
        # ``publish_interval_s`` — the per-process feed the pod
        # aggregate (obs/aggregate, tools/lt_fleet.py) folds
        self._publish_dir = publish_dir
        self._publish_interval_s = publish_interval_s
        self._publish_probes = publish_probes
        self._publisher = None
        # serve mode threads the job id — and the fleet-wide trace id
        # minted at router/serve admission — onto EVERY event of this
        # run's scope (EventLog common fields, schema-optional
        # everywhere), so a cross-job fold attributes tile traffic per
        # request and tools/lt_request.py joins the run scope to the
        # router's request spans.  ``flight`` (an
        # obs.flight.FlightRecorder) mirrors every emit into the
        # in-memory ring behind the /debug surface — the run's own ring
        # on --flight runs, the SERVER's shared ring in serve mode (so
        # job tile traffic shows up in /debug/flight live).
        self.flight = flight
        common: dict | None = {}
        if job_id:
            common["job_id"] = job_id
        if trace_id:
            common["trace_id"] = trace_id
        self.events = EventLog(
            events_path(workdir, process_index, process_count),
            common=common or None,
            mirror=flight.record if flight is not None else None,
        )
        try:
            self._init_metrics(
                workdir, fingerprint, process_index, process_count,
                metrics_port, metrics_host, metrics_interval_s,
            )
        except BaseException:
            # a half-built Telemetry (e.g. --metrics-port already bound)
            # must not leak the event fd, the exporter thread, or the
            # server — the caller only gets the exception, never a handle.
            # The event-fd close rides a finally: a server stop that
            # ALSO fails (LT008 found this gap) must not leak the fd too
            try:
                srv = getattr(self, "_server", None)
                if srv is not None:
                    srv.stop()
            finally:
                self.events.close()
            raise

    def _init_metrics(
        self,
        workdir: str,
        fingerprint: str,
        process_index: int,
        process_count: int,
        metrics_port: int | None,
        metrics_host: str,
        metrics_interval_s: float,
    ) -> None:
        self.registry = MetricsRegistry()
        r = self.registry
        self._tiles_done = r.counter(
            "lt_tiles_done_total", "tiles whose device result completed"
        )
        self._tile_retries = r.counter(
            "lt_tile_retries_total", "tile attempt failures that were retried"
        )
        self._tiles_failed = r.counter(
            "lt_tiles_failed_total", "tiles that exhausted their retry budget"
        )
        self._pixels = r.counter(
            "lt_pixels_total", "real (unpadded) pixels whose tile completed"
        )
        self._bytes_written = r.counter(
            "lt_artifact_bytes_written_total",
            "bytes of tile checkpoint artifacts persisted",
        )
        self._compute_hist = r.histogram(
            "lt_tile_compute_seconds",
            "per-tile dispatch + device-wait wall seconds",
            buckets=DEFAULT_LATENCY_BUCKETS,
        )
        self._record_hist = r.histogram(
            "lt_tile_record_seconds",
            "per-tile artifact + manifest persist seconds",
            buckets=DEFAULT_LATENCY_BUCKETS,
        )
        self._pxs_hist = r.histogram(
            "lt_tile_px_per_s", "per-tile pixel throughput", buckets=_PXS_BUCKETS
        )
        self._pxs_gauge = r.gauge("lt_px_per_s", "last tile's pixel throughput")
        self._no_fit = r.gauge("lt_no_fit_rate", "last written tile's no-fit rate")
        self._feed_backlog = r.gauge(
            "lt_feed_backlog", "fed tiles waiting for dispatch"
        )
        self._write_backlog = r.gauge(
            "lt_write_backlog", "finished tiles waiting in the writer pool"
        )
        self._fetch_backlog = r.gauge(
            "lt_fetch_backlog", "in-flight async device->host fetches"
        )
        self._dev_bytes = r.gauge(
            "lt_device_bytes_in_use", "device allocator live bytes (all local devices)"
        )
        self._dev_peak = r.gauge(
            "lt_device_bytes_peak", "high watermark of lt_device_bytes_in_use"
        )
        # feed-path decode subsystem (io/blockcache): run-scoped counters
        # folded in once per run by Telemetry.feed_cache
        self._fc_hits = r.counter(
            "lt_feed_cache_hits_total", "decoded-block cache hits (feed path)"
        )
        self._fc_misses = r.counter(
            "lt_feed_cache_misses_total", "decoded-block cache misses (feed path)"
        )
        self._fc_evictions = r.counter(
            "lt_feed_cache_evictions_total",
            "decoded blocks evicted by the cache byte budget",
        )
        self._fc_decode_s = r.counter(
            "lt_feed_decode_seconds_total",
            "block-decode wall seconds, summed across decode threads",
        )
        self._fc_ra_blocks = r.counter(
            "lt_feed_readahead_blocks_total",
            "blocks decoded into the cache by readahead hints",
        )
        self._fc_ra_hits = r.counter(
            "lt_feed_readahead_hits_total",
            "readahead-decoded blocks later served to a real read",
        )
        self._fc_bytes = r.gauge(
            "lt_feed_cache_bytes", "decoded-block cache occupancy (bytes)"
        )
        self._fc_corrupt = r.counter(
            "lt_feed_corrupt_dropped_total",
            "corrupt cached blocks invalidated and re-decoded from file",
        )
        # robustness subsystem (runtime/faults + the driver hardening)
        self._faults = r.counter(
            "lt_faults_injected_total",
            "scheduled faults fired by the deterministic injector",
        )
        self._quarantined = r.counter(
            "lt_tiles_quarantined_total",
            "tiles that exhausted retries and were quarantined",
        )
        self._stalls = r.counter(
            "lt_stalls_total", "stall-watchdog aborts (no tile progress)"
        )
        self._stragglers = r.counter(
            "lt_stragglers_total",
            "tiles whose in-flight duration exceeded k x the rolling "
            "median (obs/spans.StragglerDetector)",
        )
        # elastic pod scheduling (runtime/leases): per-acquisition
        # counters advanced by the tile_leased / lease_stolen /
        # tile_speculated emits, plus the run-end lease rollup
        self._lease_acquired = r.counter(
            "lt_lease_acquired_total",
            "tile leases this process won from the shared-manifest queue "
            "(claims + steals + speculative re-leases)",
        )
        self._lease_stolen = r.counter(
            "lt_lease_stolen_total",
            "expired tile leases this process stole from dead/wedged peers",
        )
        self._lease_renewals = r.counter(
            "lt_lease_renewals_total",
            "lease renewal records appended for held in-flight tiles",
        )
        self._spec_tiles = r.counter(
            "lt_speculative_tiles_total",
            "straggler-flagged tiles this process re-leased speculatively",
        )
        self._spec_wins = r.counter(
            "lt_speculative_wins_total",
            "speculative tiles whose first durable done record was this "
            "process's (the straggler's owner lost the race)",
        )
        self._demoted = r.gauge(
            "lt_fetch_demoted",
            "1 once repeated packed-fetch failures demoted the run to the "
            "per-product sync path",
        )
        # device→host fetch subsystem (runtime/fetch): run-scoped counters
        # folded in once per run by Telemetry.fetch
        self._fx_tiles = r.counter(
            "lt_fetch_tiles_total", "tiles whose outputs were fetched to host"
        )
        self._fx_transfers = r.counter(
            "lt_fetch_transfers_total",
            "device->host transfers issued (packed fetch = 1 per tile)",
        )
        self._fx_bytes = r.counter(
            "lt_fetch_bytes_total", "device->host wire bytes fetched"
        )
        self._fx_pack_s = r.counter(
            "lt_fetch_pack_seconds_total",
            "host seconds dispatching the device-side pack program",
        )
        self._fx_wait_s = r.counter(
            "lt_fetch_wait_seconds_total",
            "host seconds blocked waiting for fetched bytes to land",
        )
        self._fx_unpack_s = r.counter(
            "lt_fetch_unpack_seconds_total",
            "host seconds unpacking landed bytes into artifact arrays",
        )
        self._fx_backlog = r.gauge(
            "lt_fetch_backlog_max", "high watermark of in-flight async fetches"
        )
        # host→device upload subsystem (runtime/feed): run-scoped
        # counters folded in once per run by Telemetry.upload
        self._up_tiles = r.counter(
            "lt_upload_tiles_total", "tiles whose fed inputs were uploaded"
        )
        self._up_transfers = r.counter(
            "lt_upload_transfers_total",
            "host->device transfers issued (packed upload = 1 per tile)",
        )
        self._up_bytes = r.counter(
            "lt_upload_bytes_total", "host->device wire bytes uploaded"
        )
        self._up_pack_s = r.counter(
            "lt_upload_pack_seconds_total",
            "host seconds packing fed arrays + issuing device_put",
        )
        self._up_wait_s = r.counter(
            "lt_upload_wait_seconds_total",
            "host seconds blocked waiting for uploaded bytes to land",
        )
        self._up_unpack_s = r.counter(
            "lt_upload_unpack_seconds_total",
            "host seconds dispatching the device-side unpack program",
        )
        self._up_backlog = r.gauge(
            "lt_upload_backlog_max", "high watermark of in-flight async uploads"
        )
        self._up_demoted = r.gauge(
            "lt_upload_demoted",
            "1 once repeated packed-upload failures demoted the run to the "
            "per-array sync dispatch",
        )
        # persistent ingest store (io/blockstore): run-scoped counters
        # folded in once per run by Telemetry.ingest_store
        self._is_hits = r.counter(
            "lt_ingest_store_hits_total",
            "decoded blocks served from the persistent store (decode skipped)",
        )
        self._is_misses = r.counter(
            "lt_ingest_store_misses_total",
            "store lookups that fell through to a TIFF decode",
        )
        self._is_put_blocks = r.counter(
            "lt_ingest_store_put_blocks_total",
            "decoded blocks persisted into the store",
        )
        self._is_put_bytes = r.counter(
            "lt_ingest_store_put_bytes_total",
            "bytes of decoded blocks persisted into the store",
        )
        self._is_stale = r.counter(
            "lt_ingest_store_stale_dropped_total",
            "stale-generation entries dropped (input file rewritten)",
        )
        self._is_corrupt = r.counter(
            "lt_ingest_store_corrupt_dropped_total",
            "corrupt store entries/segments dropped and re-decoded",
        )
        self._is_bytes = r.gauge(
            "lt_ingest_store_bytes", "persistent store occupancy (bytes)"
        )
        # autotuned execution profiles (land_trendr_tpu/tune): probe
        # counts advanced per tune_probe emit, store verdicts per
        # tune_profile emit
        self._tn_probes = r.counter(
            "lt_tune_probes_total",
            "calibration probe reps run by the autotuner",
        )
        self._tn_failures = r.counter(
            "lt_tune_probe_failures_total",
            "knob-group probes that failed and were skipped (defaults kept)",
        )
        self._tn_store_hits = r.counter(
            "lt_tune_store_hits_total",
            "tuning-store profile reloads (zero probes run)",
        )
        self._tn_store_misses = r.counter(
            "lt_tune_store_misses_total",
            "tuning-store key misses (probed or fell back to defaults)",
        )
        self._tn_age = r.gauge(
            "lt_tune_profile_age_seconds",
            "age of the resolved tuning profile (0 = freshly probed)",
        )
        if fingerprint:
            r.gauge(
                "lt_run_info",
                "constant 1; labels carry run identity",
                labels={"fingerprint": fingerprint},
            ).set(1)

        # bind the port BEFORE starting the exporter thread: a bind
        # failure is the common construction error, and nothing should be
        # running yet when it raises
        self._server = (
            MetricsHTTPServer(self.registry, metrics_port, host=metrics_host)
            if metrics_port is not None
            else None
        )
        try:
            self._exporter = PromFileExporter(
                self.registry,
                metrics_path(workdir, process_index, process_count),
                interval_s=metrics_interval_s,
            ).start()
        except BaseException:
            # exporter construction/first-write failing after the port
            # bound: release the server HERE (locality — the __init__
            # guard then only owns the event fd) and mark it released
            if self._server is not None:
                self._server.stop()
                self._server = None
            raise
        if self._publish_dir:
            from land_trendr_tpu.obs.publish import TelemetryPublisher

            try:
                self._publisher = TelemetryPublisher(
                    self._publish_dir,
                    self.registry,
                    probes=self._publish_probes,
                    interval_s=self._publish_interval_s,
                    kind="run",
                ).start()
            except BaseException:
                # publisher construction failing (unwritable telemetry
                # dir) after the exporter/server exist: release them
                # HERE (locality, like the exporter guard) so __init__'s
                # guard only owns the event fd; telescoped so an
                # exporter-stop failure cannot skip the server release
                try:
                    self._exporter.stop()
                finally:
                    if self._server is not None:
                        self._server.stop()
                        self._server = None
                raise

    # -- paths the run summary reports -------------------------------------
    @property
    def events_file(self) -> str:
        return self.events.path

    @property
    def metrics_file(self) -> str:
        return self._exporter.path

    @property
    def metrics_port(self) -> int | None:
        return self._server.port if self._server is not None else None

    @property
    def publish_file(self) -> str | None:
        """The fleet snapshot this process refreshes (None = publish off)."""
        return self._publisher.path if self._publisher is not None else None

    # -- driver hooks ------------------------------------------------------
    def run_start(self, **fields: Any) -> dict:
        """Open the run scope; returns the emitted record — the caller
        reads the stamped ``run_id`` / clock-anchor pair back (the
        driver mirrors them into the manifest for pod-trace assembly)."""
        return self.events.run_start(**fields)

    def tile_start(self, tile_id: int, attempt: int = 1) -> None:
        self.events.emit("tile_start", tile_id=tile_id, attempt=attempt)

    def tile_done(
        self,
        tile_id: int,
        px: int,
        compute_s: float,
        feed_backlog: int,
        write_backlog: int,
        device_bytes_in_use: int | None = None,
        fetch_backlog: int | None = None,
    ) -> None:
        pxs = px / compute_s if compute_s > 0 else 0.0
        fields: dict[str, Any] = {}
        if device_bytes_in_use is not None:
            self._dev_bytes.set(device_bytes_in_use)
            self._dev_peak.set_max(device_bytes_in_use)
            fields["device_bytes_in_use"] = device_bytes_in_use
        if fetch_backlog is not None:
            self._fetch_backlog.set(fetch_backlog)
            fields["fetch_backlog"] = fetch_backlog
        self.events.emit(
            "tile_done",
            tile_id=tile_id,
            px=px,
            compute_s=round(compute_s, 6),
            px_per_s=round(pxs, 1),
            feed_backlog=feed_backlog,
            write_backlog=write_backlog,
            **fields,
        )
        self._tiles_done.inc()
        self._pixels.inc(px)
        self._compute_hist.observe(compute_s)
        self._pxs_hist.observe(pxs)
        self._pxs_gauge.set(pxs)
        self._feed_backlog.set(feed_backlog)
        self._write_backlog.set(write_backlog)

    def tile_retry(self, tile_id: int, attempt: int, error: BaseException | str) -> None:
        self.events.emit(
            "tile_retry", tile_id=tile_id, attempt=attempt, error=str(error)
        )
        self._tile_retries.inc()

    def tile_failed(self, tile_id: int, attempts: int, error: BaseException | str) -> None:
        self.events.emit(
            "tile_failed", tile_id=tile_id, attempts=attempts, error=str(error)
        )
        self._tiles_failed.inc()

    def tile_quarantined(
        self, tile_id: int, attempts: int, error: BaseException | str
    ) -> None:
        """The tile exhausted its retries under quarantine mode: the run
        goes on without it (resume re-attempts it)."""
        self.events.emit(
            "tile_quarantined",
            tile_id=tile_id,
            attempts=attempts,
            error=str(error),
        )
        self._quarantined.inc()

    def span(
        self,
        name: str,
        tile_id: int,
        start: float,
        end: float,
        attempt: "int | None" = None,
    ) -> None:
        """One per-tile stage span (``start``/``end`` on the monotonic
        clock — the same clock as ``t_mono``, so consumers anchor them
        through the scope's ``run_start`` anchor pair).  Emitted at span
        END from the driver thread, so spans always precede their
        scope's ``run_done``.  Events only — span volume would swamp a
        counter registry; the per-stage instruments stay the run-scoped
        ``lt_stage_seconds`` gauges."""
        self.events.emit(
            "span",
            name=name,
            tile_id=tile_id,
            start=round(start, 6),
            end=round(end, 6),
            **({"attempt": attempt} if attempt is not None else {}),
        )

    def tile_straggler(
        self,
        tile_id: int,
        duration_s: float,
        threshold_s: float,
        median_s: float,
        in_flight: bool = False,
        attempt: "int | None" = None,
    ) -> None:
        """This tile's in-flight duration exceeded the straggler
        threshold (``k x`` rolling median — obs/spans).  May fire from
        the flight-sampler thread (``in_flight=True``) while the driver
        is blocked inside the straggler's own wait."""
        self.events.emit(
            "tile_straggler",
            tile_id=tile_id,
            duration_s=round(duration_s, 6),
            threshold_s=round(threshold_s, 6),
            median_s=round(median_s, 6),
            in_flight=in_flight,
            **({"attempt": attempt} if attempt is not None else {}),
        )
        self._stragglers.inc()

    def tile_leased(
        self, tile_id: int, gen: int, owner: "str | None" = None
    ) -> None:
        """This process claimed a never-leased (or released) tile from
        the shared-manifest lease queue (runtime/leases)."""
        self.events.emit(
            "tile_leased",
            tile_id=tile_id,
            gen=gen,
            **({"owner": owner} if owner is not None else {}),
        )
        self._lease_acquired.inc()

    def lease_stolen(
        self,
        tile_id: int,
        gen: int,
        owner: "str | None" = None,
        from_owner: "str | None" = None,
    ) -> None:
        """This process stole a tile whose lease expired (dead or wedged
        peer); ``gen`` is the successor generation the steal claimed."""
        self.events.emit(
            "lease_stolen",
            tile_id=tile_id,
            gen=gen,
            **({"owner": owner} if owner is not None else {}),
            **({"from_owner": from_owner} if from_owner is not None else {}),
        )
        self._lease_acquired.inc()
        self._lease_stolen.inc()

    def tile_speculated(
        self,
        tile_id: int,
        gen: int,
        owner: "str | None" = None,
        from_owner: "str | None" = None,
    ) -> None:
        """This process speculatively re-leased a straggler-flagged tile
        still in flight on its owner (first durable write wins)."""
        self.events.emit(
            "tile_speculated",
            tile_id=tile_id,
            gen=gen,
            **({"owner": owner} if owner is not None else {}),
            **({"from_owner": from_owner} if from_owner is not None else {}),
        )
        self._lease_acquired.inc()
        self._spec_tiles.inc()

    def lease_summary(self, stats: Mapping[str, Any]) -> None:
        """Fold one run's terminal lease-queue counters into the metrics
        registry (``stats`` is :meth:`runtime.leases.LeaseQueue.stats`).
        Metrics only — the per-acquisition events above already carry
        the stream's story, and ``run_done`` carries the rollup fields."""
        self._lease_renewals.inc(int(stats.get("renewals", 0)))
        self._spec_wins.inc(int(stats.get("spec_wins", 0)))

    def fault_injected(self, seam: str, index: int, error: str) -> None:
        """One scheduled fault fired (the runtime.faults observer hook)."""
        self.events.emit("fault_injected", seam=seam, index=index, error=error)
        self._faults.inc()

    def stall(self, idle_s: float, timeout_s: float) -> None:
        """The stall watchdog is aborting: no tile progress for idle_s.
        Emitted from the watchdog thread, BEFORE the abort unwinds —
        a hung run's stream must say why it died even if the unwind
        itself never completes."""
        self.events.emit(
            "stall", idle_s=round(idle_s, 3), timeout_s=timeout_s
        )
        self._stalls.inc()

    def fetch_demoted(self, failures: int) -> None:
        """Packed fetch demoted to the per-product sync path for the rest
        of the run after repeated fetch failures."""
        self.events.emit("fetch_demoted", failures=failures)
        self._demoted.set(1)

    def write_done(
        self, tile_id: int, nbytes: int, record_s: float, meta: Mapping[str, Any]
    ) -> None:
        """Called by ``TileManifest.record`` once a tile is durable."""
        fields: dict[str, Any] = {}
        # only no_fit_rate rides along from the manifest meta: its
        # px_per_s is computed over PADDED tile pixels, which would
        # contradict tile_done's real-pixel px_per_s for the same tile —
        # tile_done is the one throughput source of truth in the stream
        if "no_fit_rate" in meta:
            fields["no_fit_rate"] = meta["no_fit_rate"]
        self.events.emit(
            "write_done",
            tile_id=tile_id,
            bytes=nbytes,
            record_s=round(record_s, 6),
            **fields,
        )
        self._bytes_written.inc(nbytes)
        self._record_hist.observe(record_s)
        if "no_fit_rate" in meta:
            self._no_fit.set(float(meta["no_fit_rate"]))

    def feed_cache(self, stats: Mapping[str, Any]) -> None:
        """Fold one run's feed-decode subsystem counters into the stream.

        ``stats`` is a :func:`land_trendr_tpu.io.blockcache.stats_delta`
        dict (run-scoped counter deltas + cache occupancy gauges); the
        driver calls this once, right before ``run_done``.  Emits the
        ``feed_cache`` event and advances the ``lt_feed_*`` instruments.
        """
        fields = {
            k: stats[k]
            for k in (
                "hits", "misses", "evictions", "decode_s", "inserted_bytes",
                "readahead_blocks", "readahead_hits", "readahead_dropped",
                "cache_bytes", "budget_bytes", "corrupt_dropped",
            )
            if k in stats
        }
        fields["decode_s"] = round(float(fields.get("decode_s", 0.0)), 6)
        for req in ("hits", "misses", "evictions"):
            fields.setdefault(req, 0)
        self.events.emit("feed_cache", **fields)
        self._fc_hits.inc(fields["hits"])
        self._fc_misses.inc(fields["misses"])
        self._fc_evictions.inc(fields["evictions"])
        self._fc_decode_s.inc(fields["decode_s"])
        self._fc_ra_blocks.inc(fields.get("readahead_blocks", 0))
        self._fc_ra_hits.inc(fields.get("readahead_hits", 0))
        self._fc_corrupt.inc(fields.get("corrupt_dropped", 0))
        if "cache_bytes" in fields:
            self._fc_bytes.set(fields["cache_bytes"])

    def fetch(self, stats: Mapping[str, Any]) -> None:
        """Fold one run's device→host fetch counters into the stream.

        ``stats`` is a :meth:`land_trendr_tpu.runtime.fetch.TileFetcher.
        summary` dict; the driver calls this once, right before
        ``run_done`` (success and abort paths alike).  Emits the
        ``fetch`` event and advances the ``lt_fetch_*`` instruments.
        """
        fields: dict[str, Any] = {
            "tiles": int(stats.get("tiles", 0)),
            "transfers": int(stats.get("transfers", 0)),
            "bytes": int(stats.get("bytes", 0)),
            "pack_s": round(float(stats.get("pack_s", 0.0)), 6),
            "wait_s": round(float(stats.get("wait_s", 0.0)), 6),
            "unpack_s": round(float(stats.get("unpack_s", 0.0)), 6),
        }
        if "backlog_max" in stats:
            fields["backlog_max"] = int(stats["backlog_max"])
        if "packed" in stats:
            fields["packed"] = bool(stats["packed"])
        if "demoted" in stats:
            fields["demoted"] = bool(stats["demoted"])
        self.events.emit("fetch", **fields)
        self._fx_tiles.inc(fields["tiles"])
        self._fx_transfers.inc(fields["transfers"])
        self._fx_bytes.inc(fields["bytes"])
        self._fx_pack_s.inc(fields["pack_s"])
        self._fx_wait_s.inc(fields["wait_s"])
        self._fx_unpack_s.inc(fields["unpack_s"])
        if "backlog_max" in fields:
            self._fx_backlog.set_max(fields["backlog_max"])

    def upload_demoted(self, failures: int) -> None:
        """Packed upload demoted to the per-array sync dispatch for the
        rest of the run after repeated upload failures."""
        self.events.emit("upload_demoted", failures=failures)
        self._up_demoted.set(1)

    def upload(self, stats: Mapping[str, Any]) -> None:
        """Fold one run's host→device upload counters into the stream.

        ``stats`` is a :meth:`land_trendr_tpu.runtime.feed.TileUploader.
        summary` dict; the driver calls this once, right before
        ``run_done`` (success and abort paths alike).  Emits the
        ``upload`` event and advances the ``lt_upload_*`` instruments.
        """
        fields: dict[str, Any] = {
            "tiles": int(stats.get("tiles", 0)),
            "transfers": int(stats.get("transfers", 0)),
            "bytes": int(stats.get("bytes", 0)),
            "pack_s": round(float(stats.get("pack_s", 0.0)), 6),
            "wait_s": round(float(stats.get("wait_s", 0.0)), 6),
            "unpack_s": round(float(stats.get("unpack_s", 0.0)), 6),
        }
        if "backlog_max" in stats:
            fields["backlog_max"] = int(stats["backlog_max"])
        if "packed" in stats:
            fields["packed"] = bool(stats["packed"])
        if "demoted" in stats:
            fields["demoted"] = bool(stats["demoted"])
        self.events.emit("upload", **fields)
        self._up_tiles.inc(fields["tiles"])
        self._up_transfers.inc(fields["transfers"])
        self._up_bytes.inc(fields["bytes"])
        self._up_pack_s.inc(fields["pack_s"])
        self._up_wait_s.inc(fields["wait_s"])
        self._up_unpack_s.inc(fields["unpack_s"])
        if "backlog_max" in fields:
            self._up_backlog.set_max(fields["backlog_max"])

    def ingest_store(self, stats: Mapping[str, Any]) -> None:
        """Fold one run's persistent ingest-store counters into the stream.

        ``stats`` is a :meth:`land_trendr_tpu.io.blockstore.BlockStore.
        stats_delta` dict; the driver calls this once per store-enabled
        run, right before ``run_done``.  Emits the ``ingest_store``
        event and advances the ``lt_ingest_*`` instruments.
        """
        fields: dict[str, Any] = {
            "hits": int(stats.get("hits", 0)),
            "misses": int(stats.get("misses", 0)),
            "put_blocks": int(stats.get("put_blocks", 0)),
            "put_bytes": int(stats.get("put_bytes", 0)),
        }
        for opt in (
            "stale_dropped", "corrupt_dropped", "evicted_segments",
            "bytes", "budget_bytes", "segments",
        ):
            if opt in stats:
                fields[opt] = int(stats[opt])
        self.events.emit("ingest_store", **fields)
        self._is_hits.inc(fields["hits"])
        self._is_misses.inc(fields["misses"])
        self._is_put_blocks.inc(fields["put_blocks"])
        self._is_put_bytes.inc(fields["put_bytes"])
        self._is_stale.inc(fields.get("stale_dropped", 0))
        self._is_corrupt.inc(fields.get("corrupt_dropped", 0))
        if "bytes" in fields:
            self._is_bytes.set(fields["bytes"])

    def tune_probe(
        self,
        group: str,
        ok: bool,
        probes: int,
        wall_s: float,
        speedup: "float | None" = None,
        error: "str | None" = None,
        knobs: "dict | None" = None,
    ) -> None:
        """One autotuner knob-group probe verdict (tune/autotune).

        ``ok=False`` means the group's probe failed — the tune.probe
        fault seam or a real error — and its knobs fell back to
        defaults; the tuner and any run behind it live on.
        """
        self.events.emit(
            "tune_probe",
            group=group,
            ok=bool(ok),
            probes=int(probes),
            wall_s=round(float(wall_s), 6),
            **({"speedup": round(float(speedup), 3)} if speedup is not None else {}),
            **({"error": error} if error is not None else {}),
            **({"knobs": dict(knobs)} if knobs is not None else {}),
        )
        self._tn_probes.inc(int(probes))
        if not ok:
            self._tn_failures.inc()

    def tune_profile(
        self,
        key: str,
        source: str,
        probes: int,
        age_s: "float | None" = None,
        knobs: "dict | None" = None,
        groups: "int | None" = None,
    ) -> None:
        """One tuning-profile verdict: reloaded from the store (zero
        probes), freshly probed, or hardcoded defaults (no profile).
        Emitted by ``lt tune`` and by every Run whose config resolved
        ``"auto"`` knobs."""
        self.events.emit(
            "tune_profile",
            key=key,
            source=source,
            probes=int(probes),
            **({"age_s": round(float(age_s), 3)} if age_s is not None else {}),
            **({"knobs": dict(knobs)} if knobs is not None else {}),
            **({"groups": int(groups)} if groups is not None else {}),
        )
        if source == "store":
            self._tn_store_hits.inc()
        else:
            self._tn_store_misses.inc()
        if age_s is not None:
            self._tn_age.set(float(age_s))

    def program_cache(self, stats: Mapping[str, Any]) -> None:
        """Fold one run's warm-program-cache verdict into the stream.

        ``stats`` is the driver's per-run accounting over the serve
        layer's :class:`~land_trendr_tpu.serve.programs.ProgramCache`
        (one hit or one miss per run scope, plus the compile seconds a
        miss paid); emitted right before ``run_done`` like the other
        subsystem rollups.  The ``lt_serve_*`` warm-ratio instruments
        live in the SERVER's registry, not here — a single run only
        knows its own verdict.
        """
        self.events.emit(
            "program_cache",
            hits=int(stats.get("hits", 0)),
            misses=int(stats.get("misses", 0)),
            compile_s=round(float(stats.get("compile_s", 0.0)), 6),
            **({"keys": int(stats["keys"])} if "keys" in stats else {}),
        )

    def run_done(
        self,
        status: str,
        tiles_done: int,
        pixels: int,
        wall_s: float,
        px_per_s: float,
        fit_rate: float,
        stage_s: Mapping[str, float] | None = None,
        tiles_quarantined: int | None = None,
        tiles_stolen: int | None = None,
        tiles_speculated: int | None = None,
    ) -> None:
        self.events.emit(
            "run_done",
            status=status,
            tiles_done=tiles_done,
            pixels=pixels,
            wall_s=wall_s,
            px_per_s=px_per_s,
            fit_rate=fit_rate,
            **({"stage_s": dict(stage_s)} if stage_s else {}),
            **(
                {"tiles_quarantined": tiles_quarantined}
                if tiles_quarantined
                else {}
            ),
            # lease runs only (None = static split; 0 is a real value on
            # an elastic run that stole/speculated nothing)
            **(
                {"tiles_stolen": tiles_stolen}
                if tiles_stolen is not None
                else {}
            ),
            **(
                {"tiles_speculated": tiles_speculated}
                if tiles_speculated is not None
                else {}
            ),
        )
        for name, secs in (stage_s or {}).items():
            # "feed_s" -> stage="feed"; totals only meaningful at run end
            self.registry.gauge(
                "lt_stage_seconds",
                "accumulated host seconds per driver stage",
                labels={"stage": name.removesuffix("_s")},
            ).set(secs)

    def load_phase(
        self,
        phase: str,
        mode: str,
        offered_qps: "float | None" = None,
        requests: "int | None" = None,
        workers: "int | None" = None,
        duration_s: "float | None" = None,
        seed: "int | None" = None,
    ) -> None:
        """One load-rig phase boundary (``start``/``done``/a schedule
        segment).  ``offered_qps`` only exists for open-loop phases — a
        closed loop has no offered rate, its arrival rate IS the
        completion rate."""
        self.events.emit(
            "load_phase",
            phase=phase,
            mode=mode,
            **(
                {"offered_qps": round(float(offered_qps), 6)}
                if offered_qps is not None
                else {}
            ),
            **({"requests": int(requests)} if requests is not None else {}),
            **({"workers": int(workers)} if workers is not None else {}),
            **(
                {"duration_s": round(float(duration_s), 6)}
                if duration_s is not None
                else {}
            ),
            **({"seed": int(seed)} if seed is not None else {}),
        )

    def sweep_point(
        self,
        replicas: int,
        offered_qps: float,
        achieved_qps: float,
        p50_s: float,
        p99_s: float,
        goodput_qps: float,
        done: int,
        failed: int,
        rejected: int,
        knee: "bool | None" = None,
        knee_blame: "str | None" = None,
        window_s: "float | None" = None,
        assembled: "int | None" = None,
    ) -> None:
        """One point of a capacity scaling curve: a (replica count,
        offered rate) cell measured by the load rig and assembled
        through the request-trace store.  ``knee``/``knee_blame`` are
        stamped by the analyzer on the point where the latency curve
        bends, naming the dominant blame component there."""
        self.events.emit(
            "sweep_point",
            replicas=int(replicas),
            offered_qps=round(float(offered_qps), 6),
            achieved_qps=round(float(achieved_qps), 6),
            p50_s=round(float(p50_s), 6),
            p99_s=round(float(p99_s), 6),
            goodput_qps=round(float(goodput_qps), 6),
            done=int(done),
            failed=int(failed),
            rejected=int(rejected),
            **({"knee": bool(knee)} if knee is not None else {}),
            **({"knee_blame": knee_blame} if knee_blame is not None else {}),
            **(
                {"window_s": round(float(window_s), 6)}
                if window_s is not None
                else {}
            ),
            **({"assembled": int(assembled)} if assembled is not None else {}),
        )

    def sim_replay(
        self,
        decisions: int,
        matched: int,
        match: bool,
        speedup_x: float,
        recorded_span_s: "float | None" = None,
        replay_wall_s: "float | None" = None,
        mismatch_seq: "int | None" = None,
    ) -> None:
        """One offline-replay verdict: a recorded dispatcher/autoscaler
        decision log re-driven through the same pure functions.
        ``match`` means every recorded decision was reproduced
        byte-identically; ``mismatch_seq`` pins the first divergence."""
        self.events.emit(
            "sim_replay",
            decisions=int(decisions),
            matched=int(matched),
            match=bool(match),
            speedup_x=round(float(speedup_x), 3),
            **(
                {"recorded_span_s": round(float(recorded_span_s), 6)}
                if recorded_span_s is not None
                else {}
            ),
            **(
                {"replay_wall_s": round(float(replay_wall_s), 6)}
                if replay_wall_s is not None
                else {}
            ),
            **(
                {"mismatch_seq": int(mismatch_seq)}
                if mismatch_seq is not None
                else {}
            ),
        )

    def close(self) -> None:
        """Flush the final exposition, stop the exporters, close the log.

        Idempotent and exception-tolerant in the ways that matter on the
        driver's abort path: the event log closes even when the final
        metrics flush raises.
        """
        try:
            # the publisher stops FIRST (its final snapshot reads the
            # registry, which outlives it; a publisher-stop failure must
            # not skip the exporter/server/event-fd releases below)
            if self._publisher is not None:
                self._publisher.stop()
                self._publisher = None
        finally:
            try:
                if self._server is not None:
                    self._server.stop()
                    self._server = None
            finally:
                try:
                    self._exporter.stop()
                finally:
                    self.events.close()
