"""Fleet telemetry history: a bounded on-disk time-series ring.

The **history** quarter of the fleet telemetry plane: the aggregate
layer produces an instantaneous pod view, but ``rate()``, queue-wait
trends and SLO burn rates need values **over a window** — so the fleet
loop appends one flattened :func:`~land_trendr_tpu.obs.aggregate.
pod_sample` per beat into this ring, and the alert engine / ``lt_fleet``
read windows back out.

Storage follows the blockstore discipline, scaled down to JSONL:

* **append-only segments** — samples append as single ``os.write``
  JSONL lines (atomic ``O_APPEND``, the event-log contract) to one live
  ``*.open.jsonl`` file;
* **tmp-free rename commit** — at ``samples_per_segment`` the live file
  is atomically renamed to its committed ``hist-*.jsonl`` name (the
  rename IS the commit point; an ``.open`` file is by definition the
  possibly-torn tail of a live or crashed writer);
* **whole-oldest-segment eviction** — when committed bytes exceed the
  budget the oldest segment is unlinked whole, never rewritten;
* **reopen-after-crash GC** — opening a ring adopts a STALE ``.open``
  leftover (a crashed writer's tail: parseable lines are committed, a
  torn final line is dropped and counted) and removes stale tmps, while
  a FRESH ``.open`` from another live pid in a shared dir is left
  alone, exactly like the blockstore's orphan rules.

Single-owner by contract: one fleet loop owns :meth:`append` /
:meth:`close` (the serve loop stops its thread before closing), so the
hot path carries no lock; readers — other processes included — only
ever see committed segments plus an append-only live file, both safe to
read concurrently.  The ``history.append`` fault seam fires at the top
of :meth:`append` (via the same registered-plan hook as
``obs.publish``), and callers treat a raised append as one lost sample,
never a corrupted ring.  Stdlib-only, jax-free.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any

from land_trendr_tpu.obs.publish import fault_check

__all__ = ["HistoryRing", "counter_rate", "latest_value"]

#: an ``.open`` segment untouched this long belongs to a dead writer
#: (live loops beat every few seconds) — adopt it at open
_STALE_OPEN_S = 60.0


class HistoryRing:
    """Bounded on-disk ring of JSON samples (see the module docstring)."""

    def __init__(
        self,
        directory: str,
        budget_bytes: int = 4 << 20,
        samples_per_segment: int = 256,
    ) -> None:
        if budget_bytes < 1:
            raise ValueError(f"budget_bytes={budget_bytes} must be >= 1")
        if samples_per_segment < 1:
            raise ValueError(
                f"samples_per_segment={samples_per_segment} must be >= 1"
            )
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.budget_bytes = int(budget_bytes)
        self.samples_per_segment = int(samples_per_segment)
        self.adopted_segments = 0
        self.dropped_torn_lines = 0
        self._gc_open()
        self._fd: "int | None" = None
        self._open_path: "str | None" = None
        self._open_count = 0
        self._closed = False

    # -- open-time GC ------------------------------------------------------
    def _gc_open(self) -> None:
        now = time.time()
        for tmp in glob.glob(os.path.join(self.directory, "*.tmp")):
            try:
                if now - os.path.getmtime(tmp) > _STALE_OPEN_S:
                    os.unlink(tmp)
            except OSError:
                pass
        for left in glob.glob(os.path.join(self.directory, "*.open.jsonl")):
            try:
                age = now - os.path.getmtime(left)
            except OSError:
                continue
            if age <= _STALE_OPEN_S:
                continue  # a live sibling's tail in a shared dir: not ours
            self._adopt(left)

    def _adopt(self, open_path: str) -> None:
        """Commit a crashed writer's ``.open`` tail: keep every parseable
        line, drop (and count) a torn final line, rename to the committed
        name — or remove an empty/unreadable leftover."""
        good: list = []
        torn = 0
        try:
            with open(open_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        json.loads(line)
                        good.append(line)
                    except json.JSONDecodeError:
                        torn += 1
        except OSError:
            return
        self.dropped_torn_lines += torn
        try:
            if not good:
                os.unlink(open_path)
                return
            if torn:
                # rewrite without the torn tail, atomically (tmp + rename
                # — the commit protocol, even for the salvage path)
                tmp = f"{open_path}.{os.getpid()}.tmp"
                with open(tmp, "w") as f:
                    f.write("\n".join(good) + "\n")
                os.replace(tmp, open_path)
            committed = open_path[: -len(".open.jsonl")] + ".jsonl"
            os.replace(open_path, committed)
            self.adopted_segments += 1
        except OSError:
            pass  # best-effort salvage: a failed adopt stays an orphan

    # -- the write path ----------------------------------------------------
    def append(self, sample: "dict[str, Any]") -> None:
        """Append one sample (single atomic ``O_APPEND`` write).

        Raises on an armed ``history.append`` fault or real I/O failure
        — the caller drops THAT sample; the ring itself stays
        consistent (committed segments are immutable, and a torn live
        tail is exactly what the reopen GC repairs).
        """
        if self._closed:
            raise ValueError(f"HistoryRing {self.directory} is closed")
        fault_check("history.append")
        line = (json.dumps(sample, separators=(",", ":"), default=str) + "\n").encode()
        if self._fd is None:
            self._open_path = os.path.join(
                self.directory, f"hist-{time.time_ns()}-{os.getpid()}.open.jsonl"
            )
            self._fd = os.open(
                self._open_path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644
            )
            self._open_count = 0
        n = os.write(self._fd, line)
        if n != len(line):
            raise OSError(
                f"short write to {self._open_path}: {n}/{len(line)} bytes"
            )
        self._open_count += 1
        if self._open_count >= self.samples_per_segment:
            self._commit()

    def _commit(self) -> None:
        """Rename the live segment to its committed name (the commit
        point) and evict whole oldest segments past the byte budget."""
        if self._fd is None:
            return
        os.close(self._fd)
        self._fd = None
        committed = self._open_path[: -len(".open.jsonl")] + ".jsonl"
        os.replace(self._open_path, committed)
        self._open_path = None
        self._open_count = 0
        self._evict()

    def _evict(self) -> None:
        segs = self.segments()
        sizes = []
        for p in segs:
            try:
                sizes.append((p, os.path.getsize(p)))
            except OSError:
                pass
        total = sum(s for _, s in sizes)
        # never evict the newest segment: a budget smaller than one
        # segment must not empty the ring entirely
        for p, s in sizes[:-1]:
            if total <= self.budget_bytes:
                break
            try:
                os.unlink(p)
                total -= s
            except OSError:
                pass

    # -- the read path -----------------------------------------------------
    def segments(self) -> list:
        """Committed segment paths, oldest first (the ``hist-<ns>-<pid>``
        naming sorts chronologically)."""
        return sorted(
            p
            for p in glob.glob(os.path.join(self.directory, "hist-*.jsonl"))
            if not p.endswith(".open.jsonl")
        )

    def read(self, newer_than: "float | None" = None) -> "tuple[list, int]":
        """``(samples, malformed)`` across committed segments plus the
        live tail, oldest first; malformed lines (a torn live tail, bit
        rot) are counted, never fatal.  ``newer_than`` filters on each
        sample's own ``t`` stamp."""
        paths = self.segments()
        live = sorted(glob.glob(os.path.join(self.directory, "*.open.jsonl")))
        samples: list = []
        malformed = 0
        for p in [*paths, *live]:
            try:
                with open(p) as f:
                    lines = f.read().splitlines()
            except OSError:
                continue  # evicted between glob and read
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    malformed += 1
                    continue
                if not isinstance(rec, dict):
                    malformed += 1
                    continue
                t = rec.get("t")
                if newer_than is not None and (
                    not isinstance(t, (int, float)) or t < newer_than
                ):
                    continue
                samples.append(rec)
        samples.sort(key=lambda r: r.get("t") or 0.0)
        return samples, malformed

    def close(self) -> None:
        """Commit the live tail (even short — reopen must see it) and
        release the fd.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._fd is not None:
            if self._open_count:
                self._commit()
            else:
                os.close(self._fd)
                self._fd = None
                try:
                    os.unlink(self._open_path)
                except OSError:
                    pass
                self._open_path = None


def _metric_value(sample: dict, key: str) -> "float | None":
    """A sample's scalar: top-level health fields (``hosts``,
    ``stale_hosts``, ...) or a flattened metric key."""
    v = sample.get(key)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    m = sample.get("metrics")
    if isinstance(m, dict):
        v = m.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    return None


def latest_value(samples: list, key: str) -> "float | None":
    """The most recent sample's value for ``key`` (None when the window
    never carried it)."""
    for sample in reversed(samples):
        v = _metric_value(sample, key)
        if v is not None:
            return v
    return None


def counter_rate(
    samples: list, key: str, window_s: float, now: "float | None" = None
) -> "float | None":
    """Reset-aware counter rate (per second) over the trailing window.

    A counter that DROPS between samples is a process restart, not a
    negative increase: the post-reset value counts as the increase from
    zero (the Prometheus ``rate()`` convention), so the result can
    never go negative — the aggregate-must-not-go-negative contract
    under restart churn.  Returns ``None`` with fewer than two samples
    in the window (a rate needs an interval).
    """
    if now is None:
        now = samples[-1].get("t", 0.0) if samples else 0.0
    window = [
        s for s in samples
        if isinstance(s.get("t"), (int, float)) and s["t"] >= now - window_s
    ]
    prev_v = prev_t = first_t = None
    increase = 0.0
    points = 0
    for s in window:
        v = _metric_value(s, key)
        if v is None:
            continue
        points += 1
        if first_t is None:
            first_t = s["t"]
        if prev_v is not None:
            increase += (v - prev_v) if v >= prev_v else v
        prev_v, prev_t = v, s["t"]
    if points < 2 or prev_t == first_t:
        return None
    return max(0.0, increase) / (prev_t - first_t)
