"""Flight recorder: a bounded in-memory ring over the live event stream.

``events.jsonl`` answers "what happened" after the fact; ``metrics.prom``
answers "what is the counter now".  Neither answers the question an
operator asks a live, possibly-wedged gigapixel run: *what are you doing
right now, and how did the last 60 seconds look?*  This module is that
answer — the in-process half of the ``/debug`` surface:

* :class:`FlightRecorder` — a bounded ring that **mirrors every
  telemetry emit** (an :class:`~land_trendr_tpu.obs.events.EventLog`
  ``mirror`` hook, so schema v1 stays the single vocabulary; nothing is
  re-modelled here) and is dumpable at any moment as a schema-valid
  ``events.jsonl`` slice: the latest ``run_start`` is kept sticky
  outside the ring, so a dump always opens a valid run scope even after
  the ring has evicted it.
* :class:`ResourceSampler` — a daemon thread emitting periodic
  ``flight_sample`` events (RSS, open fds, thread count, plus whatever
  gauges the host's ``probes`` callable contributes: queue depths,
  backlogs, cache/store occupancy, HBM watermark) through the normal
  event log, so the samples land in the stream, the ring, and the
  ``obs_report --trace`` counter tracks alike.
* :func:`thread_stacks` — all-thread tracebacks via
  ``sys._current_frames`` — the "is the dispatcher wedged behind a
  writer join?" question, servable over HTTP even while the main loop
  is stuck in a lock.

Lock discipline: the recorder's one lock guards only the ring deque and
two scalars — no I/O, no emit, no allocation beyond a list copy ever
happens under it, so mirroring an emit costs an append.  Everything
here is stdlib-only and jax-free, like the rest of :mod:`~land_trendr_tpu.obs`.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import traceback
from typing import Any, Callable

__all__ = [
    "FlightRecorder",
    "ResourceSampler",
    "flight_path",
    "thread_stacks",
]


def flight_path(workdir: str, process_index: int = 0, process_count: int = 1) -> str:
    """Canonical flight-dump path under a run's workdir (mirrors the
    ``events_path`` per-process naming; never matched by
    ``discover_event_files``'s ``events*.jsonl`` globs, so a dump can
    live beside the stream without polluting workdir discovery)."""
    if process_count <= 1:
        return os.path.join(workdir, "flight.jsonl")
    return os.path.join(workdir, f"flight.p{process_index}.jsonl")


class FlightRecorder:
    """Bounded ring of the most recent telemetry events.

    Wire it as the :class:`~land_trendr_tpu.obs.events.EventLog`
    ``mirror`` hook: every emitted record (timestamps and common fields
    already stamped) lands here too.  The ring holds the last
    ``capacity`` records; the latest ``run_start`` is additionally kept
    sticky so :meth:`dump` always produces a stream that opens with a
    run scope — the property that makes a dump pass
    ``tools/check_events_schema.py`` unmodified.
    """

    def __init__(self, capacity: int = 2048) -> None:
        if capacity < 2:
            raise ValueError(
                f"capacity={capacity} must be >= 2 (a useful ring holds at "
                "least a run_start and one event)"
            )
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._run_start: "dict | None" = None
        self._total = 0

    # -- the mirror hook ---------------------------------------------------
    def record(self, rec: dict) -> None:
        """Append one emitted record (called from EventLog.emit — must
        stay cheap and must never raise into the emit path)."""
        with self._lock:
            if rec.get("ev") == "run_start":
                self._run_start = rec
            self._ring.append(rec)
            self._total += 1

    # -- introspection -----------------------------------------------------
    def snapshot(self, n: "int | None" = None) -> list:
        """The most recent ``n`` records (all, when ``n`` is None) —
        oldest first, a point-in-time copy."""
        with self._lock:
            recs = list(self._ring)
        if n is not None and n > 0:
            recs = recs[-n:]
        return recs

    def stats(self) -> dict:
        with self._lock:
            held = len(self._ring)
            return {
                "capacity": self.capacity,
                "events": held,
                "recorded_total": self._total,
                "dropped": max(0, self._total - held),
            }

    # -- dumping -----------------------------------------------------------
    def dump_records(self) -> list:
        """The ring as a schema-valid event slice.

        When a ``run_start`` is still IN the ring, the slice is trimmed
        to open at the first one — the records ahead of it are the torn
        tail of an already-evicted scope, and prepending the sticky
        (latest) ``run_start`` above them would both duplicate it and
        re-anchor that tail under the wrong scope's clocks.  Only when
        eviction has pushed every ``run_start`` out (the ring then holds
        a single scope's tail by construction — scopes open WITH their
        ``run_start``) is the sticky copy prepended, restoring the
        correct scope header for exactly those events.
        """
        with self._lock:
            recs = list(self._ring)
            rs = self._run_start
        for i, rec in enumerate(recs):
            if isinstance(rec, dict) and rec.get("ev") == "run_start":
                return recs[i:]
        if rs is not None:
            return [rs, *recs]
        return recs

    def dump(self, path: str) -> int:
        """Write the current slice as JSONL (atomic tmp + rename — a
        dump taken mid-crash must never be a torn file); returns the
        number of records written."""
        recs = self.dump_records()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            for rec in recs:
                f.write(json.dumps(rec, separators=(",", ":"), default=str) + "\n")
        os.replace(tmp, path)
        return len(recs)


def _rss_bytes() -> int:
    """Resident set size, bytes (``/proc/self/statm``; ``getrusage``
    peak-RSS fallback off Linux; 0 when neither exists — the schema
    wants a non-negative int, not a missing field)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        peak = int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        # ru_maxrss is kilobytes on Linux/BSD but BYTES on Darwin
        return peak if sys.platform == "darwin" else peak * 1024
    except Exception:
        return 0


def _open_fds() -> int:
    """Open file-descriptor count (``/proc/self/fd``; 0 where /proc is
    absent)."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


class ResourceSampler:
    """Daemon thread emitting periodic ``flight_sample`` events.

    ``emit`` is the event log's emit callable (``telemetry.events.emit``),
    so samples ride the normal pipeline: stamped timestamps, common
    fields, the file, AND the mirror ring.  ``probes`` is an optional
    host callback returning extra schema-optional gauges (queue depths,
    backlogs, cache occupancy, HBM watermark) merged into each sample; a
    probe failure degrades to the base sample — the sampler must never
    take down the run it watches, and neither may a sample emitted into
    a log that is closing under it (the stop() race on the abort path).
    """

    def __init__(
        self,
        emit: Callable[..., Any],
        interval_s: float = 5.0,
        probes: "Callable[[], dict] | None" = None,
    ) -> None:
        if interval_s <= 0:
            raise ValueError(f"interval_s={interval_s} must be > 0")
        self._emit = emit
        self.interval_s = float(interval_s)
        self._probes = probes
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    def sample_fields(self) -> dict:
        """One sample's payload (probe gauges merged; never raises)."""
        fields: dict = {
            "rss_bytes": _rss_bytes(),
            "open_fds": _open_fds(),
            "threads": threading.active_count(),
        }
        if self._probes is not None:
            try:
                for k, v in self._probes().items():
                    if v is not None:
                        fields[k] = v
            except Exception:
                pass  # a sick probe degrades the sample, not the run
        return fields

    def sample(self) -> dict:
        """Emit one ``flight_sample`` NOW (also used by tests); returns
        the emitted fields."""
        fields = self.sample_fields()
        self._emit("flight_sample", **fields)
        return fields

    def start(self) -> "ResourceSampler":
        self._thread = threading.Thread(
            target=self._loop, name="lt-flight-sampler", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        # first sample right away: a short run still carries one
        while True:
            try:
                self.sample()
            except Exception:
                # emit into a log closing under us (abort-path stop race)
                # or transient /proc weirdness: skip the beat, keep going
                pass
            if self._stop.wait(self.interval_s):
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def thread_stacks() -> dict:
    """Every live thread's current traceback, newest frame last.

    Keyed ``"<name> (<ident>[, daemon])"``; frames are
    ``traceback.format_stack`` strings.  Built from
    ``sys._current_frames`` so it works from ANY thread — including an
    HTTP handler answering ``/debug/stacks`` while the dispatcher is
    wedged in a lock or a native call (the exact situation it exists
    for).  Pure read: no locks taken, no threads interrupted.
    """
    names = {t.ident: t for t in threading.enumerate()}
    out: dict = {}
    for ident, frame in sys._current_frames().items():
        t = names.get(ident)
        label = f"{t.name if t else '?'} ({ident}"
        if t is not None and t.daemon:
            label += ", daemon"
        label += ")"
        out[label] = [
            line.rstrip("\n") for line in traceback.format_stack(frame)
        ]
    return out
