"""Cross-layer request assembly: one ``trace_id`` → one blamed timeline.

The fourth observability layer (after events, pod traces, fleet
metrics): events answer "how is this host doing", pod traces "which
stage bounds this run", fleet metrics "how is the pod doing" — this
module answers **"what happened to THIS request"**.  A request entering
``lt route`` crosses tenant DRR queue → route decision → forward
(possibly a re-route hop after a replica death) → replica admission
queue → job exec → run → tile spans; the ``trace_id`` minted at router
(or serve) admission rides every one of those events
(:data:`~land_trendr_tpu.obs.events.COMMON_OPTIONAL_FIELDS`), and this
module folds the router + replica + run streams back into one
wall-aligned timeline with a **blame decomposition** whose components
provably sum to the router-observed latency.

* **Clock alignment** — each stream scope's ``(anchor_wall,
  anchor_mono)`` pair (sampled together at ``run_start`` — the pod-trace
  assembler's contract) maps every event's monotonic clock onto the
  shared wall axis drift-free.  Unlike :func:`~land_trendr_tpu.obs.
  spans.assemble_pod_trace` (which zeroes every host at its barrier'd
  ``run_start``), request assembly keeps absolute wall times: router
  and replicas start at different moments and the journey spans them.
  A fleet is same-machine by construction (loopback replicas), so wall
  clocks agree; multi-machine joins inherit NTP skew — reported, not
  corrected.

* **Blame decomposition** (:func:`blame_partition`) — the
  router-observed interval ``[submit, terminal]`` is PARTITIONED by a
  priority sweep over every interval the trace's streams contribute:
  router ``request_span`` segments (``forward`` hops, queue waits,
  throttle backoffs, the result relay), the replica's admission wait
  (``job_start.wait_s``), the run's compile verdict and pipeline spans
  (``feed``/``upload``/``fetch`` explicit, ``compute``/``write``
  derived).  Each instant is assigned to exactly ONE component (highest
  priority covering interval; uncovered instants are ``other`` — poll
  lag, inter-tile gaps), so the components sum to the interval length
  *by construction* — the property ``tools/perf_gate.py``'s reqtrace
  leg and the ``request_done`` value lint pin.

Stdlib-only and jax-free like the rest of :mod:`land_trendr_tpu.obs`.
Consumers: ``tools/lt_request.py`` (CLI + Chrome export),
``tools/fault_soak.py`` (two-hop re-route assertions),
``tools/perf_gate.py`` (reqtrace leg), ``tools/reqtrace_bench.py``.
"""

from __future__ import annotations

import glob
import json
import os

__all__ = [
    "BLAME_PRIORITY",
    "assemble_request",
    "blame_partition",
    "discover_request_files",
    "list_requests",
]

#: blame components in sweep priority order (earlier wins on overlap):
#: router-observed segments first (they are exact partitions of the
#: router's own clock), then the replica admission wait, then the run's
#: pipeline stages with ``compute`` outranking the overlappable
#: host-side stages (a pipelined instant doing compute AND feed is
#: compute-bound), ``write`` last.  Uncovered time is ``other``.
BLAME_PRIORITY = (
    "forward",
    "relay",
    "throttle_backoff",
    "route_queue",
    "replica_queue",
    "compile",
    "compute",
    "fetch",
    "upload",
    "feed",
    "write",
)


def discover_request_files(root: str) -> "list[str]":
    """Every event stream a router (or serve) workdir tree holds.

    The fleet layout is fixed: the root's own ``events*.jsonl`` (router
    or server scope), ``replicas/<rid>/events*.jsonl`` (spawned replica
    server scopes), and ``jobs/<id>/work/events*.jsonl`` (the pinned
    per-job run scopes every replica resumes).  Sorted for a
    deterministic fold; missing levels are simply absent (a standalone
    serve root has no ``replicas/``).
    """
    out: "list[str]" = []
    for pattern in (
        "events*.jsonl",
        os.path.join("replicas", "*", "events*.jsonl"),
        os.path.join("jobs", "*", "work", "events*.jsonl"),
    ):
        out.extend(glob.glob(os.path.join(root, pattern)))
    return sorted(out)


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _iter_anchored(path: str):
    """Yield ``(record, wall_t)`` for every parseable event of EVERY
    scope of one stream, with ``wall_t`` the record's monotonic clock
    mapped through its scope's anchor (drift-free wall placement).

    All scopes, not just the last: a re-routed request's run stream
    holds the killed first attempt's scope AND the resumed second one,
    and the journey needs both.  Malformed lines are skipped (the
    post-mortem fold discipline).
    """
    aw = am = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict):
                continue
            if rec.get("ev") == "run_start":
                w = rec.get("anchor_wall", rec.get("t_wall"))
                m = rec.get("anchor_mono", rec.get("t_mono"))
                if _num(w) and _num(m):
                    aw, am = float(w), float(m)
            t = rec.get("t_mono")
            if aw is not None and _num(t):
                wall = aw + (float(t) - am)
            else:
                wall = rec.get("t_wall") if _num(rec.get("t_wall")) else None
            yield rec, wall, (aw, am)


def _mono_to_wall(anchor, mono) -> "float | None":
    aw, am = anchor
    if aw is None or not _num(mono):
        return None
    return aw + (float(mono) - am)


def blame_partition(
    intervals: "list[tuple[str, float, float]]",
    t0: float,
    t1: float,
    priority: "tuple[str, ...]" = BLAME_PRIORITY,
) -> "dict[str, float]":
    """Partition ``[t0, t1]`` over prioritised components (seconds each).

    ``intervals`` is ``[(component, start, end), ...]`` on one shared
    axis; every instant of ``[t0, t1]`` is assigned to the
    highest-priority component covering it (``other`` when none does),
    so ``sum(result.values()) == t1 - t0`` exactly — the decomposition
    is a partition, not a sum of overlapping stage totals.  Components
    that claimed no time are omitted.
    """
    rank = {name: i for i, name in enumerate(priority)}
    out: "dict[str, float]" = {}
    if t1 <= t0:
        return out
    # clip to the window, drop the unrankable/empty, build sweep points
    events: "list[tuple[float, int, int]]" = []  # (t, +1/-1, rank)
    for name, s, e in intervals:
        r = rank.get(name)
        if r is None:
            continue
        s, e = max(float(s), t0), min(float(e), t1)
        if e <= s:
            continue
        events.append((s, 1, r))
        events.append((e, -1, r))
    events.sort(key=lambda x: (x[0], -x[1]))
    active = [0] * len(priority)
    cur = t0
    i = 0
    n = len(events)
    while i <= n:
        nxt = events[i][0] if i < n else t1
        nxt = min(max(nxt, t0), t1)
        if nxt > cur:
            comp = "other"
            for r, cnt in enumerate(active):
                if cnt > 0:
                    comp = priority[r]
                    break
            out[comp] = out.get(comp, 0.0) + (nxt - cur)
            cur = nxt
        if i == n:
            break
        t, delta, r = events[i]
        active[r] += delta
        i += 1
    if cur < t1:
        out["other"] = out.get("other", 0.0) + (t1 - cur)
    return out


def list_requests(paths: "list[str]") -> "list[dict]":
    """Every ``request_done`` across the streams, slowest first —
    the "which trace do I assemble" index (``lt_request --list``)."""
    out: "list[dict]" = []
    for path in paths:
        for rec, wall, _anchor in _iter_anchored(path):
            if rec.get("ev") != "request_done":
                continue
            out.append({
                "trace_id": rec.get("trace_id"),
                "status": rec.get("status"),
                "latency_s": rec.get("latency_s"),
                "hops": rec.get("hops"),
                "tenant": rec.get("tenant"),
                "job_id": rec.get("job_id"),
                "events_file": path,
            })
    out.sort(
        key=lambda r: -(r["latency_s"] if _num(r["latency_s"]) else -1.0)
    )
    return out


def assemble_request(paths: "list[str]", trace_id: str) -> dict:
    """Fold N event streams into one request's cross-layer record.

    Returns::

        {
          "trace_id": ..., "files": N, "events_scanned": n,
          "found": bool,                # any event carried the id
          "status": ..., "latency_s": ...,   # from request_done (router)
          "submitted_t": wall, "hops": [{replica, attempt, ok, t0, dur}],
          "timeline": [{component, t0, dur, file, detail?}, ...],
          "blame": {component: seconds},     # partition of latency_s
          "blame_sum_s": ...,                # == latency_s by construction
          "router_blame": {...},             # request_done's own split
          "replica_jobs": [...], "tiles_done": n,
          "complete": bool,             # request_done + >=1 hop + run events
        }

    Without a ``request_done`` (a direct serve job, or a still-running
    request) the record still assembles — ``latency_s`` then derives
    from the observed event envelope and ``complete`` is False.
    """
    events_scanned = 0
    submit_wall = None        # router job_submitted (or earliest seen)
    done_rec = None
    hops: "list[dict]" = []
    #: (component, start_wall, end_wall) for the sweep
    intervals: "list[tuple[str, float, float]]" = []
    timeline: "list[dict]" = []
    replica_jobs: "list[dict]" = []
    tiles_done = 0
    run_events = 0
    t_min = t_max = None

    def _note(component: str, s: float, e: float, fileno: int, **detail):
        nonlocal t_min, t_max
        if e < s:
            s, e = e, s
        intervals.append((component, s, e))
        entry = {
            "component": component,
            "t0": round(s, 6),
            "dur": round(e - s, 6),
            "file": fileno,
        }
        entry.update({k: v for k, v in detail.items() if v is not None})
        timeline.append(entry)
        t_min = s if t_min is None else min(t_min, s)
        t_max = e if t_max is None else max(t_max, e)

    for fileno, path in enumerate(paths):
        for rec, wall, anchor in _iter_anchored(path):
            events_scanned += 1
            ev = rec.get("ev")
            if rec.get("trace_id") != trace_id:
                continue
            if wall is None:
                continue
            if ev == "job_submitted":
                # router admission opens the window; a replica's own
                # job_submitted (re-admission per hop) only bounds it
                if submit_wall is None or wall < submit_wall:
                    submit_wall = wall
            elif ev == "request_span":
                name = rec.get("name")
                s = _mono_to_wall(anchor, rec.get("start"))
                e = _mono_to_wall(anchor, rec.get("end"))
                if not isinstance(name, str) or s is None or e is None:
                    continue
                _note(
                    name, s, e, fileno,
                    replica=rec.get("replica"),
                    attempt=rec.get("attempt"),
                    ok=rec.get("ok"),
                )
                if name == "forward":
                    hops.append({
                        "replica": rec.get("replica"),
                        "attempt": rec.get("attempt"),
                        "ok": rec.get("ok"),
                        "t0": round(s, 6),
                        "dur": round(max(e - s, 0.0), 6),
                    })
            elif ev == "request_done":
                done_rec = {**rec, "_wall": wall}
            elif ev == "job_start":
                w_s = rec.get("wait_s")
                if _num(w_s):
                    _note(
                        "replica_queue", wall - float(w_s), wall, fileno,
                        job_id=rec.get("job_id"),
                    )
                replica_jobs.append({
                    "job_id": rec.get("job_id"),
                    "tenant": rec.get("tenant"),
                    "events_file": path,
                })
            elif ev == "program_cache":
                c_s = rec.get("compile_s")
                aw = anchor[0]
                if _num(c_s) and c_s > 0 and aw is not None:
                    # the dummy-tile compile runs at scope start, before
                    # the first tile — anchor the interval there
                    _note("compile", aw, aw + float(c_s), fileno)
            elif ev == "span":
                name = rec.get("name")
                s = _mono_to_wall(anchor, rec.get("start"))
                e = _mono_to_wall(anchor, rec.get("end"))
                if name in ("feed", "upload", "fetch") and s is not None \
                        and e is not None:
                    _note(str(name), s, e, fileno, tile=rec.get("tile_id"))
                    run_events += 1
            elif ev == "tile_done":
                c_s = rec.get("compute_s")
                if _num(c_s):
                    _note(
                        "compute", wall - float(c_s), wall, fileno,
                        tile=rec.get("tile_id"),
                    )
                tiles_done += 1
                run_events += 1
            elif ev == "write_done":
                r_s = rec.get("record_s")
                if _num(r_s):
                    _note(
                        "write", wall - float(r_s), wall, fileno,
                        tile=rec.get("tile_id"),
                    )
                run_events += 1
            elif ev in ("run_start", "run_done", "tile_start"):
                run_events += 1

    hops.sort(key=lambda h: h["t0"])
    timeline.sort(key=lambda s: (s["t0"], s["component"]))
    found = bool(
        submit_wall is not None or done_rec is not None or timeline
    )
    out: dict = {
        "trace_id": trace_id,
        "files": len(paths),
        "events_scanned": events_scanned,
        "found": found,
        "hops": hops,
        "replica_jobs": replica_jobs,
        "tiles_done": tiles_done,
        "timeline": timeline,
    }
    if not found:
        out.update(complete=False, blame={}, blame_sum_s=0.0)
        return out

    # the router-observed window: admission → terminal.  request_done
    # is authoritative for the LENGTH (its latency_s is the router's
    # own submit→terminal measurement); the start anchors at the
    # router's job_submitted.  Fallbacks keep a partial trace useful.
    if submit_wall is None:
        submit_wall = t_min if t_min is not None else (
            done_rec["_wall"] if done_rec else 0.0
        )
    if done_rec is not None and _num(done_rec.get("latency_s")):
        latency = float(done_rec["latency_s"])
        out["status"] = done_rec.get("status")
        out["router_blame"] = done_rec.get("blame")
        if "hops" in done_rec:
            out["router_hops"] = done_rec["hops"]
    else:
        end = t_max if t_max is not None else submit_wall
        latency = max(0.0, end - submit_wall)
        out["status"] = None
        out["router_blame"] = None
    blame = blame_partition(
        intervals, submit_wall, submit_wall + latency
    )
    blame = {k: round(v, 6) for k, v in sorted(blame.items())}
    out.update(
        submitted_t=round(submit_wall, 6),
        latency_s=round(latency, 6),
        blame=blame,
        blame_sum_s=round(sum(blame.values()), 6),
        complete=bool(done_rec is not None and hops and run_events),
    )
    return out
