"""Fleet telemetry alerts: a small declarative rules engine.

The **alerts** quarter of the fleet telemetry plane: evaluate declared
rules over the aggregated history (:mod:`~land_trendr_tpu.obs.history`
samples) and drive a firing → resolved lifecycle per rule — the
machine-readable half of "a deadline miss fires an alert somewhere".

Rule kinds:

=============  ===========================================================
``threshold``  latest sample's ``metric`` compared ``op`` ``value``
``rate``       reset-aware counter rate of ``metric`` over ``window_s``
               (:func:`~land_trendr_tpu.obs.history.counter_rate`)
               compared ``op`` ``value``
``slo_burn``   sugar for a threshold on the pod-max ``lt_slo_burn_rate``
``absent``     host-staleness/absence: fires when the latest sample's
               ``metric`` (default ``stale_hosts``) compares ``op``
               ``value`` (defaults ``> 0`` — one stale host fires), OR
               when no sample landed within ``window_s`` at all — the
               whole plane going dark is itself an alert
=============  ===========================================================

Lifecycle: a rule's condition must hold continuously for ``for_s``
before the rule **fires** (transients don't page), and must stay clear
for ``hold_down_s`` before it **resolves** (flapping doesn't page
twice).  Transitions are returned from :meth:`AlertEngine.evaluate` as
plain dicts matching the ``alert`` event schema (``rule`` / ``state`` /
``value`` / ``threshold`` / ``duration_s``), and the engine is a pure
function of the ``(samples, now)`` sequence it was shown — replaying a
scripted history produces byte-identical transitions, which is exactly
what ``tools/perf_gate.py`` gates.  Stdlib-only, jax-free.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from land_trendr_tpu.obs.history import counter_rate, latest_value

__all__ = [
    "ALERT_KINDS",
    "ALERT_STATES",
    "DEFAULT_RULES",
    "PURE_MACHINES",
    "AlertEngine",
    "AlertRule",
    "load_rules",
    "parse_rules",
]

#: The observability-side pure decision machines, as ``(file, symbol)``
#: data — the other half of lt-lint LT009's registry (see
#: ``fleet/scheduling.py`` for the fleet half and the rationale).  The
#: alert lifecycle engine is replayed against scripted histories by the
#: perf gate; the event value-lint folds (``*_value_errors`` and the
#: stateful lint classes in ``tools/check_events_schema.py``) fold the
#: same stream to the same verdicts on every host, which is the same
#: purity obligation.  ``load_rules``/``parse_rules`` are deliberately
#: absent: loading a rules FILE is configuration, not a replayed
#: decision.
PURE_MACHINES = (
    ("land_trendr_tpu/obs/alerts.py", "AlertEngine.evaluate"),
    ("land_trendr_tpu/obs/alerts.py", "AlertEngine._rule_value"),
    ("land_trendr_tpu/obs/alerts.py", "AlertEngine._transition"),
    ("tools/check_events_schema.py", "*_value_errors"),
    ("tools/check_events_schema.py", "FetchValueLint"),
    ("tools/check_events_schema.py", "TraceRefLint"),
    ("tools/check_events_schema.py", "AlertValueLint"),
)

ALERT_KINDS = ("threshold", "rate", "slo_burn", "absent")

#: the ``alert`` event's state vocabulary (value-linted by
#: ``tools/check_events_schema.py``)
ALERT_STATES = ("firing", "resolved")

_OPS = (">", ">=", "<", "<=")


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declared rule (see the module docstring's kind table)."""

    name: str
    kind: str = "threshold"
    #: sample key: a flattened metric (``lt_serve_queue_depth``,
    #: ``lt_tiles_failed_total``...) or a sample health field
    #: (``stale_hosts``, ``corrupt_snaps``); ``slo_burn`` implies
    #: ``lt_slo_burn_rate``, ``absent`` defaults to ``stale_hosts``
    metric: str = ""
    op: str = ">"
    value: float = 0.0
    #: rate window (``rate``) / absence window (``absent``), seconds
    window_s: float = 60.0
    #: condition must hold this long before the rule fires
    for_s: float = 0.0
    #: condition must stay clear this long before the rule resolves
    hold_down_s: float = 0.0

    def __post_init__(self) -> None:
        if not self.name or not isinstance(self.name, str):
            raise ValueError("alert rule needs a non-empty string name")
        if self.kind not in ALERT_KINDS:
            raise ValueError(
                f"rule {self.name!r}: kind {self.kind!r} not one of "
                f"{ALERT_KINDS}"
            )
        if self.op not in _OPS:
            raise ValueError(
                f"rule {self.name!r}: op {self.op!r} not one of {_OPS}"
            )
        if self.kind in ("threshold", "rate") and not self.metric:
            raise ValueError(
                f"rule {self.name!r}: kind {self.kind!r} needs a metric"
            )
        if self.window_s <= 0:
            raise ValueError(
                f"rule {self.name!r}: window_s={self.window_s} must be > 0"
            )
        if self.for_s < 0 or self.hold_down_s < 0:
            raise ValueError(
                f"rule {self.name!r}: for_s/hold_down_s must be >= 0"
            )

    @property
    def resolved_metric(self) -> str:
        if self.kind == "slo_burn":
            return "lt_slo_burn_rate"
        if self.kind == "absent":
            return self.metric or "stale_hosts"
        return self.metric


def parse_rules(spec: "list | dict | str") -> "tuple[AlertRule, ...]":
    """Rule declarations → validated rules.

    Accepts the parsed JSON (a list of rule objects, or ``{"rules":
    [...]}``) or the JSON text itself.  Raises ``ValueError`` on any
    typo — an unknown key, kind or op is a config error at startup,
    never a dead rule discovered after the incident.
    """
    if isinstance(spec, str):
        try:
            spec = json.loads(spec)
        except json.JSONDecodeError as e:
            raise ValueError(f"alert rules are not valid JSON: {e}") from None
    if isinstance(spec, dict):
        spec = spec.get("rules")
    if not isinstance(spec, list):
        raise ValueError(
            "alert rules must be a JSON list of rule objects (or "
            '{"rules": [...]})'
        )
    known = {f.name for f in dataclasses.fields(AlertRule)}
    rules: list = []
    for i, item in enumerate(spec):
        if not isinstance(item, dict):
            raise ValueError(f"alert rule #{i} is not a JSON object")
        unknown = sorted(set(item) - known)
        if unknown:
            raise ValueError(
                f"alert rule #{i} ({item.get('name', '?')}): unknown "
                f"key(s) {unknown}"
            )
        rules.append(AlertRule(**item))
    names = [r.name for r in rules]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise ValueError(f"duplicate alert rule name(s): {dupes}")
    return tuple(rules)


def load_rules(path: str) -> "tuple[AlertRule, ...]":
    """Parse a rules file (JSON, see :func:`parse_rules`)."""
    with open(path) as f:
        return parse_rules(f.read())


#: the rules every fleet loop ships with unless a rules file overrides
#: them: a host going stale/dark, and a burning SLO budget
DEFAULT_RULES: "tuple[AlertRule, ...]" = (
    AlertRule(
        name="fleet_host_stale",
        kind="absent",
        window_s=60.0,
        hold_down_s=10.0,
    ),
    AlertRule(
        name="slo_burn_high",
        kind="slo_burn",
        op=">=",
        value=0.5,
        for_s=0.0,
        hold_down_s=30.0,
    ),
)


def _compare(value: float, op: str, threshold: float) -> bool:
    if op == ">":
        return value > threshold
    if op == ">=":
        return value >= threshold
    if op == "<":
        return value < threshold
    return value <= threshold


class AlertEngine:
    """Per-rule firing → resolved state machine over history samples.

    Single-owner like the history ring (one fleet loop evaluates; other
    threads read :meth:`active` snapshots the owner refreshed).  All
    timing comes from the caller's ``now`` and the samples' own ``t``
    stamps — no internal clock reads — so a scripted history replays to
    identical transitions.
    """

    def __init__(self, rules: "tuple[AlertRule, ...]" = DEFAULT_RULES) -> None:
        self.rules = tuple(rules)
        # phase: "ok" | "pending" | "firing" ; pending_since / fired_t /
        # clear_since are the lifecycle clocks
        self._state: dict = {
            r.name: {
                "phase": "ok",
                "pending_since": None,
                "fired_t": None,
                "clear_since": None,
                "value": None,
            }
            for r in self.rules
        }

    # -- condition evaluation ----------------------------------------------
    def _rule_value(
        self, rule: AlertRule, samples: list, now: float
    ) -> "tuple[float | None, bool]":
        """``(observed value, condition holds)`` for one rule."""
        key = rule.resolved_metric
        if rule.kind == "rate":
            v = counter_rate(samples, key, rule.window_s, now=now)
            return v, v is not None and _compare(v, rule.op, rule.value)
        if rule.kind == "absent":
            recent = [
                s for s in samples
                if isinstance(s.get("t"), (int, float))
                and s["t"] >= now - rule.window_s
            ]
            if not recent:
                # the plane itself is dark: no sample in the window
                return None, True
            v = latest_value(recent, key)
            # the declared op/value are honored (defaults `> 0` — one
            # stale host fires), not a hardcoded bound: a rule asking
            # for `corrupt_snaps >= 3` must page at 3, never silently 1
            return v, v is not None and _compare(v, rule.op, rule.value)
        v = latest_value(samples, key)  # threshold | slo_burn
        return v, v is not None and _compare(v, rule.op, rule.value)

    # -- the lifecycle -----------------------------------------------------
    def evaluate(self, samples: list, now: float) -> list:
        """Advance every rule against the history; returns this
        evaluation's transitions (``alert``-event-shaped dicts), firing
        first, in rule order."""
        transitions: list = []
        for rule in self.rules:
            st = self._state[rule.name]
            value, cond = self._rule_value(rule, samples, now)
            st["value"] = value
            if cond:
                st["clear_since"] = None
                if st["phase"] == "ok":
                    st["phase"] = "pending"
                    st["pending_since"] = now
                if st["phase"] == "pending" and (
                    now - st["pending_since"] >= rule.for_s
                ):
                    st["phase"] = "firing"
                    st["fired_t"] = now
                    transitions.append(self._transition(
                        rule, "firing", value,
                        duration_s=now - st["pending_since"],
                    ))
            else:
                if st["phase"] == "pending":
                    st["phase"] = "ok"
                    st["pending_since"] = None
                elif st["phase"] == "firing":
                    if st["clear_since"] is None:
                        st["clear_since"] = now
                    if now - st["clear_since"] >= rule.hold_down_s:
                        transitions.append(self._transition(
                            rule, "resolved", value,
                            duration_s=now - st["fired_t"],
                        ))
                        st.update(
                            phase="ok", pending_since=None, fired_t=None,
                            clear_since=None,
                        )
        return transitions

    def _transition(
        self, rule: AlertRule, state: str, value: "float | None",
        duration_s: float,
    ) -> dict:
        return {
            "rule": rule.name,
            "state": state,
            "value": round(float(value), 6) if value is not None else 0.0,
            "threshold": float(rule.value),
            "duration_s": round(max(0.0, duration_s), 6),
            "window_s": float(rule.window_s),
        }

    def active(self) -> list:
        """Currently-firing rules (JSON-safe snapshots for ``/healthz``,
        the publisher's ``state.alerts`` block and ``lt top``)."""
        out: list = []
        for rule in self.rules:
            st = self._state[rule.name]
            if st["phase"] == "firing":
                out.append({
                    "rule": rule.name,
                    "state": "firing",
                    "since_t": st["fired_t"],
                    "value": st["value"],
                    "threshold": float(rule.value),
                })
        return out
