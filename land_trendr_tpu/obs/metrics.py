"""Metrics registry + Prometheus text exposition for tile runs.

The counters/gauges/histograms half of :mod:`land_trendr_tpu.obs` — the
TPU-native stand-in for the reference's Hadoop job counters, in the format
the rest of the monitoring world scrapes.  Pure stdlib (no
``prometheus_client`` dependency — the container must not grow one): the
exposition writer emits the node-exporter text format 0.0.4 directly.

Three consumption paths, least- to most-infrastructure:

* :meth:`MetricsRegistry.render` — the exposition text, for tests and ad
  hoc inspection;
* :class:`PromFileExporter` — a daemon thread atomically refreshing
  ``<workdir>/metrics.prom`` every ``interval_s`` (tmp + ``os.replace``, so
  a scraper-side ``cat`` never sees a torn file; node_exporter's textfile
  collector ingests it as-is);
* :class:`MetricsHTTPServer` — an optional stdlib ``http.server``
  ``/metrics`` endpoint (CLI ``--metrics-port``; default off) so an
  in-flight gigapixel run is scrapeable directly.

All instruments are thread-safe (one registry lock — observation cost is a
dict update, far below the driver's per-tile work) and support optional
constant labels, e.g. ``registry.gauge("lt_stage_seconds", labels={"stage":
"feed"})``; instruments sharing a name must share a type and help string.
"""

from __future__ import annotations

import http.server
import math
import os
import re
import threading
from typing import Iterable, Mapping

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "PromFileExporter",
    "MetricsHTTPServer",
    "DEFAULT_LATENCY_BUCKETS",
    "EXEMPLAR_RING",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: tile-latency histogram buckets (seconds): spans sub-100ms TPU tiles to
#: multi-minute CPU-backend tiles
DEFAULT_LATENCY_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


def _fmt(v: float) -> str:
    """A Prometheus-parseable number (repr floats, bare ints, +Inf/NaN)."""
    if isinstance(v, bool):  # pragma: no cover - guarded upstream
        v = int(v)
    if isinstance(v, int):
        return str(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return repr(float(v))


def _fmt_labels(labels: Mapping[str, str] | None, extra: str = "") -> str:
    parts = []
    for k, v in sorted((labels or {}).items()):
        # exposition-format label-value escapes: backslash, quote, AND
        # line-feed — a raw newline inside the quoted value makes the
        # whole scrape unparseable
        escaped = (
            str(v)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )
        parts.append(f'{k}="{escaped}"')
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    kind = "untyped"

    def __init__(
        self, name: str, help: str, labels: Mapping[str, str] | None, lock: threading.Lock
    ) -> None:
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = lock


class Counter(_Metric):
    """Monotonically non-decreasing count (Prometheus ``counter``)."""

    kind = "counter"

    def __init__(self, *a) -> None:
        super().__init__(*a)
        self._value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {v})")
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _render(self) -> list[str]:
        # lt: noqa[LT001] — only called from MetricsRegistry.render, which
        # already holds this same shared (non-reentrant) lock
        return [f"{self.name}{_fmt_labels(self.labels)} {_fmt(self._value)}"]


class Gauge(_Metric):
    """Settable instantaneous value (Prometheus ``gauge``)."""

    kind = "gauge"

    def __init__(self, *a) -> None:
        super().__init__(*a)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def set_max(self, v: float) -> None:
        """Watermark update: keep the maximum ever seen (e.g. HBM peak)."""
        with self._lock:
            self._value = max(self._value, float(v))

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._value += v

    def dec(self, v: float = 1.0) -> None:
        self.inc(-v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _render(self) -> list[str]:
        # lt: noqa[LT001] — only called from MetricsRegistry.render, which
        # already holds this same shared (non-reentrant) lock
        return [f"{self.name}{_fmt_labels(self.labels)} {_fmt(self._value)}"]


#: per-bucket exemplar-ring bound: enough recent trace ids to resolve
#: "the p99 bucket" to concrete requests, small enough that exemplar
#: state stays O(buckets) per histogram
EXEMPLAR_RING = 4


class Histogram(_Metric):
    """Fixed-bucket histogram (Prometheus ``histogram``).

    Buckets are chosen at construction (no dynamic rebinning — exposition
    must stay append-consistent across scrapes); observations above the
    last bound land in ``+Inf`` only, per the exposition contract.

    Observations may carry an **exemplar** (a trace id): each bucket
    keeps a bounded ring of the most recent ``(exemplar, value)`` pairs
    it absorbed, so a tail bucket names concrete requests an operator
    can go assemble (``tools/lt_request.py``) instead of an anonymous
    count.  Exemplar state is created lazily on the first exemplar'd
    observation — plain ``observe(v)`` paths pay nothing.
    """

    kind = "histogram"

    def __init__(self, name, help, labels, lock, buckets: Iterable[float]) -> None:
        super().__init__(name, help, labels, lock)
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError(f"histogram {name} needs at least one bucket")
        if any(b != b or math.isinf(b) for b in bounds):
            raise ValueError(f"histogram {name}: finite bucket bounds only")
        self.bounds = tuple(bounds)
        self._counts = [0] * (len(bounds) + 1)  # +Inf last
        self._sum = 0.0
        self._count = 0
        #: lazily-created per-bucket exemplar rings (newest last)
        self._ex: "list[list] | None" = None

    def observe(self, v: float, exemplar: "str | None" = None) -> None:
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            idx = len(self.bounds)
            for i, b in enumerate(self.bounds):
                if v <= b:
                    idx = i
                    break
            self._counts[idx] += 1
            if exemplar is not None:
                if self._ex is None:
                    self._ex = [[] for _ in range(len(self.bounds) + 1)]
                ring = self._ex[idx]
                ring.append((str(exemplar), v))
                if len(ring) > EXEMPLAR_RING:
                    del ring[0]

    def _exemplars_locked(self) -> "dict[str, list] | None":
        """The ring→JSON shaping (caller holds the shared lock) — ONE
        copy serving both the per-metric accessor and the registry dump
        (which cannot re-take the shared non-reentrant lock)."""
        if self._ex is None:
            return None
        out: "dict[str, list]" = {}
        for i, ring in enumerate(self._ex):
            if not ring:
                continue
            le = _fmt(self.bounds[i]) if i < len(self.bounds) else "+Inf"
            out[le] = [{"trace_id": t, "value": v} for t, v in ring]
        return out or None

    def exemplars(self) -> "dict[str, list] | None":
        """Per-bucket exemplar rings, ``le`` string → newest-last
        ``[{"trace_id", "value"}, ...]`` (buckets with none omitted;
        None when no observation ever carried an exemplar)."""
        with self._lock:
            return self._exemplars_locked()

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def _render(self) -> list[str]:
        lines = []
        cum = 0
        for b, c in zip(self.bounds, self._counts):
            cum += c
            le = 'le="%s"' % _fmt(b)
            lines.append(f"{self.name}_bucket{_fmt_labels(self.labels, le)} {cum}")
        inf = 'le="+Inf"'
        lines.append(
            f"{self.name}_bucket{_fmt_labels(self.labels, inf)} {self._count}"
        )
        lines.append(f"{self.name}_sum{_fmt_labels(self.labels)} {_fmt(self._sum)}")
        lines.append(f"{self.name}_count{_fmt_labels(self.labels)} {self._count}")
        return lines


class MetricsRegistry:
    """Instrument factory + exposition renderer.

    ``counter``/``gauge``/``histogram`` are get-or-create on the full
    ``(name, labels)`` identity, so instrumentation sites can re-request an
    instrument instead of threading references around; a name re-used with
    a different metric type (or different histogram buckets) raises.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> (kind, help); insertion-ordered for stable exposition
        self._families: dict[str, tuple[str, str]] = {}
        self._metrics: dict[tuple[str, tuple], _Metric] = {}

    def _get(self, cls, name, help, labels, *args) -> _Metric:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for k in labels or {}:
            if not _LABEL_RE.match(k):
                raise ValueError(f"invalid label name {k!r} on {name}")
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            fam = self._families.get(name)
            if fam is not None and fam[0] != cls.kind:
                raise ValueError(
                    f"metric {name} already registered as {fam[0]}, not {cls.kind}"
                )
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help or (fam[1] if fam else ""), labels, self._lock, *args)
                self._metrics[key] = m
                if fam is None:
                    self._families[name] = (cls.kind, help)
            elif args and getattr(m, "bounds", None) != tuple(sorted(float(b) for b in args[0])):
                raise ValueError(f"histogram {name} re-registered with different buckets")
        return m

    def counter(self, name: str, help: str = "", labels: Mapping[str, str] | None = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: Mapping[str, str] | None = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Mapping[str, str] | None = None,
        buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS,
    ) -> Histogram:
        return self._get(Histogram, name, help, labels, tuple(buckets))

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every instrument."""
        with self._lock:
            by_name: dict[str, list[_Metric]] = {}
            for (name, _), m in self._metrics.items():
                by_name.setdefault(name, []).append(m)
            lines: list[str] = []
            for name, (kind, help) in self._families.items():
                if help:
                    lines.append(f"# HELP {name} {help}")
                lines.append(f"# TYPE {name} {kind}")
                for m in by_name.get(name, []):
                    lines.extend(m._render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> list:
        """JSON-able dump of every instrument — the fleet-publish wire
        format (:mod:`land_trendr_tpu.obs.publish`).

        One dict per instrument: ``name`` / ``kind`` / ``help`` /
        ``labels`` plus ``value`` (counter, gauge) or ``sum`` /
        ``count`` / ``bounds`` / ``buckets`` (histogram — per-bucket
        RAW counts, last entry the ``+Inf`` overflow, so a cross-host
        merge is a plain elementwise sum).  Sorted by ``(name,
        labels)`` so two snapshots of identical state are byte-identical
        once serialised — the aggregate layer's determinism contract
        starts here.
        """
        out: list = []
        with self._lock:
            for (name, lkey), m in self._metrics.items():
                kind, help = self._families[name]
                d: dict = {
                    "name": name,
                    "kind": kind,
                    "help": help,
                    "labels": dict(m.labels),
                }
                if kind == "histogram":
                    d["sum"] = m._sum
                    d["count"] = m._count
                    d["bounds"] = list(m.bounds)
                    d["buckets"] = list(m._counts)
                else:
                    d["value"] = m._value
                out.append(d)
        out.sort(key=lambda d: (d["name"], sorted(d["labels"].items())))
        return out

    def exemplars(self) -> list:
        """The ``/metrics``-adjacent exemplar JSON: one entry per
        histogram that ever absorbed an exemplar'd observation —
        ``name`` / ``labels`` / ``exemplars`` (``le`` → newest-last
        ``[{"trace_id", "value"}, ...]``).  Uses the histograms'
        ``_exemplars_locked`` under the shared lock, like
        :meth:`snapshot` (the per-metric accessor would re-take the
        same non-reentrant lock)."""
        out: list = []
        with self._lock:
            for (name, _), m in self._metrics.items():
                locked = getattr(m, "_exemplars_locked", None)
                rings = locked() if locked is not None else None
                if rings:
                    out.append({
                        "name": name,
                        "labels": dict(m.labels),
                        "exemplars": rings,
                    })
        out.sort(key=lambda d: (d["name"], sorted(d["labels"].items())))
        return out


class PromFileExporter:
    """Daemon thread atomically refreshing a ``.prom`` exposition file.

    ``write_now`` runs once at :meth:`start` (so even a sub-interval run
    leaves a file) and once at :meth:`stop` (the final state is always on
    disk); in between, the thread refreshes every ``interval_s``.  Atomic
    tmp + ``os.replace`` — a scrape never reads a torn file; the pid in
    the tmp name keeps shared-workdir pod processes from racing.
    """

    def __init__(self, registry: MetricsRegistry, path: str, interval_s: float = 5.0) -> None:
        if interval_s <= 0:
            raise ValueError(f"interval_s={interval_s} must be > 0")
        self.registry = registry
        self.path = path
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # serializes the loop thread and stop()'s final flush: they share
        # the pid-based tmp path, so unserialized they can tear it
        self._write_lock = threading.Lock()

    def write_now(self) -> None:
        with self._write_lock:
            self._write_locked()

    def _write_locked(self) -> None:
        tmp = f"{self.path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write(self.registry.render())
        os.replace(tmp, self.path)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.write_now()
            except OSError:  # pragma: no cover - transient FS pressure
                pass  # keep trying; the final stop() write will surface it

    def start(self) -> "PromFileExporter":
        self.write_now()
        self._thread = threading.Thread(
            target=self._loop, name="lt-metrics-exporter", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # after a join timeout the loop thread may still be wedged INSIDE
        # write_now on a hung shared filesystem: take the lock with a
        # bound and skip the final flush rather than race its tmp file or
        # hang (and possibly crash) a run whose artifacts are already
        # durable — the wedged writer holds the freshest state anyway
        if self._write_lock.acquire(timeout=5.0):
            try:
                self._write_locked()
            finally:
                self._write_lock.release()


class _QuietHTTPServer(http.server.ThreadingHTTPServer):
    """ThreadingHTTPServer that does not traceback on dropped scrapes.

    A scraper that disconnects mid-response (timeout, health-check
    half-open, port scan) raises BrokenPipeError/ConnectionResetError in
    the handler, which the stdlib ``handle_error`` dumps as a multi-line
    traceback to stderr — routine noise on a multi-hour run's log, not an
    error.  Anything else still gets the default report.
    """

    daemon_threads = True

    def handle_error(self, request, client_address) -> None:
        import sys

        if isinstance(sys.exc_info()[1], (BrokenPipeError, ConnectionResetError)):
            return
        super().handle_error(request, client_address)


class MetricsHTTPServer:
    """Optional in-flight scrape endpoint: stdlib ``/metrics`` server.

    ``port=0`` binds an ephemeral port (tests); the bound port is exposed
    as :attr:`port`.  Serves only GET ``/metrics`` (404 otherwise) on a
    daemon thread — nothing here can outlive or block the run.
    """

    def __init__(self, registry: MetricsRegistry, port: int, host: str = "") -> None:
        reg = registry

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - stdlib API name
                if self.path.split("?")[0] != "/metrics":
                    self.send_error(404)
                    return
                body = reg.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a) -> None:  # quiet: no per-scrape stderr
                pass

        self._server = _QuietHTTPServer((host, port), Handler)
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="lt-metrics-http", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=10)
