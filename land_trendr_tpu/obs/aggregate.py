"""Fleet telemetry aggregate: N per-process snapshots → one pod view.

The **aggregate** quarter of the fleet telemetry plane: fold the
snapshot files :mod:`land_trendr_tpu.obs.publish` writes under a shared
telemetry directory into one pod-level view — exposed as merged
instrument dicts, aggregated Prometheus exposition text, and the
flattened scalar samples the history ring retains.

Merge semantics are a **per-instrument policy table**, not a guess:

* **counters** always sum — the pod total is the per-host sum by
  definition (the acceptance invariant ``tools/perf_gate.py`` pins
  exactly);
* **histograms** merge bucket-wise — same bounds sum elementwise
  (``sum``/``count`` too); a bounds mismatch across hosts is flagged in
  ``conflicts`` and the divergent host's histogram is skipped rather
  than silently mis-binned;
* **gauges** follow :data:`GAUGE_SUM` / :data:`GAUGE_LAST` with ``max``
  as the default: backlogs and occupancy sum to meaningful pod totals,
  per-host "last observed" gauges take the freshest host's value, and
  everything else (burn rates, watermarks, demotion flags) takes the
  pod-worst ``max`` — the alerting-relevant fold.

Staleness is **flagged, never silently dropped**: every discovered
snapshot appears in the ``hosts`` list with its age — judged on the
FRESHER of the snapshot's own ``t_wall`` and the file's shared-FS mtime
(the multihost merge's mtime pattern: a publisher whose wall clock lags
the aggregator still refreshes its file on the filesystem's one clock,
and must not read permanently stale).  Hosts beyond their staleness
bound fold with ``stale: true``, torn/unparseable files fold as
``corrupt`` (excluded from the metric merge — a half-written JSON has
no trustworthy counters), and snapshots older than ``newer_than`` (a
reused telemetry dir's dead leftovers, e.g. a restarted replica's
predecessor) are listed ``excluded`` without contributing values or
feeding the staleness count.  Pid
reuse is superseded by ``generation``: of two snapshots claiming the
same ``(host, pid)``, only the highest ``(generation, seq)`` folds, so
a restarted process is never summed with its dead predecessor.

Everything is deterministic and byte-stable: instruments sort on
``(name, labels)``, hosts on ``(host, pid)``, and two folds of the same
files render identical exposition bytes — the property the history ring
and the alert engine's replayability stand on.  Stdlib-only, jax-free.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any, Iterable

from land_trendr_tpu.obs.metrics import _fmt, _fmt_labels
from land_trendr_tpu.obs.publish import SNAP_SCHEMA

__all__ = [
    "GAUGE_SUM",
    "GAUGE_LAST",
    "discover_snapshots",
    "flatten_scalars",
    "fold",
    "fold_dir",
    "gauge_policy",
    "histogram_quantile",
    "load_snapshots",
    "merge_instruments",
    "pod_sample",
    "render_prom",
]

#: gauges whose pod fold is the per-host SUM (backlogs, occupancy,
#: throughput — quantities that physically add across processes)
GAUGE_SUM = frozenset({
    "lt_feed_backlog",
    "lt_write_backlog",
    "lt_fetch_backlog",
    "lt_upload_backlog",
    "lt_feed_cache_bytes",
    "lt_ingest_store_bytes",
    "lt_device_bytes_in_use",
    "lt_device_bytes_peak",
    "lt_px_per_s",
    "lt_serve_queue_depth",
    "lt_serve_running",
    "lt_alerts_firing",
})

#: gauges where the FRESHEST host's value is the pod answer (per-host
#: "last observed" facts that neither sum nor max meaningfully)
GAUGE_LAST = frozenset({
    "lt_no_fit_rate",
    "lt_run_info",
})


def gauge_policy(name: str) -> str:
    """``sum`` / ``last`` / ``max`` for one gauge family — ``max`` (the
    pod-worst fold: burn rates, watermarks, demotion flags) unless the
    tables above say otherwise."""
    if name in GAUGE_SUM:
        return "sum"
    if name in GAUGE_LAST:
        return "last"
    return "max"


def discover_snapshots(directory: str) -> list:
    """Sorted ``*.snap.json`` paths under a telemetry directory (tmp
    files never match — publishers write ``*.tmp`` then rename)."""
    return sorted(glob.glob(os.path.join(directory, "*.snap.json")))


def load_snapshots(directory: str) -> list:
    """Parse every discovered snapshot into fold entries.

    Each entry: ``{"path", "mtime", "snap" | None, "corrupt"}`` — a
    torn/unparseable/mis-shaped file is an entry with ``corrupt: true``
    and no ``snap``, NOT an exception: one killed-mid-write publisher
    must never blind the pod view to its healthy peers.
    """
    entries: list = []
    for path in discover_snapshots(directory):
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue  # unlinked between glob and stat — a publisher churn
        entry: dict = {"path": path, "mtime": mtime, "snap": None, "corrupt": False}
        try:
            with open(path) as f:
                snap = json.load(f)
            if (
                not isinstance(snap, dict)
                or not isinstance(snap.get("host"), str)
                or not isinstance(snap.get("pid"), int)
                or not isinstance(snap.get("t_wall"), (int, float))
                or snap.get("schema") != SNAP_SCHEMA
            ):
                raise ValueError("snapshot missing identity fields")
            entry["snap"] = snap
        except (OSError, ValueError, json.JSONDecodeError):
            entry["corrupt"] = True
        entries.append(entry)
    return entries


def _dedupe_generations(entries: list) -> None:
    """Mark all but the highest ``(generation, seq)`` per ``(host,
    pid)`` as superseded (pid reuse after restart: the dead process's
    counters must not sum with its successor's)."""
    best: dict = {}
    for e in entries:
        snap = e["snap"]
        if snap is None:
            continue
        key = (snap["host"], snap["pid"])
        rank = (snap.get("generation", 0), snap.get("seq", 0))
        cur = best.get(key)
        if cur is None or rank > cur[0]:
            best[key] = (rank, e)
    for e in entries:
        snap = e["snap"]
        if snap is None:
            continue
        e["superseded"] = best[(snap["host"], snap["pid"])][1] is not e


def merge_instruments(per_host: "Iterable[tuple[float, list]]") -> "tuple[list, list]":
    """Fold per-host instrument lists into one merged, sorted list.

    ``per_host`` yields ``(t_wall, instruments)`` pairs — the timestamp
    orders the ``last`` gauge policy (freshest host wins).  Returns
    ``(merged, conflicts)``; conflicts are human-readable strings (kind
    clashes, histogram-bound mismatches) and the conflicting host's
    instrument is skipped, never silently coerced.
    """
    merged: dict = {}
    conflicts: list = []
    for t_wall, instruments in sorted(per_host, key=lambda p: p[0]):
        for inst in instruments:
            name = inst.get("name")
            labels = inst.get("labels") or {}
            kind = inst.get("kind")
            key = (name, tuple(sorted(labels.items())))
            cur = merged.get(key)
            if cur is None:
                cur = merged[key] = {
                    "name": name,
                    "kind": kind,
                    "help": inst.get("help", ""),
                    "labels": dict(labels),
                }
                if kind == "histogram":
                    cur["sum"] = 0.0
                    cur["count"] = 0
                    cur["bounds"] = list(inst.get("bounds", []))
                    cur["buckets"] = [0] * len(inst.get("buckets", []))
                else:
                    cur["value"] = 0.0 if kind == "counter" else None
                cur.setdefault("hosts", 0)
            if cur["kind"] != kind:
                conflicts.append(
                    f"{name}: kind {kind} clashes with {cur['kind']}"
                )
                continue
            cur["hosts"] += 1
            if kind == "counter":
                cur["value"] += float(inst.get("value", 0.0))
            elif kind == "histogram":
                if list(inst.get("bounds", [])) != cur["bounds"] or len(
                    inst.get("buckets", [])
                ) != len(cur["buckets"]):
                    conflicts.append(
                        f"{name}: histogram bounds differ across hosts"
                    )
                    cur["hosts"] -= 1
                    continue
                cur["sum"] += float(inst.get("sum", 0.0))
                cur["count"] += int(inst.get("count", 0))
                cur["buckets"] = [
                    a + int(b) for a, b in zip(cur["buckets"], inst["buckets"])
                ]
            else:  # gauge
                v = float(inst.get("value", 0.0))
                policy = gauge_policy(name)
                if cur["value"] is None:
                    cur["value"] = v
                elif policy == "sum":
                    cur["value"] += v
                elif policy == "last":
                    cur["value"] = v  # per_host iterates oldest → freshest
                else:
                    cur["value"] = max(cur["value"], v)
    out = sorted(
        merged.values(),
        key=lambda d: (d["name"], sorted(d["labels"].items())),
    )
    return out, sorted(set(conflicts))


def histogram_quantile(inst: dict, q: float) -> "float | None":
    """Estimate the ``q``-quantile (``0 < q <= 1``) of one histogram
    instrument dict (the :func:`merge_instruments` shape: ``bounds`` and
    per-bucket NON-cumulative ``buckets``, ``+Inf`` last).

    Prometheus ``histogram_quantile`` semantics: linear interpolation
    inside the target bucket between its lower and upper bound (the
    first bucket interpolates from 0); a quantile landing in the
    ``+Inf`` bucket answers the highest finite bound — the honest cap
    of what bucketed data can say.  ``None`` when the histogram is
    empty or shapeless.
    """
    bounds = list(inst.get("bounds") or [])
    buckets = list(inst.get("buckets") or [])
    count = int(inst.get("count", 0))
    if not bounds or len(buckets) != len(bounds) + 1 or count <= 0:
        return None
    rank = q * count
    cum = 0.0
    for i, c in enumerate(buckets):
        prev = cum
        cum += c
        if cum >= rank and c > 0:
            if i >= len(bounds):  # +Inf bucket: cap at the last bound
                return float(bounds[-1])
            lo = float(bounds[i - 1]) if i > 0 else 0.0
            hi = float(bounds[i])
            return lo + (hi - lo) * (rank - prev) / c
    return float(bounds[-1])


def fold(
    entries: list,
    now: "float | None" = None,
    stale_after_s: "float | None" = None,
    newer_than: "float | None" = None,
) -> dict:
    """Fold loaded snapshot entries into the pod view.

    ``stale_after_s`` overrides the per-host default of ``3 x`` the
    snapshot's own ``interval_s`` (a publisher that missed two
    consecutive beats is stale); ``newer_than`` (absolute wall time)
    excludes dead leftovers in a reused telemetry dir from the value
    fold — they stay LISTED with ``excluded: true``, per the
    never-silently-dropped contract.  Pass ``now`` explicitly for a
    deterministic (replayable, byte-stable) fold.
    """
    if now is None:
        now = time.time()
    _dedupe_generations(entries)
    hosts: list = []
    foldable: list = []
    alerts: list = []
    n_stale = n_corrupt = n_excluded = 0
    for e in entries:
        snap = e["snap"]
        if snap is None:
            n_corrupt += 1
            hosts.append({
                "path": os.path.basename(e["path"]),
                "host": None,
                "pid": None,
                "corrupt": True,
                "stale": True,
                "excluded": True,
                "age_s": round(max(0.0, now - e["mtime"]), 3),
            })
            continue
        # freshness is judged on the FRESHER of the snapshot's own stamp
        # and the file's shared-FS mtime: the publisher's wall clock is
        # never trusted alone (the PR-10 principle) — a host whose clock
        # lags the aggregator still refreshes its file on the shared
        # FS's one clock, and must not read permanently stale
        fresh_t = max(snap["t_wall"], e["mtime"])
        if e.get("superseded"):
            n_excluded += 1
            hosts.append({
                "path": os.path.basename(e["path"]),
                "host": snap["host"],
                "pid": snap["pid"],
                "generation": snap.get("generation"),
                "corrupt": False,
                "stale": True,
                "excluded": True,
                "superseded": True,
                "age_s": round(max(0.0, now - fresh_t), 3),
            })
            continue
        age = max(0.0, now - fresh_t)
        bound = (
            stale_after_s
            if stale_after_s is not None
            else 3.0 * float(snap.get("interval_s") or 5.0)
        )
        stale = age > bound
        excluded = newer_than is not None and fresh_t < newer_than
        row = {
            "path": os.path.basename(e["path"]),
            "host": snap["host"],
            "pid": snap["pid"],
            "kind": snap.get("kind", "run"),
            "generation": snap.get("generation"),
            "seq": snap.get("seq"),
            "age_s": round(age, 3),
            "uptime_s": snap.get("uptime_s"),
            "interval_s": snap.get("interval_s"),
            "corrupt": False,
            "stale": bool(stale),
            "excluded": bool(excluded),
        }
        state = snap.get("state")
        if isinstance(state, dict) and state:
            row["state"] = state
        hosts.append(row)
        if excluded:
            # a departed host (beyond newer_than) is excluded, not
            # stale: it must stop feeding the staleness alert — the
            # alert covers the in-between window where the host is
            # late but not yet written off
            n_excluded += 1
            continue
        if stale:
            n_stale += 1
        foldable.append((snap["t_wall"], snap.get("metrics") or []))
        if isinstance(state, dict):
            for a in state.get("alerts") or []:
                if isinstance(a, dict):
                    alerts.append({**a, "host": snap["host"]})
    hosts.sort(key=lambda h: (h.get("host") or "", h.get("pid") or 0, h["path"]))
    metrics, conflicts = merge_instruments(foldable)
    alerts.sort(key=lambda a: (str(a.get("rule")), str(a.get("host"))))
    return {
        "schema": SNAP_SCHEMA,
        "generated_t": now,
        "hosts": hosts,
        "metrics": metrics,
        "conflicts": conflicts,
        "alerts": alerts,
        "counts": {
            "snapshots": len(entries),
            "folded": len(foldable),
            "stale": n_stale,
            "corrupt": n_corrupt,
            "excluded": n_excluded,
        },
    }


def fold_dir(
    directory: str,
    now: "float | None" = None,
    stale_after_s: "float | None" = None,
    newer_than: "float | None" = None,
) -> dict:
    """``load_snapshots`` + :func:`fold` in one call — the consumer
    entrypoint (``tools/lt_fleet.py``, ``lt top --dir``, the serve
    fleet loop)."""
    return fold(
        load_snapshots(directory),
        now=now,
        stale_after_s=stale_after_s,
        newer_than=newer_than,
    )


def render_prom(view: dict) -> str:
    """Pod view → aggregated Prometheus exposition (format 0.0.4).

    The merged instruments plus the fleet meta-gauges
    (``lt_fleet_hosts`` / ``lt_fleet_stale_hosts`` /
    ``lt_fleet_corrupt_snaps``).  Deterministic: identical views render
    identical bytes (the perf gate's byte-stability check).
    """
    lines: list = []
    counts = view.get("counts", {})
    for name, help_, val in (
        ("lt_fleet_hosts", "snapshots folded into this pod view",
         counts.get("folded", 0)),
        ("lt_fleet_stale_hosts", "hosts past their staleness bound",
         counts.get("stale", 0)),
        ("lt_fleet_corrupt_snaps", "torn/unparseable snapshot files",
         counts.get("corrupt", 0)),
    ):
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt(val)}")
    seen_family: set = set()
    for inst in view.get("metrics", []):
        name, kind = inst["name"], inst["kind"]
        if name not in seen_family:
            seen_family.add(name)
            if inst.get("help"):
                lines.append(f"# HELP {name} {inst['help']}")
            lines.append(f"# TYPE {name} {kind}")
        labels = inst.get("labels") or {}
        if kind == "histogram":
            cum = 0
            for b, c in zip(inst["bounds"], inst["buckets"]):
                cum += c
                le = 'le="%s"' % _fmt(float(b))
                lines.append(f"{name}_bucket{_fmt_labels(labels, le)} {cum}")
            inf = 'le="+Inf"'
            lines.append(
                f"{name}_bucket{_fmt_labels(labels, inf)} {inst['count']}"
            )
            lines.append(f"{name}_sum{_fmt_labels(labels)} {_fmt(inst['sum'])}")
            lines.append(f"{name}_count{_fmt_labels(labels)} {inst['count']}")
        else:
            v = inst.get("value")
            lines.append(
                f"{name}{_fmt_labels(labels)} {_fmt(0.0 if v is None else v)}"
            )
    return "\n".join(lines) + "\n"


def _scalar_key(name: str, labels: "dict | None") -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return name + "{" + inner + "}"


def flatten_scalars(metrics: list) -> dict:
    """Merged instruments → flat ``{key: value}`` scalars for history
    samples: counters/gauges by ``name{labels}``, histograms as their
    ``_sum`` / ``_count`` pair (enough for every rate/burn rule — the
    ring stays compact)."""
    out: dict = {}
    for inst in metrics:
        key = _scalar_key(inst["name"], inst.get("labels"))
        if inst["kind"] == "histogram":
            out[key + "_sum"] = inst["sum"]
            out[key + "_count"] = inst["count"]
        else:
            v = inst.get("value")
            out[key] = 0.0 if v is None else v
    return out


def pod_sample(view: dict, t: "float | None" = None) -> dict:
    """One history-ring sample from a pod view: the timestamp, the host
    health counts, and the flattened scalar metrics the alert engine
    evaluates over."""
    counts = view.get("counts", {})
    return {
        "t": view.get("generated_t", time.time()) if t is None else t,
        "hosts": int(counts.get("folded", 0)),
        "stale_hosts": int(counts.get("stale", 0)),
        "corrupt_snaps": int(counts.get("corrupt", 0)),
        "alerts_firing": len(view.get("alerts", [])),
        "metrics": flatten_scalars(view.get("metrics", [])),
    }
