"""Fleet telemetry publish: periodic per-process snapshots on a shared FS.

A pod of N hosts (or a fleet of N serve replicas) has N event streams, N
``metrics.prom`` files and N loopback ``/debug`` surfaces — useful per
process, useless as a single pane of glass.  This module is the
**publish** quarter of the fleet telemetry plane (publish → aggregate →
history → alerts): every participating process periodically snapshots
its whole observable state — the Prometheus registry, the live
``Run.progress`` / serve stats the host contributes via ``probes``, and
its identity — into ONE atomic JSON file under a shared telemetry
directory::

    <workdir>/telemetry/<host>.<pid>.snap.json

Design rules (the aggregate side depends on every one of them):

* **Atomic tmp + rename** per snapshot (the manifest/blockstore
  first-write-wins discipline): a reader never sees a torn file from a
  healthy publisher; a torn file therefore MEANS a fault (kill mid-write,
  injected) and the aggregator flags it corrupt instead of crashing.
* **Per-process files, zero coordination**: the filename is the
  ``(host, pid)`` identity, so publishers never contend; a restarted
  process overwrites its predecessor's file, and the snapshot's
  ``generation`` (publisher start, ns) lets the aggregator supersede a
  reused pid's stale snap instead of double-counting it.
* **Staleness is the failure signal**: a publisher that dies, wedges, or
  hits an injected ``obs.publish`` fault simply stops refreshing its
  file — the beat is skipped, never the run.  The snapshot carries its
  own ``interval_s`` so the aggregator can derive a per-host staleness
  bound without out-of-band config.
* **Never fail the run**: after the constructor (where an unwritable
  telemetry dir is a config error), no publish attempt ever raises out
  of :meth:`TelemetryPublisher.start`, the loop, or :meth:`stop` —
  failed beats are counted in :meth:`stats` and show up as staleness.

Like the rest of :mod:`land_trendr_tpu.obs` this is stdlib-only and
jax-free; the fault seams reach the active plan through the same
registered-hook pattern as ``io.blockcache`` (``runtime/faults``
registers itself here via :func:`set_fault_plan`, so ``obs/`` never
imports ``runtime/``).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Callable

__all__ = [
    "SNAP_SCHEMA",
    "TelemetryPublisher",
    "fault_check",
    "set_fault_plan",
    "snap_path",
    "telemetry_dir",
]

#: bump when a REQUIRED snapshot field is added/renamed/retyped (the
#: aggregate layer validates it, like the event stream's SCHEMA_VERSION)
SNAP_SCHEMA = 1

# -- fault-seam hook (registered by runtime.faults.activate, like the
# -- io.blockcache hook — obs/ never imports runtime/) --------------------
_fault_plan: "Any | None" = None


def set_fault_plan(plan: "Any | None") -> None:
    """Install/clear the active fault plan for the ``obs.publish`` and
    ``history.append`` seams (called by ``runtime.faults.activate`` /
    ``deactivate``)."""
    global _fault_plan
    _fault_plan = plan


def fault_check(seam: str) -> None:
    """Raising seam against the registered plan (no-op when none is
    active) — shared by this module and :mod:`~land_trendr_tpu.obs.
    history`."""
    plan = _fault_plan
    if plan is not None:
        plan.check(seam)


def telemetry_dir(workdir: str) -> str:
    """Canonical shared telemetry directory under a run/serve workdir."""
    return os.path.join(workdir, "telemetry")


def snap_path(directory: str, host: "str | None" = None, pid: "int | None" = None) -> str:
    """Canonical per-process snapshot path (``<host>.<pid>.snap.json``)."""
    return os.path.join(
        directory,
        f"{host or socket.gethostname()}.{pid or os.getpid()}.snap.json",
    )


class TelemetryPublisher:
    """Daemon thread refreshing one process's fleet snapshot.

    ``registry`` is the process's :class:`~land_trendr_tpu.obs.metrics.
    MetricsRegistry` (dumped via :meth:`~land_trendr_tpu.obs.metrics.
    MetricsRegistry.snapshot`); ``probes`` is an optional host callback
    returning the live JSON-safe state block (``Run.progress``, serve
    queue/SLO facts, active alerts) merged into each snapshot under
    ``"state"`` — a probe failure degrades the snapshot to metrics-only,
    never the run (the flight sampler's contract).

    Publishes once at :meth:`start` (a sub-interval run still leaves a
    snapshot), every ``interval_s`` in between, and once at
    :meth:`stop` (the terminal state is on disk for post-mortem folds).
    Each write goes to a per-``(pid, seq)`` tmp name then ``os.replace``
    — concurrent writers (a wedged loop thread racing the final stop()
    flush) cannot tear each other; last rename wins, which for a
    monotonically-refreshed snapshot is the right answer.
    """

    def __init__(
        self,
        directory: str,
        registry,
        *,
        probes: "Callable[[], dict] | None" = None,
        interval_s: float = 5.0,
        kind: str = "run",
        host: "str | None" = None,
    ) -> None:
        if interval_s <= 0:
            raise ValueError(f"interval_s={interval_s} must be > 0")
        # an unwritable telemetry dir is a CONFIG error surfaced now;
        # everything past construction is best-effort by contract
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.registry = registry
        self.kind = kind
        self.host = host or socket.gethostname()
        self.pid = os.getpid()
        #: supersedes a reused pid: the aggregator keeps the highest
        #: generation per (host, pid), so a restarted process's counters
        #: are never summed with its dead predecessor's
        self.generation = time.time_ns()
        self.path = snap_path(directory, self.host, self.pid)
        self.interval_s = float(interval_s)
        self._probes = probes
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._lock = threading.Lock()
        self._seq = 0
        self._published = 0
        self._failed = 0
        self._t0 = time.time()

    # -- snapshot assembly -------------------------------------------------
    def snapshot_fields(self) -> dict:
        """One snapshot's payload (probe failures degrade, never raise)."""
        state: dict = {}
        if self._probes is not None:
            try:
                probed = self._probes()
                if isinstance(probed, dict):
                    state = probed
            except Exception:
                pass  # a sick probe degrades the snapshot, not the run
        now = time.time()
        with self._lock:
            self._seq += 1
            seq = self._seq
        return {
            "schema": SNAP_SCHEMA,
            "kind": self.kind,
            "host": self.host,
            "pid": self.pid,
            "generation": self.generation,
            "seq": seq,
            "t_wall": now,
            "uptime_s": round(now - self._t0, 3),
            "interval_s": self.interval_s,
            "metrics": self.registry.snapshot(),
            "state": state,
        }

    def publish_now(self) -> dict:
        """Write one snapshot NOW (atomic tmp + rename); returns the
        record written.  Raises on I/O failure or an armed
        ``obs.publish`` fault — loop/stop callers swallow (a skipped
        beat is staleness, the aggregate-side contract), while tests
        and the perf gate call this directly."""
        fault_check("obs.publish")
        rec = self.snapshot_fields()
        data = json.dumps(rec, separators=(",", ":"), default=str)
        # per-(pid, seq) tmp name: the loop thread and a final stop()
        # flush can never share (and tear) one tmp file — no lock spans
        # the write, the rename race resolves last-writer-wins
        tmp = f"{self.path}.{self.pid}.{rec['seq']}.tmp"
        try:
            with open(tmp, "w") as f:
                f.write(data)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with self._lock:
            self._published += 1
        return rec

    def _publish_best_effort(self) -> None:
        try:
            self.publish_now()
        except Exception:
            # injected obs.publish fault, transient FS pressure, full
            # disk: the beat is skipped and the host ages toward stale —
            # the publisher must never take down the run it describes
            with self._lock:
                self._failed += 1

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "TelemetryPublisher":
        self._publish_best_effort()
        self._thread = threading.Thread(
            target=self._loop, name="lt-fleet-publisher", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._publish_best_effort()

    def stop(self) -> None:
        """Stop the loop and flush the terminal snapshot (best-effort —
        the final state matters most on the abort path, where a publish
        error must not mask the propagating failure)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._publish_best_effort()

    def stats(self) -> dict:
        with self._lock:
            return {
                "seq": self._seq,
                "published": self._published,
                "failed": self._failed,
                "path": self.path,
            }
