"""Structured run-event log: a schema-versioned JSONL stream per process.

The reference's observability is Hadoop job counters plus task logs; the
TPU-native driver previously emitted free-form ``log.info`` lines and one
end-of-run summary dict — unusable for watching a gigapixel run in flight
or for regression-tracking a scaling PR.  This module is the event half of
the :mod:`land_trendr_tpu.obs` subsystem: every run writes an append-only
``events.jsonl`` (one file *per process* in multihost runs —
``events.p<i>.jsonl`` — so no cross-process write coordination is ever
needed; the primary merges post-hoc via
:func:`land_trendr_tpu.parallel.multihost.merge_host_event_logs`).

Design rules:

* **One JSON object per line**, schema-versioned via the ``schema`` field
  on every ``run_start`` event.  Consumers (``tools/obs_report.py``,
  ``tools/check_events_schema.py``) validate against
  :data:`EVENT_FIELDS` — required fields are a *minimum*; extra fields are
  always allowed, so instrumentation can grow without a schema bump.
* **Every event carries both clocks**: ``t_wall`` (``time.time()`` — joins
  across processes and with external logs) and ``t_mono``
  (``time.perf_counter()`` — duration-accurate within one process).  The
  trace exporter anchors each run scope's monotonic clock to its
  ``run_start`` wall time, so multihost timelines line up.
* **Atomic thread-safe append**: one ``os.write`` of the whole line to an
  ``O_APPEND`` descriptor under a lock, so the driver's ``write_workers``
  pool, the feed pool, and the main loop can all emit without interleaving
  bytes.  A resumed run appends a fresh ``run_start`` to the same file;
  each ``run_start`` opens a new *run scope* for consumers.
* **Never fail the run**: emitting into a full disk raises at the caller —
  deliberate (silently lost telemetry is worse) — but schema problems are
  a consumer-side concern; ``emit`` does not validate.
"""

from __future__ import annotations

import glob
import json
import os
import re
import socket
import threading
import time
import uuid
from typing import Any, Callable, Iterator

__all__ = [
    "SCHEMA_VERSION",
    "COMMON_OPTIONAL_FIELDS",
    "EVENT_FIELDS",
    "OPTIONAL_FIELDS",
    "REQUEST_SPAN_STAGES",
    "EventLog",
    "events_path",
    "discover_event_files",
    "expand_event_paths",
    "iter_events",
    "run_scope_reset",
    "summarize_events_file",
    "validate_event",
    "validate_events_file",
]

#: bump when a REQUIRED field is added/renamed/retyped; adding optional
#: fields is backward-compatible and does not bump the version
SCHEMA_VERSION = 1

_NUM = (int, float)

#: event type → required payload fields (beyond the common ``ev`` /
#: ``t_wall`` / ``t_mono`` every event carries) and their types.  This is
#: the normative schema ``tools/check_events_schema.py`` lints against.
EVENT_FIELDS: dict[str, dict[str, Any]] = {
    # run lifecycle — first event of every run scope
    "run_start": {
        "schema": int,
        "fingerprint": str,
        "pid": int,
        "host": str,
        "process_index": int,
        "process_count": int,
        "tiles_total": int,
        "tiles_todo": int,
        "tiles_skipped_resume": int,
        "mesh_devices": int,
        "impl": str,
    },
    # a tile's device program was dispatched (attempt 1) or re-dispatched
    "tile_start": {"tile_id": int, "attempt": int},
    # one per-tile pipeline-stage span (obs/spans.py): ``start``/``end``
    # are monotonic-clock values (the same clock as ``t_mono``), sampled
    # at the stage boundary and emitted as ONE event at span end — so a
    # span can never be torn across lines.  ``name`` is a stage from
    # spans.SPAN_STAGES (the vocabulary is open: unknown names still
    # validate, consumers group by name).  Emitted from the driver
    # thread only, so spans always precede their scope's run_done.
    # Additive event type, introduced without a schema bump.
    "span": {"name": str, "tile_id": int, "start": _NUM, "end": _NUM},
    # live straggler verdict (obs/spans.StragglerDetector): this tile's
    # in-flight duration exceeded k x the rolling median of recent tile
    # durations.  duration_s >= threshold_s by construction (the value
    # lint in tools/check_events_schema.py pins it); in_flight=true
    # means the tile was still running when flagged (sampler scan) vs
    # flagged at completion.  Additive event type.
    "tile_straggler": {
        "tile_id": int,
        "duration_s": _NUM,
        "threshold_s": _NUM,
        "median_s": _NUM,
    },
    # --- elastic pod scheduling (runtime/leases) -------------------------
    # this host claimed a never-leased (or cleanly released) tile from
    # the shared-manifest lease queue at generation ``gen``.  Additive
    # event type, introduced without a schema bump.
    "tile_leased": {"tile_id": int, "gen": int},
    # this host STOLE a tile whose lease expired (dead/wedged peer) —
    # gen is the successor generation the steal claimed (>= 1 by
    # construction; the value lint pins it).  Additive.
    "lease_stolen": {"tile_id": int, "gen": int},
    # this host speculatively re-leased a straggler-flagged tile still
    # in flight on its owner: first durable write wins, the loser's
    # write lands as an identical no-op.  gen >= 1 like a steal.
    # Additive.
    "tile_speculated": {"tile_id": int, "gen": int},
    # the tile's result is ready on host (dispatch + device wait)
    "tile_done": {
        "tile_id": int,
        "px": int,
        "compute_s": _NUM,
        "px_per_s": _NUM,
        "feed_backlog": int,
        "write_backlog": int,
    },
    "tile_retry": {"tile_id": int, "attempt": int, "error": str},
    "tile_failed": {"tile_id": int, "attempts": int, "error": str},
    # a tile exhausted its retry budget under --quarantine-tiles: the run
    # continues without it (the manifest records it kind="tile_failed";
    # resume re-attempts it).  Always follows a tile_failed for the tile.
    "tile_quarantined": {"tile_id": int, "attempts": int, "error": str},
    # the deterministic fault injector (runtime/faults) fired a scheduled
    # fault: seam name, per-seam invocation index, error kind.  Emitted
    # only on injection runs — production streams never carry it.
    "fault_injected": {"seam": str, "index": int, "error": str},
    # the stall watchdog saw no tile progress for stall_timeout_s and is
    # aborting the run (exit code 4 via the CLI); idle_s is the observed
    # progress gap at the moment the watchdog fired
    "stall": {"idle_s": _NUM, "timeout_s": _NUM},
    # graceful degradation: repeated packed-fetch failures demoted the
    # device→host path to per-product synchronous transfers for the rest
    # of the run (artifacts are byte-identical either way)
    "fetch_demoted": {"failures": int},
    # the tile's artifact + manifest line are durable (emitted by
    # TileManifest.record, i.e. from a writer-pool thread)
    "write_done": {"tile_id": int, "bytes": int, "record_s": _NUM},
    # feed-path decode subsystem rollup (io/blockcache): one terminal
    # event per run scope with the counters accumulated over that run —
    # cache effectiveness, decode wall seconds (summed across threads),
    # and readahead effectiveness.  Additive event type: introduced
    # without a schema bump (older consumers flag it unknown; required
    # fields of EXISTING types are unchanged).
    "feed_cache": {
        "hits": int,
        "misses": int,
        "evictions": int,
        "decode_s": _NUM,
    },
    # device→host fetch subsystem rollup (runtime/fetch): one terminal
    # event per run scope — transfer counts (packed = 1 per tile), wire
    # bytes, and the pack/wait/unpack second split.  Additive event type,
    # introduced without a schema bump (like feed_cache).
    "fetch": {
        "tiles": int,
        "transfers": int,
        "bytes": int,
        "pack_s": _NUM,
        "wait_s": _NUM,
        "unpack_s": _NUM,
    },
    # host→device upload subsystem rollup (runtime/feed): one terminal
    # event per run scope — transfer counts (packed = 1 per tile), wire
    # bytes, and the host-pack / landing-wait / device-unpack second
    # split.  Additive event type, introduced without a schema bump.
    "upload": {
        "tiles": int,
        "transfers": int,
        "bytes": int,
        "pack_s": _NUM,
        "wait_s": _NUM,
        "unpack_s": _NUM,
    },
    # graceful degradation: repeated packed-upload failures demoted the
    # host→device path to per-array sync dispatch for the rest of the
    # run (artifacts are byte-identical either way)
    "upload_demoted": {"failures": int},
    # persistent ingest-store rollup (io/blockstore): one terminal event
    # per run scope on store-enabled runs — store tier effectiveness
    # (hits avoid TIFF decode entirely) and ingest volume.  Additive.
    "ingest_store": {
        "hits": int,
        "misses": int,
        "put_blocks": int,
        "put_bytes": int,
    },
    "run_done": {
        "status": str,  # "ok" | "aborted"
        "tiles_done": int,
        "pixels": int,
        "wall_s": _NUM,
        "px_per_s": _NUM,
        "fit_rate": _NUM,
    },
    # --- segmentation-as-a-service events (land_trendr_tpu/serve) -------
    # a job passed admission control and entered the queue (server scope)
    "job_submitted": {
        "job_id": str,
        "tenant": str,
        "priority": int,
        "queue_depth": int,
    },
    # the dispatcher picked the job up; wait_s is its queue wait
    "job_start": {"job_id": str, "tenant": str, "wait_s": _NUM},
    # terminal job state (done / config_error / retries_exhausted /
    # stalled / cancelled / error — README §Service mode maps these onto
    # the CLI exit-code contract); wall_s is submit→terminal
    "job_done": {"job_id": str, "status": str, "wall_s": _NUM},
    # admission control refused a submission (429-style: queue full,
    # tenant cap) or the submission itself failed validation
    "job_rejected": {"reason": str, "queue_depth": int},
    # warm program cache verdict: one per run scope in serve mode (a
    # MISS paid compile_s compiling the run's programs against a dummy
    # tile; a HIT ran zero compiles), plus a server-scope aggregate at
    # shutdown.  Additive event type, like the subsystem rollups above.
    "program_cache": {"hits": int, "misses": int, "compile_s": _NUM},
    # --- flight recorder / live debug surface (obs/flight) --------------
    # periodic resource sample from the flight sampler thread: process
    # vitals required, host-contributed gauges (queue depths, backlogs,
    # cache/store occupancy, HBM watermark) optional.  Emitted through
    # the normal event log, so it lands in the stream, the flight ring,
    # and the obs_report trace counter tracks alike.  Additive.
    "flight_sample": {"rss_bytes": int, "open_fds": int, "threads": int},
    # one on-demand profiler capture attempt (POST /debug/profile): a
    # FAILED capture carries ok=false + error — the capture fails, the
    # job and the server do not.  Additive.
    "profile_captured": {"ok": bool, "duration_s": _NUM, "path": str},
    # per-job SLO accounting (serve): the latency split (queue wait vs
    # execution) and the deadline verdict for one terminal job.  A
    # deadline miss is ACCOUNTING, never enforcement — the job ran to
    # its natural terminal state (job_timeout_s is the enforcement
    # knob).  Additive.
    "job_slo": {
        "job_id": str,
        "tenant": str,
        "queue_wait_s": _NUM,
        "exec_s": _NUM,
        "latency_s": _NUM,
        "met": bool,
    },
    # --- fleet telemetry plane (obs/publish + aggregate + history +
    # --- alerts) --------------------------------------------------------
    # one alert-rule lifecycle transition (obs/alerts.AlertEngine over
    # the aggregated history ring): state is "firing" | "resolved" (the
    # value lint pins the enum AND firing-before-resolved ordering per
    # rule within a run scope).  duration_s is how long the condition
    # held before firing / how long the alert was firing before it
    # resolved — >= 0 by construction.  Additive event type.
    "alert": {
        "rule": str,
        "state": str,
        "value": _NUM,
        "threshold": _NUM,
        "duration_s": _NUM,
    },
    # one fleet-loop beat: the pod fold's host-health counts (the same
    # numbers the lt_fleet_* meta-gauges and the history-ring sample
    # carry), emitted through the server's event log so the pod's
    # health timeline rides the normal stream.  Additive event type.
    "fleet_sample": {"hosts": int, "stale_hosts": int},
    # --- serving fleet router (land_trendr_tpu/fleet) --------------------
    # the router forwarded one job to a replica: ``warm`` is true when
    # the choice was affinity-driven (the replica's warm/sticky key set
    # contained the job's affinity key), false for the least-loaded
    # fallback.  Emitted once per SUCCESSFUL forward; the optional
    # ``attempt`` (>= 1) counts every forward TRY, so a job whose first
    # forward failed lands with one route_decision carrying attempt=2.
    # Additive event type.
    "route_decision": {
        "job_id": str,
        "tenant": str,
        "replica": str,
        "warm": bool,
    },
    # a replica joined the routable pool (spawned or adopted, or
    # recovered from unready).  Additive.
    "replica_up": {"replica": str},
    # a replica left the routable pool: ``reason`` is "health" (probe
    # failures), "dead" (spawned process exited), "scale_down" (drained
    # by the autoscaler) or "shutdown".  Its accepted jobs are NOT
    # failed — they re-route or keep polling.  Additive.
    "replica_down": {"replica": str, "reason": str},
    # router admission refused a submission with 429 + Retry-After:
    # ``reason`` is "tenant_quota" (per-tenant queued+routed bound) or
    # "queue_full" (router-wide queue bound).  Additive.
    "tenant_throttled": {"tenant": str, "reason": str, "queue_depth": int},
    # one autoscaler action: ``direction`` is "up" | "down", ``burn``
    # the pod burn-rate that drove it, ``replicas`` the pool size AFTER
    # the action was initiated.  Additive.
    "scale_decision": {"direction": str, "burn": _NUM, "replicas": int},
    # --- autotuned execution profiles (land_trendr_tpu/tune) -------------
    # one knob-group calibration probe: ``ok=false`` means the group's
    # probe failed (the tune.probe fault seam or a real error) and was
    # SKIPPED — its knobs fell back to defaults; ``probes`` counts the
    # timed candidate reps the group ran (0 on a skipped group; >= 1 on
    # a succeeded one — the value lint pins it).  Additive event type.
    "tune_probe": {"group": str, "ok": bool, "probes": int, "wall_s": _NUM},
    # one profile verdict: ``source`` is "store" (reloaded on sight —
    # probes is 0 BY DEFINITION, the value lint pins it), "probed" (a
    # key miss or --retune ran the probes) or "defaults" (no store / no
    # profile for the key: the hardcoded knobs, byte-identical
    # behavior).  ``key`` is the store key
    # "device_kind|backend|shape_class" ("" for defaults).  Emitted by
    # `lt tune` and by every Run whose config resolved "auto" knobs.
    # Additive event type.
    "tune_profile": {"key": str, "source": str, "probes": int},
    # --- end-to-end request tracing (obs/reqtrace) -----------------------
    # one router-side segment of a request's journey: ``name`` is a
    # stage from REQUEST_SPAN_STAGES (open vocabulary, like ``span``),
    # ``start``/``end`` are monotonic-clock values on the emitting
    # scope's anchor clock (the ``span`` convention), and ``trace_id``
    # is the request correlation id minted at router (or serve)
    # admission.  A ``forward`` span is ONE hop: it carries the target
    # ``replica``, the ``attempt`` ordinal, and ``ok`` (a failed
    # forward is a span too — the re-route story needs both hops).
    # Additive event type.
    "request_span": {"trace_id": str, "name": str, "start": _NUM, "end": _NUM},
    # the request's terminal record at the router: the router-observed
    # end-to-end ``latency_s`` (admission to terminal) and the
    # router-side ``blame`` split — a consecutive partition of that
    # latency (route_queue / throttle_backoff / forward / replica), so
    # the components SUM to ``latency_s`` by construction (the value
    # lint pins it).  ``hops`` counts forward attempts (>= 2 means the
    # request was re-routed).  Additive event type.
    "request_done": {"trace_id": str, "status": str, "latency_s": _NUM},
    # --- fleet-scale load harness + capacity planner (loadgen/,
    # --- fleet/capacity) -------------------------------------------------
    # one load-rig phase transition: ``phase`` names the schedule
    # segment (free-form — "warmup" / "steady" / "wave" / "drain" /
    # "sweep@<qps>"...), ``mode`` is the arrival process ("open" =
    # offered-rate Poisson, "closed" = fixed-concurrency).  The offered
    # rate rides as the OPTIONAL ``offered_qps`` (strictly positive
    # when present — the value lint pins it): a closed-loop phase has
    # no offered rate by definition, only achieved throughput.
    # Additive event type.
    "load_phase": {"phase": str, "mode": str},
    # one capacity sweep point: a fixed ``replicas`` count driven at
    # ``offered_qps`` for one window, folded to the achieved rate, the
    # latency quantiles (p99 >= p50 — the value lint pins it), and
    # ``goodput_qps`` (terminal ``done`` per second — rejected and
    # failed submissions are throughput, not goodput).  The OPTIONAL
    # ``knee`` marks the detected knee of this replica count's curve
    # and ``knee_blame`` names the dominant assembled blame component
    # there (∈ the PR-15 blame vocabulary + "other").  Additive.
    "sweep_point": {
        "replicas": int,
        "offered_qps": _NUM,
        "achieved_qps": _NUM,
        "p50_s": _NUM,
        "p99_s": _NUM,
        "goodput_qps": _NUM,
        "done": int,
        "failed": int,
        "rejected": int,
    },
    # one offline replay of a recorded decision log
    # (fleet/capacity.replay_decisions): ``decisions`` recorded,
    # ``matched`` reproduced byte-identically (``match`` ⇔ all of them
    # — the value lint pins the implication), and ``speedup_x`` =
    # recorded wall span / replay wall.  Additive event type.
    "sim_replay": {
        "decisions": int,
        "matched": int,
        "match": bool,
        "speedup_x": _NUM,
    },
    # --- cross-job continuous batching (serve/batching) ------------------
    # one coalesced device launch: ``jobs`` same-affinity member jobs
    # (>= 1 — a degenerate batch of one is today's path) whose tile
    # union (``tiles`` >= ``jobs`` when every member has work — the
    # value lint pins tiles >= jobs >= 1) runs through ONE warm
    # pipeline.  Stamped with the LEADER's job_id/trace_id; the optional
    # occupancy (useful px / padded px, 0 < occupancy <= 1 — pinned) and
    # window_wait_s (time spent holding the batch window open) carry the
    # packing efficiency story.  Additive event type.
    "batch_launch": {"jobs": int, "tiles": int},
    # batched results demuxed back to ONE member's manifest: ``tiles``
    # durable tile artifacts this member received from the shared launch
    # (byte-identical to a solo run's writes).  Stamped with the
    # MEMBER's job_id/trace_id, so PR-15 blame attribution still
    # partitions each request exactly.  Additive event type.
    "batch_demux": {"tiles": int},
    # --- crash-safe control plane (fleet/journal) ------------------------
    # one durably committed admission-journal record: ``rec`` is the
    # record kind (∈ journal.RECORD_KINDS — "admitted" / "forwarded" /
    # "terminal"), ``segment`` the 1-based segment it landed in and
    # ``bytes`` the committed line size (both >= 1 — the value lint
    # pins them).  Emitted AFTER the os.write returns: an append the
    # seam or the disk failed never produces this event (the 503
    # ``journal_error`` rejection does not either — the job was never
    # admitted).  Additive event type.
    "journal_append": {"rec": str, "segment": int, "bytes": int},
    # one router restart's recovery summary: ``replayed`` non-terminal
    # jobs rebuilt from the journal, split into ``relayed`` (replica
    # finished during the outage — result relayed from its terminal
    # snapshot), ``requeued`` (replica gone — re-enqueued front-of-line
    # with resume semantics) and the optional ``reattached`` (replica
    # still running the job — polling resumed); the split sums to
    # ``replayed`` (the value lint pins relayed + requeued [+
    # reattached] <= replayed).  ``deduped`` counts idempotency keys
    # restored to the dedupe table, ``clean`` whether the previous
    # process wrote the clean-shutdown marker (probes skipped).
    # Additive event type.
    "router_recovered": {
        "replayed": int,
        "relayed": int,
        "requeued": int,
        "deduped": int,
        "recovery_s": _NUM,
        "clean": bool,
    },
}

#: the request-span stage vocabulary, in journey order (open like
#: SPAN_STAGES — unknown names still validate; consumers group by name)
REQUEST_SPAN_STAGES = ("route_queue", "throttle_backoff", "forward", "relay")

#: well-known OPTIONAL fields: type-checked when present, never required
OPTIONAL_FIELDS: dict[str, dict[str, Any]] = {
    # run identity + the scope's clock anchor: ``run_id`` names the run
    # scope pod-wide (correlation ID on every assembled span), and the
    # ``(anchor_wall, anchor_mono)`` pair — sampled TOGETHER by
    # EventLog.run_start — is what the pod-trace assembler
    # (obs/spans.assemble_pod_trace) aligns cross-host clocks with.
    # Optional so pre-anchor streams keep validating (consumers fall
    # back to the record's own t_wall/t_mono).
    "run_start": {"run_id": str, "anchor_wall": _NUM, "anchor_mono": _NUM},
    "span": {"attempt": int},
    "tile_straggler": {"in_flight": bool, "attempt": int},
    "tile_done": {"device_bytes_in_use": _NUM, "fetch_backlog": int},
    # no px_per_s here: the manifest meta's rate is over PADDED tile
    # pixels; tile_done's real-pixel px_per_s is the stream's one
    # throughput number (extra fields still validate — see module doc)
    "write_done": {"no_fit_rate": _NUM},
    "feed_cache": {
        "inserted_bytes": int,
        "readahead_blocks": int,
        "readahead_hits": int,
        "readahead_dropped": int,
        "cache_bytes": int,
        "budget_bytes": int,
        "corrupt_dropped": int,
    },
    "fetch": {"packed": bool, "backlog_max": int, "demoted": bool},
    "upload": {"packed": bool, "backlog_max": int, "demoted": bool},
    "ingest_store": {
        "stale_dropped": int,
        "corrupt_dropped": int,
        "evicted_segments": int,
        "bytes": int,
        "budget_bytes": int,
        "segments": int,
    },
    "run_done": {
        "stage_s": dict,
        "tiles_quarantined": int,
        # elastic scheduling rollups (lease runs only): tiles this host
        # STOLE from expired leases / ran speculatively
        "tiles_stolen": int,
        "tiles_speculated": int,
    },
    "tile_leased": {"owner": str},
    "lease_stolen": {"owner": str, "from_owner": str},
    "tile_speculated": {"owner": str, "from_owner": str},
    "job_submitted": {"source": str},
    "job_done": {"tiles_quarantined": int, "error": str},
    "job_rejected": {"job_id": str, "tenant": str},
    "program_cache": {"keys": int},
    "flight_sample": {
        "feed_backlog": int,
        "write_backlog": int,
        "fetch_backlog": int,
        "upload_backlog": int,
        "queue_depth": int,
        "running": int,
        "jobs_total": int,
        "warm_program_count": int,
        "cache_bytes": int,
        "store_bytes": int,
        "device_bytes_in_use": _NUM,
        "stragglers": int,
        "tiles_stolen": int,
        "tiles_speculated": int,
        # cross-job batching live state (the running leader's progress)
        "batch_jobs": int,
        "batch_tiles": int,
        "batch_occupancy": _NUM,
    },
    "profile_captured": {"error": str, "bytes": int},
    "job_slo": {"deadline_s": _NUM},
    "alert": {"window_s": _NUM},
    "fleet_sample": {
        "corrupt_snaps": int,
        "alerts_firing": int,
        "history_samples": int,
    },
    "route_decision": {
        "key": str,
        "attempt": int,
        "queue_wait_s": _NUM,
        "queue_depth": int,
    },
    "replica_up": {"base": str, "spawned": bool},
    "replica_down": {"base": str, "inflight": int},
    "scale_decision": {"replica": str, "queue_depth": int},
    "tune_probe": {"speedup": _NUM, "error": str, "knobs": dict},
    "tune_profile": {"age_s": _NUM, "knobs": dict, "groups": int},
    "request_span": {"replica": str, "attempt": int, "tenant": str, "ok": bool},
    "request_done": {"tenant": str, "hops": int, "blame": dict},
    "load_phase": {
        "offered_qps": _NUM,
        "requests": int,
        "workers": int,
        "duration_s": _NUM,
        "seed": int,
    },
    "sweep_point": {
        "knee": bool,
        "knee_blame": str,
        "window_s": _NUM,
        "assembled": int,
    },
    "sim_replay": {
        "recorded_span_s": _NUM,
        "replay_wall_s": _NUM,
        "mismatch_seq": int,
    },
    "batch_launch": {
        "padded_px": int,
        "occupancy": _NUM,
        "window_wait_s": _NUM,
    },
    "batch_demux": {"member_jobs": int},
    "router_recovered": {"reattached": int},
}

#: fields optional on EVERY event type — request-scoped threading the
#: serve layer stamps onto a whole run scope (``EventLog`` common
#: fields), so any tile/write/rollup event can be attributed to the job
#: that caused it.  ``trace_id`` is ``job_id``'s cross-layer sibling:
#: minted once at router (or serve) admission and carried through the
#: forward payload into the job's run scope, so router spans, serve
#: lifecycle events, and per-tile run events all join on one id.
#: Type-checked when present, never required.
COMMON_OPTIONAL_FIELDS: dict[str, Any] = {"job_id": str, "trace_id": str}


def events_path(workdir: str, process_index: int = 0, process_count: int = 1) -> str:
    """Canonical per-process event-log path under a run's workdir.

    Single-process runs write ``events.jsonl``; multihost runs write one
    file per process (``events.p<i>.jsonl``) into the shared workdir so
    appends never cross processes — the same per-host-output pattern the
    tile manifest's artifact writes use.
    """
    if process_count <= 1:
        return os.path.join(workdir, "events.jsonl")
    return os.path.join(workdir, f"events.p{process_index}.jsonl")


def _declared_process_count(p0_path: str) -> int | None:
    """The pod shape the latest run scope of ``events.p0.jsonl`` declares.

    ``run_start`` lines are rare (one per scope), so a forward filter scan
    is cheap relative to the full read every post-hoc consumer does
    anyway; any parse problem returns ``None`` (caller keeps everything).
    """
    last = None
    try:
        with open(p0_path) as f:
            for line in f:
                if '"ev":"run_start"' in line:
                    last = line
        if last is None:
            return None
        n = json.loads(last).get("process_count")
        return n if isinstance(n, int) and n > 0 else None
    except (OSError, json.JSONDecodeError):
        return None


def discover_event_files(
    workdir: str, process_count: int | None = None
) -> list[str]:
    """The event files that constitute a workdir's (latest) run.

    The one file-discovery contract every consumer shares (the multihost
    merge, ``tools/obs_report.py``, ``tools/check_events_schema.py``):
    when per-process pod files (``events.p<i>.jsonl``) exist they ARE the
    run, in process order — a bare ``events.jsonl`` alongside them is a
    stale single-process leftover in a reused workdir, not a host.
    Raises ``FileNotFoundError`` when the workdir has no event files.

    With ``process_count`` (a caller that KNOWS the run shape, like the
    pod primary's merge), only that shape's files are returned: leftover
    ``events.p2.jsonl``/``events.p3.jsonl`` from a previous 4-host run of
    a workdir now reused by 2 hosts are dead streams, not hosts.
    Without it, the shape is recovered from the stream itself — process 0
    always exists, and its latest ``run_start`` declares the current
    pod's ``process_count``, so the same leftovers are excluded for the
    post-hoc consumers too (unparseable p0 = keep everything, best
    effort).  When BOTH namings exist the more recently written set
    wins — the reuse could have gone in either direction.
    """
    if process_count is not None:
        expected = [
            events_path(workdir, i, process_count)
            for i in range(process_count)
        ]
        found = [p for p in expected if os.path.exists(p)]
        if not found:
            raise FileNotFoundError(
                f"no events files for a {process_count}-process run "
                f"under {workdir}"
            )
        return found
    pod = glob.glob(os.path.join(workdir, "events.p*.jsonl"))
    if pod:
        def pidx(p: str) -> int:
            m = re.search(r"events\.p(\d+)\.jsonl$", p)
            return int(m.group(1)) if m else -1
        pod = sorted(pod, key=pidx)
        shape = _declared_process_count(os.path.join(workdir, "events.p0.jsonl"))
        if shape is not None:
            pod = [p for p in pod if 0 <= pidx(p) < shape]
    single = os.path.join(workdir, "events.jsonl")
    has_single = os.path.exists(single)
    if pod and has_single:
        newest_pod = max(os.path.getmtime(p) for p in pod)
        return pod if newest_pod >= os.path.getmtime(single) else [single]
    if pod:
        return pod
    if has_single:
        return [single]
    raise FileNotFoundError(f"no events*.jsonl under {workdir}")


def expand_event_paths(paths: list[str]) -> list[str]:
    """CLI arguments → event files: the expansion both tools share.

    Each path is an event file OR a workdir (expanded via
    :func:`discover_event_files`, so stale files in a reused/resized
    workdir are excluded identically everywhere).  Raises
    ``FileNotFoundError`` for a missing file or an event-less workdir —
    callers turn that into their clean exit-2 path.  Lives here so
    ``obs_report`` and ``check_events_schema`` cannot drift on which
    files constitute a run.
    """
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(discover_event_files(p))
        elif os.path.exists(p):
            out.append(p)
        else:
            raise FileNotFoundError(f"{p} does not exist")
    return out


class EventLog:
    """Append-only JSONL event stream with atomic thread-safe writes.

    Each :meth:`emit` serialises one event to a single ``os.write`` on an
    ``O_APPEND`` descriptor (atomic for regular files) under a lock, so
    concurrent emitters — the driver loop, the feed pool, the writer pool —
    can never interleave partial lines.  Timestamps are stamped here, not
    by callers, so every event's two clocks are sampled together.
    """

    def __init__(
        self,
        path: str,
        common: "dict[str, Any] | None" = None,
        mirror: "Callable[[dict], None] | None" = None,
    ) -> None:
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fd: int | None = os.open(
            path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644
        )
        self._lock = threading.Lock()
        #: fields stamped onto EVERY event of this log (request-scoped
        #: threading — e.g. ``{"job_id": ...}`` in serve mode); explicit
        #: per-emit fields win on collision
        self._common = dict(common or {})
        #: optional per-record tap — the flight recorder's ring
        #: (:meth:`land_trendr_tpu.obs.flight.FlightRecorder.record`):
        #: called with the full stamped record AFTER the durable write,
        #: outside the write lock (the ring has its own, cheaper one)
        self._mirror = mirror

    def emit(self, ev: str, **fields: Any) -> dict:
        """Append one event line; returns the record as written."""
        rec = {
            "ev": ev,
            "t_wall": time.time(),
            "t_mono": time.perf_counter(),
            **self._common,
            **fields,
        }
        data = (json.dumps(rec, separators=(",", ":"), default=str) + "\n").encode()
        with self._lock:
            if self._fd is None:
                raise ValueError(f"EventLog {self.path} is closed")
            n = os.write(self._fd, data)
            if n != len(data):
                # a short write (ENOSPC reached mid-line) tears the line;
                # the contract is raise-at-caller, never silent loss
                raise OSError(
                    f"short write to {self.path}: {n}/{len(data)} bytes"
                )
        if self._mirror is not None:
            self._mirror(rec)
        return rec

    def run_start(self, **fields: Any) -> dict:
        """``run_start`` with the ambient process facts filled in.

        Beyond the process identity, this stamps the scope's tracing
        correlation facts: a fresh ``run_id`` (names the scope pod-wide)
        and the ``(anchor_wall, anchor_mono)`` clock-anchor pair,
        sampled back to back HERE so the pair is as atomic as two clock
        reads get — the pod-trace assembler maps every event's ``t_mono``
        through this anchor, so pairing skew would become trace skew.
        """
        fields.setdefault("schema", SCHEMA_VERSION)
        fields.setdefault("pid", os.getpid())
        fields.setdefault("host", socket.gethostname())
        fields.setdefault("run_id", uuid.uuid4().hex[:12])
        has_wall = "anchor_wall" in fields
        has_mono = "anchor_mono" in fields
        if has_wall != has_mono:
            # half a pair is worse than none: pairing an explicit anchor
            # with a clock read taken NOW would silently shift every
            # assembled span by the gap between the two instants
            raise ValueError(
                "run_start needs anchor_wall and anchor_mono together "
                "(they are one atomically-sampled pair) or neither — got "
                f"only anchor_{'wall' if has_wall else 'mono'}"
            )
        if not has_wall:
            fields["anchor_wall"] = time.time()
            fields["anchor_mono"] = time.perf_counter()
        return self.emit("run_start", **fields)

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def run_scope_reset(rec: Any, default_process_index: "int | None" = None) -> dict:
    """The per-file aggregate fields a ``run_start`` record resets.

    Every consumer that folds a per-process event file scope-by-scope —
    :func:`summarize_events_file` here and ``tools/obs_report.fold`` —
    must reset the same identity + terminal fields when a new run scope
    opens, or a resumed file's earlier scope leaks into the rollup
    (previous ``run_done`` status surviving a fresh ``run_start`` was the
    exact hand-rolled-copy drift this primitive removes).  Identity
    fields come from the ``run_start`` record; terminal fields reset to
    ``None`` until the scope's own ``run_done`` arrives.
    """
    get = rec.get if isinstance(rec, dict) else (lambda *_: None)
    return {
        "process_index": get("process_index", default_process_index),
        "host": get("host"),
        "pid": get("pid"),
        "run_id": get("run_id"),
        "status": None,
        "wall_s": None,
        "px_per_s": None,
    }


def summarize_events_file(path: str) -> dict:
    """Fold one per-process event file into its LAST run scope's aggregate.

    The per-host rollup the multihost primary folds into the run summary
    (:func:`land_trendr_tpu.parallel.multihost.merge_host_event_logs`).
    A resumed run appends a fresh ``run_start`` to the same file, so
    counters reset at every ``run_start`` — the summary describes the most
    recent run, which is the one the merging driver is part of.  Malformed
    lines are counted, not fatal: a crashed peer's torn final line must
    not take down the primary's summary.  Lives here, next to
    :data:`EVENT_FIELDS`, so the schema knowledge stays in one module.
    """
    agg: dict = {
        "events_file": path,
        "process_index": None,
        "host": None,
        "pid": None,
        "run_id": None,
        "tiles_done": 0,
        "tile_retries": 0,
        "tiles_failed": 0,
        "tiles_quarantined": 0,
        "stragglers": 0,
        "pixels": 0,
        "wall_s": None,
        "px_per_s": None,
        "status": None,
        "malformed_lines": 0,
    }
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                agg["malformed_lines"] += 1
                continue
            ev = rec.get("ev")
            if ev == "run_start":
                agg.update(
                    run_scope_reset(rec),
                    tiles_done=0,
                    tile_retries=0,
                    tiles_failed=0,
                    tiles_quarantined=0,
                    stragglers=0,
                    pixels=0,
                    # the torn final line of a crashed PREVIOUS scope must
                    # not flag the healthy resumed scope as corrupt
                    malformed_lines=0,
                )
            elif ev == "tile_done":
                agg["tiles_done"] += 1
                agg["pixels"] += int(rec.get("px", 0))
            elif ev == "tile_retry":
                agg["tile_retries"] += 1
            elif ev == "tile_failed":
                agg["tiles_failed"] += 1
            elif ev == "tile_quarantined":
                agg["tiles_quarantined"] += 1
            elif ev == "tile_straggler":
                agg["stragglers"] += 1
            elif ev == "run_done":
                agg["status"] = rec.get("status")
                agg["wall_s"] = rec.get("wall_s")
                agg["px_per_s"] = rec.get("px_per_s")
    return agg


def iter_events(path: str) -> Iterator[dict]:
    """Yield parsed event records; skips blank lines, raises on bad JSON."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)


def validate_event(rec: Any, lineno: int | None = None) -> list[str]:
    """Schema errors for one record (empty list = valid).

    Required fields are a minimum — unknown extra fields pass, so older
    validators accept newer (compatible) streams.
    """
    where = f"line {lineno}: " if lineno is not None else ""
    if not isinstance(rec, dict):
        return [f"{where}event is not a JSON object: {type(rec).__name__}"]
    errs: list[str] = []
    ev = rec.get("ev")
    if ev not in EVENT_FIELDS:
        return [f"{where}unknown event type {ev!r}"]
    for name in ("t_wall", "t_mono"):
        v = rec.get(name)
        if not isinstance(v, _NUM) or isinstance(v, bool):
            errs.append(f"{where}{ev}: {name} missing or non-numeric ({v!r})")
    for name, typ in EVENT_FIELDS[ev].items():
        if name not in rec:
            errs.append(f"{where}{ev}: missing required field {name!r}")
        elif not isinstance(rec[name], typ) or (
            typ is not bool and isinstance(rec[name], bool)
        ):
            errs.append(
                f"{where}{ev}: field {name!r} has type "
                f"{type(rec[name]).__name__}, expected {typ}"
            )
    optional = {**COMMON_OPTIONAL_FIELDS, **OPTIONAL_FIELDS.get(ev, {})}
    for name, typ in optional.items():
        if name in EVENT_FIELDS[ev]:
            continue  # required wins (e.g. job_submitted.job_id)
        # same bool guard as required fields: isinstance(True, int) holds,
        # but a bool in a numeric field is producer drift, not a number
        if name in rec and (
            not isinstance(rec[name], typ)
            or (typ is not bool and isinstance(rec[name], bool))
        ):
            errs.append(
                f"{where}{ev}: optional field {name!r} has type "
                f"{type(rec[name]).__name__}, expected {typ}"
            )
    if ev == "run_start" and rec.get("schema") not in (None, SCHEMA_VERSION):
        errs.append(
            f"{where}run_start: schema version {rec.get('schema')!r} != "
            f"{SCHEMA_VERSION} (this validator)"
        )
    return errs


def validate_events_file(
    path: str, extra: "Callable[[Any, int], list[str]] | None" = None
) -> list[str]:
    """All schema errors in one JSONL event file (empty list = valid).

    Beyond per-record checks: the first event of the file must be a
    ``run_start`` (every later run scope re-opens with its own), and
    malformed JSON is an error, not a crash.  ``extra`` is an optional
    per-record hook ``(record, lineno) -> errors`` run in the SAME pass —
    how ``tools/check_events_schema.py`` adds its value-level feed_cache
    lint without a second parse of the file, with errors in line order.
    """
    errs: list[str] = []
    first_seen = False
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errs.append(f"line {i}: malformed JSON ({e})")
                continue
            if not first_seen:
                first_seen = True
                if isinstance(rec, dict) and rec.get("ev") != "run_start":
                    errs.append(
                        f"line {i}: first event is {rec.get('ev')!r}, "
                        "expected 'run_start'"
                    )
            errs.extend(validate_event(rec, lineno=i))
            if extra is not None:
                errs.extend(extra(rec, i))
    if not first_seen:
        errs.append("file contains no events")
    return errs
