"""obs subpackage: run-wide telemetry — structured events, metrics, traces.

The observability layer the whole runtime reports through (ROADMAP
"§5 metrics / logging" growth item): a schema-versioned JSONL event
stream per process, a Prometheus-exposition metrics registry with file
and HTTP exporters, and the :class:`Telemetry` bundle the tile driver
wires them up with.  Consumers live in ``tools/obs_report.py`` (per-stage
report + ``chrome://tracing`` export) and ``tools/check_events_schema.py``
(schema lint).  Everything here is stdlib-only — no jax import, no new
dependencies.
"""

from land_trendr_tpu.obs.events import (
    EVENT_FIELDS,
    SCHEMA_VERSION,
    EventLog,
    discover_event_files,
    events_path,
    expand_event_paths,
    iter_events,
    validate_event,
    validate_events_file,
)
from land_trendr_tpu.obs.flight import (
    FlightRecorder,
    ResourceSampler,
    flight_path,
    thread_stacks,
)
from land_trendr_tpu.obs.spans import (
    SPAN_STAGES,
    StragglerDetector,
    assemble_pod_trace,
    critical_path,
)
from land_trendr_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsHTTPServer,
    MetricsRegistry,
    PromFileExporter,
)
from land_trendr_tpu.obs.aggregate import (
    fold_dir,
    merge_instruments,
    pod_sample,
    render_prom,
)
from land_trendr_tpu.obs.alerts import AlertEngine, AlertRule, load_rules
from land_trendr_tpu.obs.history import HistoryRing, counter_rate
from land_trendr_tpu.obs.publish import TelemetryPublisher, telemetry_dir
from land_trendr_tpu.obs.telemetry import Telemetry, metrics_path

__all__ = [
    "EVENT_FIELDS",
    "SCHEMA_VERSION",
    "EventLog",
    "discover_event_files",
    "events_path",
    "expand_event_paths",
    "iter_events",
    "validate_event",
    "validate_events_file",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "ResourceSampler",
    "flight_path",
    "thread_stacks",
    "MetricsHTTPServer",
    "MetricsRegistry",
    "PromFileExporter",
    "SPAN_STAGES",
    "StragglerDetector",
    "Telemetry",
    "AlertEngine",
    "AlertRule",
    "HistoryRing",
    "TelemetryPublisher",
    "assemble_pod_trace",
    "counter_rate",
    "critical_path",
    "fold_dir",
    "load_rules",
    "merge_instruments",
    "metrics_path",
    "pod_sample",
    "render_prom",
    "telemetry_dir",
]
