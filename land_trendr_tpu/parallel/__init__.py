"""parallel subpackage: device-mesh SPMD sharding of the pixel axis."""

from land_trendr_tpu.parallel.mesh import (
    PIXEL_AXIS,
    make_mesh,
    pad_to_multiple,
    segment_pixels_sharded,
    shard_pixels,
    summarize_sharded,
)
from land_trendr_tpu.parallel.multihost import (
    feed_global,
    gather_local_rows,
    host_share,
    init_distributed,
    is_primary_host,
)

__all__ = [
    "PIXEL_AXIS",
    "make_mesh",
    "pad_to_multiple",
    "segment_pixels_sharded",
    "shard_pixels",
    "summarize_sharded",
    "feed_global",
    "gather_local_rows",
    "host_share",
    "init_distributed",
    "is_primary_host",
]
