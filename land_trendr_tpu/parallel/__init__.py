"""parallel subpackage: device-mesh SPMD sharding of the pixel axis."""

from land_trendr_tpu.parallel.mesh import (
    PIXEL_AXIS,
    make_mesh,
    pad_to_multiple,
    segment_pixels_sharded,
    shard_pixels,
    summarize_sharded,
)

__all__ = [
    "PIXEL_AXIS",
    "make_mesh",
    "pad_to_multiple",
    "segment_pixels_sharded",
    "shard_pixels",
    "summarize_sharded",
]
