"""Multi-host (pod-scale) input feeding over DCN.

The reference scales out through Hadoop: the driver ships per-pixel records
to map tasks over the cluster network and collects them through the shuffle
(SURVEY.md §2 L4, §4 call stack (1)).  The TPU-native equivalent keeps the
*same* host-side data distribution idea — each host feeds only its own
slice of the scene — but the "shuffle" disappears: every host places its
local pixel block directly into a globally-sharded ``jax.Array``, the SPMD
program runs with **zero device-side cross-host traffic** (no cross-pixel
collectives — BASELINE north star), and results come back per-host from
each host's addressable shards.  DCN carries only coordination and each
host's input reads; ICI carries nothing but the optional metrics ``psum``
(SURVEY.md §5 "Distributed communication backend").

The v5e-256 scale-out config (BASELINE configs[5]) maps to two layers:

* **row-sharded batches** (this module's ``feed_global`` /
  ``gather_local_rows``): one global mesh over all chips, each host placing
  its contiguous rows — the right shape when one batch spans the pod;
* **the production tile driver** (:func:`land_trendr_tpu.runtime.
  run_stack` with ``mesh=make_mesh(jax.local_devices())``): tiles are the
  cross-host unit — each process takes its :func:`host_share` of the tile
  list and shards each tile's pixels over its OWN chips only, with the
  shared-filesystem manifest as the global job state (the reference's
  HDFS-backed bookkeeping).  No device-side cross-host traffic exists at
  all in this mode; ``tests/test_multihost.py``'s two-process driver test
  runs exactly this flow.

Common to both: one process per host and ``init_distributed`` before any
device use.

Everything here degrades to single-process: ``init_distributed`` is a
no-op without a coordinator, and ``feed_global`` on one process is just
``device_put`` with a sharding.  Tests exercise the same code path on the
virtual 8-device CPU mesh (one process owning all shards — exactly how a
single-host multi-chip machine runs it).
"""

from __future__ import annotations

import logging
import os
from typing import Sequence, TypeVar

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from land_trendr_tpu.parallel.mesh import PIXEL_AXIS

__all__ = [
    "init_distributed",
    "is_primary_host",
    "host_share",
    "feed_global",
    "gather_local_rows",
    "merge_host_event_logs",
]

_T = TypeVar("_T")

_log = logging.getLogger(__name__)

def _cluster_env_detected() -> bool:
    """True when the environment says this process is part of a multi-host
    cluster: a failed ``jax.distributed.initialize`` there must raise, not
    fall back to single-process mode — N hosts silently each computing the
    full scene would race on the same outputs.

    Single-host markers don't count: ``TPU_WORKER_HOSTNAMES`` with one entry
    is how a lone v5e host (or the axon tunnel) presents, and a SLURM job
    with one task is just a batch wrapper.
    """
    for k in (
        "JAX_COORDINATOR_ADDRESS",
        "COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS",
    ):
        if os.environ.get(k):
            return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hosts.split(",") if h.strip()]) > 1:
        return True
    if os.environ.get("SLURM_JOB_ID"):
        ntasks = os.environ.get("SLURM_NTASKS") or os.environ.get(
            "SLURM_NPROCS", "1"
        )
        try:
            if int(ntasks) > 1:
                return True
        except ValueError:
            pass
    return False


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialise ``jax.distributed`` when running multi-process.

    Call once per process before touching any device.  Explicit arguments
    win; with none, ``jax.distributed.initialize()`` runs its cluster
    auto-detection (TPU pod metadata, GKE, SLURM, ``JAX_COORDINATOR_*``
    env vars) — so a pod driver calls this with no args.  Returns True when
    distributed mode came up; when no cluster is detected *and* nothing was
    requested explicitly, returns False (the single-process no-op), keeping
    the same call portable from laptop CPU to pod.  An explicitly-requested
    coordinator that fails to connect still raises, as does a failure in an
    environment carrying cluster markers (SLURM / TPU pod metadata /
    coordinator env vars) — falling back there would leave every host
    computing the full scene and racing on the same outputs.
    """
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
        or _cluster_env_detected()
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except Exception as e:
        if explicit:
            raise
        _log.warning(
            "jax.distributed.initialize() found no cluster (%s: %s); "
            "running SINGLE-PROCESS. If this host is part of a pod, outputs "
            "will conflict — pass coordinator_address/num_processes/"
            "process_id explicitly.",
            type(e).__name__,
            e,
        )
        return False  # no cluster detected → single-process mode
    return True


def is_primary_host() -> bool:
    """True on the process that should write manifests / assemble rasters."""
    return jax.process_index() == 0


def host_share(items: Sequence[_T]) -> list[_T]:
    """The contiguous slice of ``items`` (e.g. tile specs) this host feeds.

    Hosts take near-equal contiguous blocks in process order — contiguous so
    a host's input reads stay sequential on its local storage view.  The
    remainder spreads one-per-host from process 0 (``np.array_split``
    semantics, computed with plain slicing so items pass through untouched).

    This is the STATIC split: deterministic and coordination-free, but one
    slow or dead host strands its whole share.  The tile driver's elastic
    mode (``RunConfig.lease_batch > 0`` —
    :mod:`land_trendr_tpu.runtime.leases`) supersedes it with a
    shared-manifest lease queue: hosts claim tiles in small batches,
    finishing hosts steal expired/unclaimed work, and hosts may join or
    leave mid-run.  ``host_share`` remains for row-sharded global batches
    and for lease-free runs.
    """
    n, i = jax.process_count(), jax.process_index()
    q, r = divmod(len(items), n)
    start = i * q + min(i, r)
    stop = start + q + (1 if i < r else 0)
    return list(items[start:stop])


def feed_global(
    mesh: Mesh,
    local_values: np.ndarray,
    local_mask: np.ndarray,
) -> tuple[jax.Array, jax.Array]:
    """Assemble globally-sharded ``(PX_global, NY)`` arrays from this host's
    local pixel rows.

    ``local_values``/``local_mask`` are the rows for *this host's* pixels
    only (``PX_global = PX_local × process_count``; every host must pass the
    same local row count — pad with fully-masked rows via
    ``pad_to_multiple`` first).  Each host's rows land on its own
    addressable devices — the placement is pure host→local-device transfer,
    nothing crosses DCN.
    """
    sharding = NamedSharding(mesh, P(PIXEL_AXIS, None))
    vals = jax.make_array_from_process_local_data(sharding, local_values)
    mask = jax.make_array_from_process_local_data(sharding, local_mask)
    return vals, mask


def merge_host_event_logs(
    workdir: str,
    expect_hosts: int | None = None,
    timeout_s: float = 60.0,
    poll_s: float = 0.1,
    newer_than: float | None = None,
) -> list[dict]:
    """Merge every per-process ``events*.jsonl`` in a shared workdir into
    per-host run aggregates — the primary-host fold the run summary carries.

    The pod driver flow keeps each process's telemetry in its own file
    (:func:`land_trendr_tpu.obs.events_path`), so merging is a plain
    shared-filesystem read — the same trust the tile manifest already
    places in the workdir, with no device collective involved.  With
    ``expect_hosts`` the merge WAITS (bounded by ``timeout_s``) until that
    many files carry a terminal ``run_done``: hosts finish their tile
    shares at different times, and the primary must not fold a peer's
    half-written stream.  On timeout the partial merge is returned with a
    warning — a crashed peer must not hang the primary's summary.

    While waiting, terminal state is probed from each file's TAIL only
    (``run_done`` is the last event a process emits); the full per-file
    parse happens exactly once, after the wait resolves — a straggler
    must not cost the primary quadratic re-parsing of gigarun streams.

    ``newer_than`` (a wall-clock timestamp — the caller's own run start,
    minus clock-skew slack) guards a REUSED workdir against a peer that
    died before writing this run's ``run_start``: its file still ends in
    the previous scope's ``run_done``, which the tail probe alone cannot
    tell from a live one.  Files not modified since ``newer_than`` are
    never counted terminal (the timeout warning surfaces the missing
    peer) and their summaries carry ``"stale": True``.

    Each summary also carries the peer's ``run_id`` and straggler count
    (``summarize_events_file``): every host's ``run_start`` records a
    ``(anchor_wall, anchor_mono)`` clock-anchor pair (mirrored into the
    shared manifest as ``kind="clock_anchor"`` lines), which is what
    ``tools/lt_trace.py`` aligns the per-host streams with — the merge
    itself stays a pure shared-filesystem fold with no clock trust
    beyond the existing mtime staleness guard.
    """
    import time

    from land_trendr_tpu.obs.events import (
        discover_event_files,
        summarize_events_file,
    )
    from land_trendr_tpu.runtime import faults

    def _files() -> list[str]:
        # the shared discovery contract: pod per-process files are the
        # run; a bare events.jsonl next to them — or p-files beyond
        # expect_hosts from a previous, larger pod run — are stale
        # leftovers in a reused workdir, not hosts
        try:
            return discover_event_files(workdir, process_count=expect_hosts)
        except FileNotFoundError:
            return []

    def _stale(path: str) -> bool:
        # untouched since the current run started = the stream is all
        # previous-scope history; its run_done must not satisfy the wait
        if newer_than is None:
            return False
        try:
            return os.path.getmtime(path) < newer_than
        except OSError:
            return True

    def _tail_terminal(path: str, tail_bytes: int = 8192) -> bool:
        # terminal = the LAST run scope has its run_done: a run_done with
        # a run_start after it in the tail belongs to a finished PREVIOUS
        # scope of a resumed run, and that peer is still mid-stream
        if faults.fired("merge.peer"):
            # behavioral fault seam: this probe sees a slow/dead peer —
            # the file reads as not-terminal, exercising the bounded-wait
            # timeout and partial-merge path deterministically
            return False
        if _stale(path):
            return False
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - tail_bytes))
                tail = f.read()
        except OSError:
            return False
        done = tail.rfind(b'"ev":"run_done"')
        return done >= 0 and done > tail.rfind(b'"ev":"run_start"')

    deadline = time.monotonic() + timeout_s
    # a file probed terminal stays terminal for THIS wait (its last scope
    # cannot lose its run_done) — only the pending set is re-probed, so a
    # straggler costs one tail read per poll, not one per host per poll
    # against the shared filesystem
    terminal: set[str] = set()
    while True:
        files = _files()
        terminal.update(p for p in files if p not in terminal and _tail_terminal(p))
        n_terminal = sum(1 for p in files if p in terminal)
        if expect_hosts is None or n_terminal >= expect_hosts:
            break
        if time.monotonic() > deadline:
            _log.warning(
                "merge_host_event_logs: only %d/%d hosts reached run_done "
                "within %.0fs; returning the partial merge",
                n_terminal, expect_hosts, timeout_s,
            )
            break
        time.sleep(poll_s)
    merged = []
    for p in files:
        s = summarize_events_file(p)
        if p not in terminal and _stale(p):
            # the summary describes a PREVIOUS run's scope, not this one —
            # a consumer must not read its status='ok' as a live host
            s["stale"] = True
        merged.append(s)
    return merged


def gather_local_rows(out: jax.Array) -> np.ndarray:
    """This host's rows of a pixel-sharded output, as one NumPy block.

    The inverse of :func:`feed_global`: concatenates the host's addressable
    shards in pixel order (shard index = row order on a 1-D mesh).  Each
    host persists its own rows (per-host manifests); no host ever
    materialises the global array, so result collection scales like the
    reference's distributed output writes rather than a single-point
    gather.
    """
    shards = sorted(
        out.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0)
