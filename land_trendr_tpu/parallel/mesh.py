"""Device-mesh data parallelism for the segmentation kernel.

The reference's only parallelism strategy is data-parallel over pixels —
one Hadoop map task per pixel with a shuffle to collect results (SURVEY.md
§3 "Parallelism strategies"; BASELINE.json north_star: tiles shard over a
TPU pod "with no cross-pixel collectives").  The TPU-native re-expression
is SPMD sharding of the pixel axis over a 1-D ``jax.sharding.Mesh``:

* the ``(PX, NY)`` value/mask arrays carry ``NamedSharding(mesh,
  P("pixels", None))`` — each chip owns a contiguous pixel block;
* the ``(NY,)`` year axis is replicated (it is shared by every pixel);
* ``jax_segment_pixels`` is purely ``vmap``-ed elementwise over pixels, so
  XLA partitions it with **zero cross-pixel data collectives** — exactly
  the reference's communication structure, minus the Hadoop shuffle
  (results stay sharded in HBM and are gathered host-side only when
  materialised).  The single cross-shard exchange in the compiled program
  is a 1-bit ``pred[]`` all-reduce: the convergence flag of ``betainc``'s
  iterative lowering (loop control, not pixel data; asserted in
  ``tests/test_parallel.py``);
* the only collective in the whole framework is an optional ``psum``-shaped
  metrics reduction (:func:`summarize_sharded`), mirroring SURVEY.md §5
  "at most a psum-style metrics reduction".

Multi-host note (SURVEY.md §5 distributed backend): on a multi-host pod the
same program runs under ``jax.distributed`` with each host feeding its
addressable shard of the pixel axis (``jax.make_array_from_process_local_
data``); no device-side cross-host traffic is required, so all layout
decisions here keep traffic off DCN entirely.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops.segment import SegOutputs, jax_segment_pixels

__all__ = [
    "PIXEL_AXIS",
    "make_mesh",
    "pad_to_multiple",
    "shard_pixels",
    "segment_pixels_sharded",
    "summarize_sharded",
]

#: Name of the single mesh axis; everything shards along pixels.
PIXEL_AXIS = "pixels"


def make_mesh(devices: Sequence[jax.Device] | None = None) -> Mesh:
    """A 1-D mesh over ``devices`` (default: all local devices).

    One axis suffices because the workload has nothing to shard but data
    (SURVEY.md §3: no model weights → TP/PP/EP are N/A; the 38-year
    temporal axis stays whole and HBM-resident per pixel → SP is N/A).
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (PIXEL_AXIS,))


def pad_to_multiple(
    values: np.ndarray | jnp.ndarray,
    mask: np.ndarray | jnp.ndarray,
    multiple: int,
) -> tuple[np.ndarray | jnp.ndarray, np.ndarray | jnp.ndarray, int]:
    """Pad the pixel axis up to a multiple of ``multiple``.

    Padded rows are fully masked (``mask=False``), which the kernel already
    treats as the insufficient-data path, so they cost compute but never
    produce NaNs or affect real pixels.  Returns ``(values, mask, n_real)``.
    """
    px = values.shape[0]
    n_pad = (-px) % multiple
    if n_pad == 0:
        return values, mask, px
    if isinstance(values, np.ndarray):
        pad_v = np.zeros((n_pad,) + values.shape[1:], dtype=values.dtype)
        pad_m = np.zeros((n_pad,) + mask.shape[1:], dtype=bool)
        return (
            np.concatenate([values, pad_v]),
            np.concatenate([mask, pad_m]),
            px,
        )
    pad_v = jnp.zeros((n_pad,) + values.shape[1:], dtype=values.dtype)
    pad_m = jnp.zeros((n_pad,) + mask.shape[1:], dtype=bool)
    return jnp.concatenate([values, pad_v]), jnp.concatenate([mask, pad_m]), px


def shard_pixels(
    mesh: Mesh, values, mask
) -> tuple[jax.Array, jax.Array]:
    """Place ``(PX, NY)`` arrays on the mesh, pixel axis sharded.

    The pixel count must already be a multiple of the mesh size (use
    :func:`pad_to_multiple`).
    """
    sh = NamedSharding(mesh, P(PIXEL_AXIS, None))
    return jax.device_put(values, sh), jax.device_put(mask, sh)


def segment_pixels_sharded(
    years,
    values,
    mask,
    params: LTParams = LTParams(),
    mesh: Mesh | None = None,
) -> SegOutputs:
    """Sharded :func:`jax_segment_pixels` over a device mesh.

    ``values``/``mask`` are ``(PX, NY)`` with ``PX`` a multiple of the mesh
    size; host arrays are placed with :func:`shard_pixels` first so the
    compiled program is SPMD from the start (no broadcast-then-reshard).
    Outputs keep the pixel-axis sharding; scalar-per-pixel outputs (rmse,
    p_of_f, ...) are sharded ``P("pixels")``.

    This compiles to the *same* program as the single-device path plus a
    partitioning annotation — XLA inserts no collectives because no op in
    the kernel crosses the pixel axis (BASELINE north star: "no cross-pixel
    collectives").
    """
    if mesh is None:
        mesh = make_mesh()
    n_dev = math.prod(mesh.devices.shape)
    if values.shape[0] % n_dev:
        raise ValueError(
            f"pixel count {values.shape[0]} not divisible by mesh size "
            f"{n_dev}; use pad_to_multiple first"
        )
    if (
        not isinstance(values, jax.Array)
        or getattr(values.sharding, "mesh", None) != mesh
    ):
        values, mask = shard_pixels(mesh, values, mask)
    years = jax.device_put(years, NamedSharding(mesh, P()))
    return jax_segment_pixels(years, values, mask, params)


def summarize_sharded(out: SegOutputs, n_real: int | None = None) -> dict[str, float]:
    """Cross-pixel run metrics — the framework's one ``psum``-shaped
    reduction (host-visible scalars; XLA emits the all-reduce over ICI).

    Returns pixel counts and quality aggregates used by the runtime's
    structured per-tile logs (SURVEY.md §5 observability).  Pass the
    ``n_real`` from :func:`pad_to_multiple` so the fully-masked padding
    rows (always no-fit) don't dilute the rates.
    """
    valid = out.model_valid
    rmse = out.rmse
    p_of_f = out.p_of_f
    if n_real is not None:
        valid, rmse, p_of_f = valid[:n_real], rmse[:n_real], p_of_f[:n_real]
    n = valid.shape[0]
    n_fit = jnp.sum(valid)
    mean_p = jnp.where(n_fit > 0, jnp.sum(jnp.where(valid, p_of_f, 0.0)) / jnp.maximum(n_fit, 1), 1.0)
    mean_rmse = jnp.sum(rmse) / n
    return {
        "pixels": float(n),
        "fit_rate": float(n_fit / n),
        "no_fit_rate": float(1.0 - n_fit / n),
        "mean_p_of_f": float(mean_p),
        "mean_rmse": float(mean_rmse),
    }
