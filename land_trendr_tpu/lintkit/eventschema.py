"""LT005 — telemetry emit sites must match the event schema.

The events.jsonl contract has one normative source —
``land_trendr_tpu.obs.events.EVENT_FIELDS`` / ``OPTIONAL_FIELDS`` — and
two sets of consumers that validate against it at runtime
(``tools/check_events_schema.py``, ``tools/obs_report.py``).  But the
PRODUCER side (the dict-literal keys at ``Telemetry``'s
``self.events.emit(...)`` call sites) was only checked by actually
running a telemetry run through the schema lint: a typo'd field name or
a forgotten required field ships silently until some integration test
happens to exercise that event.  This rule closes the loop statically.

For every ``*.emit("<event>", ...)`` call in the producer modules:

* the literal event name must exist in ``EVENT_FIELDS``;
* every explicit keyword must be a required or optional field of that
  event (``t_wall``/``t_mono`` are stamped by ``EventLog.emit`` itself);
* ``**splat`` arguments are resolved within the enclosing function —
  dict literals, ``{k: ... for k in ("a", "b", ...)}`` comprehensions
  over constant tuples, ``fields["k"] = ...`` stores and
  ``fields.setdefault("k", ...)`` calls all contribute keys; resolved
  keys are checked like keywords.  A splat the resolver cannot see
  through (a parameter, a call result) disables only the
  missing-required check — unknown-key checks still apply to what IS
  visible;
* when every splat resolved, each required field must appear.

It also cross-checks the runtime value-lint tables exported by
``tools/check_events_schema.py`` (``NONNEG_FIELDS`` — the satellite
refactor that made them importable data): every event and field they
name must exist in the schema, so the static rule and the runtime
linter can never drift onto two parallel copies.
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import Iterator

from land_trendr_tpu.lintkit.core import Checker, FileCtx, Finding, RepoCtx

__all__ = ["EventSchemaChecker"]

#: producer modules whose emit sites are checked (the Telemetry bundle
#: is THE emit surface; EventLog.emit itself is the transport, not a site)
PRODUCER_FILES = ("land_trendr_tpu/obs/telemetry.py",)

SCHEMA_TOOL = "tools/check_events_schema.py"

#: stamped by EventLog.emit on every record — never passed by callers
_COMMON = {"t_wall", "t_mono"}


def _load_nonneg_tables(repo: RepoCtx) -> "dict | None":
    """``NONNEG_FIELDS`` from tools/check_events_schema.py, or None when
    the tool is absent/unloadable (the cross-check then just skips)."""
    path = os.path.join(repo.root, SCHEMA_TOOL)
    if not os.path.exists(path):
        return None
    try:
        spec = importlib.util.spec_from_file_location("_lt_schema_tool", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return getattr(mod, "NONNEG_FIELDS", None)
    except Exception:
        return None


class _SplatKeys:
    """Key-gathering for one ``**name`` splat inside one function."""

    def __init__(self) -> None:
        self.keys: set = set()
        self.resolved = True
        #: did ANY source contribute?  A splatted name with no visible
        #: assignment (a parameter, a closure) is unresolvable, not empty
        self.found = False

    def add_dict_expr(self, expr: ast.AST) -> None:
        """Gather keys from a dict-producing expression (best effort)."""
        self.found = True
        if isinstance(expr, ast.Dict):
            for k in expr.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    self.keys.add(k.value)
                elif k is not None:  # non-constant key or ** merge
                    self.resolved = False
        elif isinstance(expr, ast.DictComp):
            # {k: ... for k in ("a", "b") if ...} — constant-tuple domains
            gen = expr.generators[0] if expr.generators else None
            if (
                gen is not None
                and isinstance(expr.key, ast.Name)
                and isinstance(gen.target, ast.Name)
                and expr.key.id == gen.target.id
                and isinstance(gen.iter, (ast.Tuple, ast.List))
                and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in gen.iter.elts
                )
            ):
                self.keys.update(e.value for e in gen.iter.elts)
            else:
                self.resolved = False
        elif isinstance(expr, ast.IfExp):
            # **({"stage_s": ...} if stage_s else {}) — both branches
            self.add_dict_expr(expr.body)
            self.add_dict_expr(expr.orelse)
        else:
            self.resolved = False


def _gather_splat(fn: ast.AST, name: str) -> _SplatKeys:
    """All keys a local dict ``name`` can carry within ``fn``."""
    out = _SplatKeys()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == name:
                    if node.value is not None:
                        out.add_dict_expr(node.value)
                elif (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == name
                ):
                    if isinstance(t.slice, ast.Constant) and isinstance(
                        t.slice.value, str
                    ):
                        out.found = True
                        out.keys.add(t.slice.value)
                    # non-constant subscript keys: conservative — they can
                    # only ADD keys we cannot name, so requiredness stays
                    # checkable but unknown-key checks skip them
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "setdefault"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == name
            and node.args
        ):
            k = node.args[0]
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out.found = True
                out.keys.add(k.value)
    if not out.found:
        out.resolved = False
    return out


class EventSchemaChecker(Checker):
    rule_id = "LT005"
    title = "emit-site fields drift from the event schema"

    def __init__(self) -> None:
        from land_trendr_tpu.obs.events import EVENT_FIELDS, OPTIONAL_FIELDS

        self.required = {ev: set(f) for ev, f in EVENT_FIELDS.items()}
        self.optional = {ev: set(f) for ev, f in OPTIONAL_FIELDS.items()}

    def inputs(self, repo: RepoCtx) -> set:
        return set(PRODUCER_FILES) | {SCHEMA_TOOL, "land_trendr_tpu/obs/events.py"}

    def check(self, repo: RepoCtx) -> Iterator[Finding]:
        for relpath in PRODUCER_FILES:
            if repo.exists(relpath):
                ctx = repo.file(relpath)
                if ctx.tree is not None:
                    yield from self._check_producer(ctx)
        yield from self._check_value_tables(repo)

    # -- producer emit sites ----------------------------------------------
    def _check_producer(self, ctx: FileCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "emit"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            ev = node.args[0].value
            if ev not in self.required:
                yield Finding(
                    ctx.path, node.lineno, self.rule_id,
                    f"emit of unknown event type '{ev}' (not in "
                    "obs.events.EVENT_FIELDS)",
                )
                continue
            allowed = self.required[ev] | self.optional.get(ev, set()) | _COMMON
            present: set = set()
            all_resolved = True
            for kw in node.keywords:
                if kw.arg is not None:
                    present.add(kw.arg)
                    if kw.arg not in allowed:
                        yield Finding(
                            ctx.path, node.lineno, self.rule_id,
                            f"emit('{ev}') passes field '{kw.arg}' that is "
                            "neither required nor a known optional field — "
                            "add it to OPTIONAL_FIELDS or fix the name",
                        )
                    continue
                # **splat: resolve within the enclosing function
                splat = _SplatKeys()
                if isinstance(kw.value, ast.Name):
                    fn = node
                    from land_trendr_tpu.lintkit.core import enclosing_function

                    owner = enclosing_function(fn)
                    if owner is not None:
                        splat = _gather_splat(owner, kw.value.id)
                    else:
                        splat.resolved = False
                else:
                    splat.add_dict_expr(kw.value)
                present.update(splat.keys)
                all_resolved = all_resolved and splat.resolved
                for key in sorted(splat.keys - allowed):
                    yield Finding(
                        ctx.path, node.lineno, self.rule_id,
                        f"emit('{ev}') splat carries field '{key}' that is "
                        "neither required nor a known optional field",
                    )
            if all_resolved:
                for missing in sorted(self.required[ev] - present):
                    yield Finding(
                        ctx.path, node.lineno, self.rule_id,
                        f"emit('{ev}') never sets required field "
                        f"'{missing}' (schema EVENT_FIELDS['{ev}'])",
                    )

    # -- runtime value-lint tables vs the schema ---------------------------
    def _check_value_tables(self, repo: RepoCtx) -> Iterator[Finding]:
        tables = _load_nonneg_tables(repo)
        if tables is None:
            return
        for ev, names in tables.items():
            if ev not in self.required:
                yield Finding(
                    SCHEMA_TOOL, 1, self.rule_id,
                    f"NONNEG_FIELDS names unknown event '{ev}' — the value "
                    "lint and the schema have drifted",
                )
                continue
            known = self.required[ev] | self.optional.get(ev, set())
            for name in names:
                if name not in known:
                    yield Finding(
                        SCHEMA_TOOL, 1, self.rule_id,
                        f"NONNEG_FIELDS['{ev}'] names field '{name}' that "
                        "the schema does not define",
                    )
