"""Intra-procedural taint/value-flow analysis over the parent-linked AST.

The third lint generation (LT009–LT012) checks *where values come from*,
not just what a statement looks like: a monotonic-clock read subtracted
from a wall-clock read three assignments later, a final artifact path
handed to a bare ``open(.., "w")``, a ``time.time()`` call two resolved
calls below a registered pure decision machine.  Statement-local walks
cannot see any of those; this module is the shared engine that can.

The model is deliberately small — a flow-insensitive fixpoint over one
function body:

* every variable (``x``), attribute cell (``self.x``) and constant-key
  subscript cell (``rec["t"]``) holds a **set of labels**;
* labels enter at leaves through a caller-supplied ``seeds`` hook (a
  ``time.monotonic()`` call seeds ``{"mono"}``, a string constant seeds
  its own text for path-fragment flow);
* labels propagate through assignments, tuple unpacking, augmented
  assignment, ``for``/``with`` bindings, arithmetic, f-strings,
  conditional expressions, container literals and constant-key subscript
  stores/loads, with a caller-supplied ``combine`` hook deciding what a
  ``BinOp`` does to its operand labels (the clock rule's algebra lives
  there: ``mono - mono`` is a duration and drops both labels);
* a ``calls`` hook lets a rule graft **interprocedural reach** on top:
  :class:`ReturnLabels` composes this engine with the PR-8
  :mod:`.callgraph` summaries, so a helper that returns
  ``time.monotonic()`` taints its (resolved) call sites one summary at a
  time, memoized across the whole run.

Iteration is bounded (label sets only grow, and the lattice is finite
per function), so the fixpoint terminates without widening.  Everything
is stdlib-only and jax-free, like the rest of lintkit.

:func:`module_literal` is the companion registry reader: LT009/LT011
consume data tables exported by heavy modules (``fleet/scheduling.py``'s
``PURE_MACHINES``, ``tools/fault_soak.py``'s ``SOAK_COVERED_SEAMS``)
by literal-evaluating the module-level assignment out of the AST — the
PR-4 ``NONNEG_FIELDS`` shared-table idea, without importing numpy into
the linter.
"""

from __future__ import annotations

import ast
from typing import Callable, Iterable, Iterator

__all__ = [
    "FieldStore",
    "FunctionFlow",
    "ReturnLabels",
    "dotted_call",
    "module_literal",
]

EMPTY: frozenset = frozenset()

#: builtins transparent to value flow: the result carries its arguments'
#: labels (``float(t_mono)`` is still a monotonic value, ``str(path)``
#: still names the same file)
_TRANSPARENT_CALLS = {
    "float", "int", "str", "abs", "min", "max", "round", "sum",
    "sorted", "list", "tuple", "set", "dict", "copy", "deepcopy",
}

#: receiver methods that MUTATE the receiver with their arguments'
#: labels (``d.update(other)``, ``xs.append(t)``) — the "taint crosses a
#: dict store" cases that are not syntactic assignments
_MUTATOR_METHODS = {"append", "add", "update", "setdefault", "insert",
                    "extend", "put"}


def dotted_call(node: ast.Call) -> str:
    """Best-effort dotted name of a call's callee: ``time.monotonic``,
    ``os.path.join``, ``open``, ``self._plan.check`` → ``"time.
    monotonic"`` / … / ``"self._plan.check"``; ``""`` when the callee is
    not a name/attribute chain (a call on a call, a subscript)."""
    parts: list[str] = []
    cur: ast.AST = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def module_literal(tree: "ast.AST | None", name: str):
    """Literal value of the module-level ``NAME = <literal>`` assignment
    in ``tree``, or ``None`` when absent/non-literal.  This is how the
    lint reads data registries exported by modules it must not import
    (``tools/fault_soak.py`` imports numpy at module level; the linter
    stays stdlib-only)."""
    if tree is None:
        return None
    for stmt in tree.body:
        value = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            t = stmt.targets[0]
            if isinstance(t, ast.Name) and t.id == name:
                value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            if isinstance(stmt.target, ast.Name) and stmt.target.id == name:
                value = stmt.value
        if value is not None:
            try:
                return ast.literal_eval(value)
            except ValueError:
                return None
    return None


def _target_cell(node: ast.AST) -> "str | None":
    """Environment cell name for an assignment target / load expression:
    ``x`` → ``"x"``, ``self.x`` → ``"self.x"``, ``rec["t"]`` →
    ``"rec['t']"`` (constant keys only); None for anything richer."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    if isinstance(node, ast.Subscript) and isinstance(node.value, ast.Name):
        if isinstance(node.slice, ast.Constant):
            return f"{node.value.id}[{node.slice.value!r}]"
    return None


class FieldStore:
    """One record-field store event: ``rec["t"] = v``, ``self.t0 = v``,
    a ``{"t": v}`` dict-literal entry, or an ``emit(..., t=v)`` keyword
    argument.  ``container`` is the receiver's source form (``"rec"``,
    ``"self"``, the callee for keywords), ``field`` the constant key /
    attribute / keyword name, ``node`` the stored value expression."""

    __slots__ = ("container", "field", "node", "kind")

    def __init__(self, container: str, field: str, node: ast.AST,
                 kind: str) -> None:
        self.container = container
        self.field = field
        self.node = node
        self.kind = kind  # "subscript" | "attribute" | "dict" | "keyword"


class FunctionFlow:
    """Label flow through one function body (flow-insensitive fixpoint).

    ``seeds(node)`` → labels introduced at any expression node;
    ``combine(node, left, right)`` → labels of a ``BinOp`` (default:
    union); ``calls(node)`` → labels of a call's result beyond its
    transparent-builtin propagation (the interprocedural hook).
    After construction, :meth:`labels` answers for any expression in the
    body and :meth:`field_stores` yields every record-field store with
    its stored labels.
    """

    MAX_PASSES = 10

    def __init__(
        self,
        func: ast.AST,
        seeds: "Callable[[ast.AST], frozenset]",
        combine: "Callable[[ast.AST, frozenset, frozenset], frozenset] | None" = None,
        calls: "Callable[[ast.Call], frozenset] | None" = None,
    ) -> None:
        self.func = func
        self._seeds = seeds
        self._combine = combine or (lambda node, a, b: a | b)
        self._calls = calls or (lambda node: EMPTY)
        self.env: dict[str, frozenset] = {}
        self._stores: dict[int, FieldStore] = {}
        self.returns: frozenset = EMPTY
        self._run()

    # -- fixpoint ----------------------------------------------------------
    def _run(self) -> None:
        body = getattr(self.func, "body", [])
        for _ in range(self.MAX_PASSES):
            before = {k: v for k, v in self.env.items()}
            returns_before = self.returns
            for stmt in body:
                self._stmt(stmt)
            if self.env == before and self.returns == returns_before:
                break

    def _stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes analyze separately
        if isinstance(stmt, ast.Assign):
            v = self.labels(stmt.value)
            for t in stmt.targets:
                self._bind(t, v, stmt.value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._bind(stmt.target, self.labels(stmt.value), stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            cell = _target_cell(stmt.target)
            cur = self.env.get(cell, EMPTY) if cell else EMPTY
            v = self._combine(stmt, cur, self.labels(stmt.value))
            self._bind(stmt.target, v, stmt.value, replace=True)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.returns |= self.labels(stmt.value)
        elif isinstance(stmt, ast.For):
            self._bind(stmt.target, self.labels(stmt.iter), stmt.iter)
            self._block(stmt.body + stmt.orelse)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                v = self.labels(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, v, item.context_expr)
            self._block(stmt.body)
        elif isinstance(stmt, ast.If):
            self._block(stmt.body + stmt.orelse)
        elif isinstance(stmt, ast.While):
            self._block(stmt.body + stmt.orelse)
        elif isinstance(stmt, ast.Try):
            self._block(stmt.body + stmt.orelse + stmt.finalbody)
            for h in stmt.handlers:
                self._block(h.body)
        elif isinstance(stmt, ast.Expr):
            self.labels(stmt.value)  # record stores/mutators inside
            self._mutator(stmt.value)

    def _block(self, stmts: "list[ast.AST]") -> None:
        for s in stmts:
            self._stmt(s)

    def _mutator(self, expr: ast.AST) -> None:
        """``d.update(x)`` / ``xs.append(t)`` taints the receiver."""
        if not (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr in _MUTATOR_METHODS):
            return
        cell = _target_cell(expr.func.value)
        if cell is None:
            return
        v = EMPTY
        for a in expr.args:
            v |= self.labels(a)
        for kw in expr.keywords:
            v |= self.labels(kw.value)
        if v:
            self.env[cell] = self.env.get(cell, EMPTY) | v

    def _bind(self, target: ast.AST, labels: frozenset, value: ast.AST,
              replace: bool = False) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            elts = None
            if isinstance(value, (ast.Tuple, ast.List)) and len(
                value.elts
            ) == len(target.elts):
                elts = value.elts
            for i, t in enumerate(target.elts):
                if elts is not None:
                    self._bind(t, self.labels(elts[i]), elts[i])
                else:
                    self._bind(t, labels, value)
            return
        if isinstance(target, ast.Starred):
            self._bind(target.value, labels, value)
            return
        cell = _target_cell(target)
        if cell is not None:
            if replace:
                self.env[cell] = labels
            else:
                self.env[cell] = self.env.get(cell, EMPTY) | labels
        if isinstance(target, ast.Subscript) and isinstance(
            target.value, ast.Name
        ):
            # the container itself is tainted too (unknown-key loads)
            base = target.value.id
            self.env[base] = self.env.get(base, EMPTY) | labels
            if isinstance(target.slice, ast.Constant) and isinstance(
                target.slice.value, str
            ):
                self._note_store(target.value.id, target.slice.value,
                                 value, "subscript")
        elif isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ):
            self._note_store(target.value.id, target.attr, value,
                             "attribute")

    def _note_store(self, container: str, field: str, node: ast.AST,
                    kind: str) -> None:
        self._stores[id(node)] = FieldStore(container, field, node, kind)

    # -- expression labels -------------------------------------------------
    def labels(self, expr: ast.AST) -> frozenset:
        """Label set of ``expr`` under the current environment."""
        out = frozenset(self._seeds(expr))
        if isinstance(expr, ast.Name) or isinstance(
            expr, (ast.Attribute, ast.Subscript)
        ):
            cell = _target_cell(expr)
            if cell is not None and cell in self.env:
                out |= self.env[cell]
            if isinstance(expr, (ast.Attribute, ast.Subscript)):
                if cell is None or cell not in self.env:
                    # unknown member of a tainted container
                    out |= self.labels(expr.value)
        elif isinstance(expr, ast.BinOp):
            out |= self._combine(
                expr, self.labels(expr.left), self.labels(expr.right)
            )
        elif isinstance(expr, ast.UnaryOp):
            out |= self.labels(expr.operand)
        elif isinstance(expr, ast.BoolOp):
            for v in expr.values:
                out |= self.labels(v)
        elif isinstance(expr, ast.IfExp):
            out |= self.labels(expr.body) | self.labels(expr.orelse)
        elif isinstance(expr, ast.Call):
            out |= self._call_labels(expr)
        elif isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            for e in expr.elts:
                out |= self.labels(e)
        elif isinstance(expr, ast.Dict):
            for k, v in zip(expr.keys, expr.values):
                out |= self.labels(v)
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    self._note_store("{}", k.value, v, "dict")
        elif isinstance(expr, ast.JoinedStr):
            for v in expr.values:
                if isinstance(v, ast.FormattedValue):
                    out |= self.labels(v.value)
                else:
                    out |= frozenset(self._seeds(v))
        elif isinstance(expr, ast.FormattedValue):
            out |= self.labels(expr.value)
        elif isinstance(expr, ast.NamedExpr):
            v = self.labels(expr.value)
            self._bind(expr.target, v, expr.value)
            out |= v
        elif isinstance(expr, ast.Starred):
            out |= self.labels(expr.value)
        elif isinstance(expr, ast.Compare):
            pass  # a bool carries no value labels
        return out

    def _call_labels(self, call: ast.Call) -> frozenset:
        out = frozenset(self._calls(call))
        name = dotted_call(call)
        terminal = name.rsplit(".", 1)[-1]
        if terminal in _TRANSPARENT_CALLS or terminal in ("join", "format"):
            for a in call.args:
                out |= self.labels(a)
            for kw in call.keywords:
                out |= self.labels(kw.value)
        if isinstance(call.func, ast.Attribute):
            # a method result on a tainted receiver stays tainted
            # (str(path).strip(), d.get("t")) — coarse but safe
            out |= self.labels(call.func.value)
        for kw in call.keywords:
            if kw.arg:
                self._note_store(name or "<call>", kw.arg, kw.value,
                                 "keyword")
        return out

    def field_stores(self) -> Iterator[tuple]:
        """Yield ``(FieldStore, labels)`` for every record-field store
        seen in the body, with labels evaluated at the fixpoint.
        Labeling a stored expression can itself discover nested stores
        (a dict literal inside a keyword argument), so drain until no
        new store appears rather than iterating the dict live."""
        seen: set[int] = set()
        while True:
            pending = [s for i, s in self._stores.items() if i not in seen]
            if not pending:
                return
            for store in pending:
                seen.add(id(store.node))
                yield store, self.labels(store.node)


class ReturnLabels:
    """Memoized per-function *return-label* summaries over the project
    call graph — the interprocedural composition layer.

    ``of(qname)`` runs the callee's own :class:`FunctionFlow` (with a
    ``calls`` hook that recurses through the graph's resolved edges,
    cycle-guarded to the empty set) and returns the labels its return
    statements carry.  LT010 uses this so ``def _stamp(): return
    time.monotonic()`` taints every resolved ``_stamp()`` call site.
    """

    def __init__(self, graph, seeds, combine=None) -> None:
        self.graph = graph
        self._seeds = seeds
        self._combine = combine
        self._memo: dict[str, frozenset] = {}
        self._in_progress: set[str] = set()

    def of(self, qname: str) -> frozenset:
        if qname in self._memo:
            return self._memo[qname]
        if qname in self._in_progress:
            return EMPTY  # recursion: converge from below
        info = self.graph.funcs.get(qname)
        if info is None:
            return EMPTY
        self._in_progress.add(qname)
        try:
            flow = FunctionFlow(
                info.node, self._seeds, combine=self._combine,
                calls=lambda c, _i=info: self.call_labels(_i, c),
            )
            self._memo[qname] = flow.returns
        finally:
            self._in_progress.discard(qname)
        return self._memo[qname]

    def call_labels(self, info, call: ast.Call) -> frozenset:
        """Labels a call inside ``info`` returns, via resolved callees."""
        out = EMPTY
        for site in info.calls:
            if site.line != call.lineno:
                continue
            for q in site.resolved:
                if q:
                    out |= self.of(q)
        return out
