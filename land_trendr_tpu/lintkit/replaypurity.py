"""LT009 — registered pure decision machines must stay replayable.

The capacity planner's byte-identity proof (``CAPACITY_r17.json``) holds
ONLY because the decision machines it replays — the DRR queue, the
warm-affinity replica choice, the autoscaler policy, the alert
lifecycle engine, the Kneedle fold — are pure functions of ``(state,
now)``: no clock reads, no randomness, no environment, no file IO, no
module-global mutation.  ``now`` and every seed arrive as *parameters*.
One stray ``time.time()`` three calls down and a replay diverges from
the live run on no reproducible schedule; PR 16 fixed exactly that bug
class by hand.

The registry is data, not prose: ``PURE_MACHINES`` tuples exported by
``fleet/scheduling.py`` and ``obs/alerts.py`` (the ``NONNEG_FIELDS``
shared-table pattern) name ``(file, symbol)`` roots — a bare function,
a ``Class.method``, a whole class (every method), or an ``fnmatch``
pattern (``*_value_errors`` covers the event value-lint folds).  This
rule expands each root through the PR-8 call graph's resolved edges and
walks every transitively reached body for impurity primitives; findings
attribute to the *registered root* with the full call chain spelled
out, so the baseline keys on the machine, not on whichever helper the
impurity happens to hide in today.

A registry entry that matches nothing is itself a finding — a renamed
machine must take its registration with it.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterator

from land_trendr_tpu.lintkit.callgraph import get_graph
from land_trendr_tpu.lintkit.core import Checker, Finding, RepoCtx
from land_trendr_tpu.lintkit.dataflow import dotted_call, module_literal

__all__ = ["ReplayPurityChecker", "REGISTRY_FILES"]

#: modules exporting a ``PURE_MACHINES`` registry (missing files are
#: tolerated so fixture mini-repos can carry just one)
REGISTRY_FILES = (
    "land_trendr_tpu/fleet/scheduling.py",
    "land_trendr_tpu/obs/alerts.py",
)

#: dotted call names that read a clock / randomness / the environment
_IMPURE_CALLS = {
    "time.time": "wall-clock read",
    "time.time_ns": "wall-clock read",
    "time.monotonic": "monotonic-clock read",
    "time.monotonic_ns": "monotonic-clock read",
    "time.perf_counter": "monotonic-clock read",
    "time.perf_counter_ns": "monotonic-clock read",
    "time.sleep": "clock-dependent sleep",
    "datetime.now": "wall-clock read",
    "datetime.utcnow": "wall-clock read",
    "datetime.datetime.now": "wall-clock read",
    "datetime.datetime.utcnow": "wall-clock read",
    "os.getenv": "environment read",
    "os.environ.get": "environment read",
    "os.urandom": "randomness",
    "uuid.uuid1": "randomness",
    "uuid.uuid4": "randomness",
}

#: module prefixes whose every call is impure (unseeded randomness)
_IMPURE_PREFIXES = ("random.", "secrets.")

#: file-IO call names (reads included: a pure machine's inputs arrive
#: as arguments, not as files it opens behind the replay's back)
_IO_CALLS = {
    "open": "file IO (open)",
    "os.open": "file IO (os.open)",
    "os.write": "file IO (os.write)",
    "os.read": "file IO (os.read)",
    "os.remove": "file IO (os.remove)",
    "os.replace": "file IO (os.replace)",
    "os.rename": "file IO (os.rename)",
    "os.makedirs": "file IO (os.makedirs)",
    "os.fsync": "file IO (os.fsync)",
}

#: method terminals that are file IO on any receiver worth flagging
_IO_METHODS = {
    "write_text": "file IO (write_text)",
    "write_bytes": "file IO (write_bytes)",
    "read_text": "file IO (read_text)",
    "read_bytes": "file IO (read_bytes)",
}


def _impurity(call: ast.Call) -> "str | None":
    name = dotted_call(call)
    if not name:
        return None
    if name in _IMPURE_CALLS:
        return f"{_IMPURE_CALLS[name]} ({name}())"
    for prefix in _IMPURE_PREFIXES:
        if name.startswith(prefix):
            return f"unseeded randomness ({name}())"
    if name in _IO_CALLS:
        return _IO_CALLS[name]
    terminal = name.rsplit(".", 1)[-1]
    if terminal in _IO_METHODS and "." in name:
        return _IO_METHODS[terminal]
    return None


def _scan_body(node: ast.AST) -> "list[tuple[int, str]]":
    """(line, description) impurity primitives directly in one body."""
    out: list[tuple[int, str]] = []
    globals_declared: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Global):
            globals_declared.update(n.names)
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            desc = _impurity(n)
            if desc is not None:
                out.append((n.lineno, desc))
        elif isinstance(n, (ast.Assign, ast.AugAssign)):
            targets = (
                n.targets if isinstance(n, ast.Assign) else [n.target]
            )
            for t in targets:
                if isinstance(t, ast.Name) and t.id in globals_declared:
                    out.append(
                        (n.lineno, f"module-global mutation ({t.id})")
                    )
        elif isinstance(n, ast.Attribute) and n.attr == "environ":
            if isinstance(n.value, ast.Name) and n.value.id == "os":
                out.append((n.lineno, "environment read (os.environ)"))
    return out


class ReplayPurityChecker(Checker):
    rule_id = "LT009"
    title = "registered pure decision machine reaches an impure primitive"

    def inputs(self, repo: RepoCtx) -> "set[str] | None":
        return {f for f in repo.py_files if not f.startswith("tests/")}

    # -- registry ----------------------------------------------------------
    def _registry(self, repo: RepoCtx) -> "list[tuple[str, str]]":
        entries: list[tuple[str, str]] = []
        for relpath in REGISTRY_FILES:
            if not repo.exists(relpath):
                continue
            machines = module_literal(repo.file(relpath).tree,
                                      "PURE_MACHINES")
            if machines:
                entries.extend((str(f), str(s)) for f, s in machines)
        return entries

    def _expand(self, graph, file: str, symbol: str) -> "list[str]":
        """Registry entry → root qnames in the call graph."""
        roots: list[str] = []
        if "*" in symbol or "?" in symbol:
            for qname, info in graph.funcs.items():
                if info.file != file:
                    continue
                local = f"{info.cls}.{info.name}" if info.cls else info.name
                if fnmatch.fnmatch(local, symbol):
                    roots.append(qname)
            return roots
        direct = f"{file}::{symbol}"
        if direct in graph.funcs:
            return [direct]
        # a bare class name registers every method
        for qname, info in graph.funcs.items():
            if info.file == file and info.cls == symbol:
                roots.append(qname)
        return roots

    # -- the rule ----------------------------------------------------------
    def check(self, repo: RepoCtx) -> Iterator[Finding]:
        graph = get_graph(repo)
        registry = self._registry(repo)
        impure_cache: dict[str, list] = {}

        def direct(qname: str) -> list:
            if qname not in impure_cache:
                info = graph.funcs.get(qname)
                impure_cache[qname] = (
                    _scan_body(info.node) if info is not None else []
                )
            return impure_cache[qname]

        for file, symbol in registry:
            roots = self._expand(graph, file, symbol)
            if not roots:
                yield Finding(
                    file=file,
                    line=1,
                    rule_id=self.rule_id,
                    message=(
                        f"PURE_MACHINES entry ({file!r}, {symbol!r}) "
                        "matches no function — the registry drifted from "
                        "the code"
                    ),
                    symbol="<registry>",
                )
                continue
            for root in roots:
                yield from self._check_root(graph, root, direct)

    def _check_root(self, graph, root: str, direct) -> Iterator[Finding]:
        info = graph.funcs[root]
        root_symbol = f"{info.cls}.{info.name}" if info.cls else info.name
        # BFS over resolved call edges, remembering one parent per node
        # so every finding carries a concrete witness chain
        parent: dict[str, "str | None"] = {root: None}
        order = [root]
        i = 0
        while i < len(order):
            q = order[i]
            i += 1
            qi = graph.funcs.get(q)
            if qi is None:
                continue
            for site in qi.calls:
                for callee in site.resolved:
                    if callee and callee not in parent:
                        parent[callee] = q
                        order.append(callee)
        reported: set = set()
        for q in order:
            for line, desc in direct(q):
                chain: list[str] = []
                cur: "str | None" = q
                while cur is not None:
                    ci = graph.funcs[cur]
                    chain.append(
                        f"{ci.cls}.{ci.name}" if ci.cls else ci.name
                    )
                    cur = parent[cur]
                chain.reverse()
                qi = graph.funcs[q]
                key = (desc, qi.file, line)
                if key in reported:
                    continue
                reported.add(key)
                via = " -> ".join(chain)
                where = (
                    f" at {qi.file}:{line}" if q != root else ""
                )
                yield Finding(
                    file=info.file,
                    line=info.node.lineno,
                    rule_id=self.rule_id,
                    message=(
                        f"pure decision machine '{root_symbol}' reaches "
                        f"{desc} via {via}{where} — replay determinism "
                        "requires clocks/seeds as parameters"
                    ),
                    symbol=root_symbol,
                )
