"""AST lint framework for the repo's concurrency/coupling invariants.

PRs 1-3 turned the single-process pipeline into a concurrent system — a
process-wide decoded-block cache with a shared decode pool
(``io/blockcache.py``), an async fetch backlog (``runtime/fetch.py``),
and serialized telemetry writers (``obs/``) — whose correctness rests on
invariants no runtime test can pin, because races and stray host syncs
are timing-dependent.  PR 3 found one such bug (a blocking
``model_valid`` fetch hiding in a write-timer metadata branch) by eye;
this package is the machine that finds the class, on every PR.

Pieces:

* :class:`Finding` — one violation: ``(file, line, rule_id, message)``.
* :class:`FileCtx` / :class:`RepoCtx` — parsed-AST caches.  Every tree
  is **parent-linked** (:func:`link_parents` stamps ``node.parent``), so
  rules ask "is this statement inside a ``with self._lock``" by walking
  ancestors instead of threading state through a visitor.
* :class:`Checker` — one rule: ``rule_id``, ``title``, and a
  ``check(repo)`` generator.  Per-file rules override ``check_file``;
  repo-level rules (config/README coupling, emit-site schema) override
  ``check`` directly and declare ``inputs(repo)`` so ``--changed`` runs
  know when they apply.
* suppressions — two layers, both requiring intent to be written down:
  inline ``# lt: noqa[LT001]`` on the finding's line or in the
  comment-only block immediately above it (``# lt: noqa`` suppresses
  every rule), and :class:`Baseline` — a
  committed ``LINT_BASELINE.json`` of deliberate exceptions, each entry
  carrying a non-empty ``reason`` string (entries without one are a
  lint-configuration error, not a suppression).

The CLI is ``tools/lt_lint.py``; the rules live in the sibling modules
(:mod:`.locks`, :mod:`.hostsync`, :mod:`.jitpurity`, :mod:`.configdoc`,
:mod:`.eventschema`).  Everything here is stdlib-only and jax-free, so
the linter runs in any environment the tests do.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable, Iterator

__all__ = [
    "Baseline",
    "BaselineError",
    "Checker",
    "FileCtx",
    "Finding",
    "RepoCtx",
    "link_parents",
    "ancestors",
    "enclosing_function",
    "in_with_lock",
    "run_rules",
]

#: dirs never linted: VCS state, caches, generated protobuf, C++ sources
_SKIP_DIRS = {".git", "__pycache__", ".claude", "native", "_proto"}

_NOQA_RE = re.compile(r"#\s*lt:\s*noqa(?:\[([A-Za-z0-9_,\s]+)\])?")

#: sentinel for a bare ``# lt: noqa`` (suppresses every rule on the line)
ALL_RULES = "*"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location.

    ``file`` is repo-relative (what the baseline keys on and what CI
    prints); ``line`` is 1-based.  ``symbol`` is the enclosing
    ``Class.method`` / function qualname (stamped by :func:`run_rules`
    from the AST when the rule did not set it) — baselines key on it so
    entries survive unrelated edits that shift line numbers.
    """

    file: str
    line: int
    rule_id: str
    message: str
    symbol: str = ""

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.file}:{self.line}: {self.rule_id}{sym} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def link_parents(tree: ast.AST) -> ast.AST:
    """Stamp ``node.parent`` on every node (root's parent is ``None``)."""
    tree.parent = None  # type: ignore[attr-defined]
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]
    return tree


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    """Yield ``node``'s ancestors, innermost first (parent-link walk)."""
    cur = getattr(node, "parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "parent", None)


def enclosing_function(node: ast.AST) -> "ast.FunctionDef | ast.AsyncFunctionDef | None":
    """Nearest enclosing function definition, or None at module level."""
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return a
    return None


def in_with_lock(node: ast.AST, is_lock_expr) -> bool:
    """True when ``node`` sits inside a ``with`` whose context expression
    satisfies ``is_lock_expr`` (the rule's definition of "the lock")."""
    for a in ancestors(node):
        if isinstance(a, ast.With):
            for item in a.items:
                if is_lock_expr(item.context_expr):
                    return True
    return False


class FileCtx:
    """One source file: text, parent-linked AST, and noqa line map."""

    def __init__(self, root: str, relpath: str, source: "str | None" = None) -> None:
        self.root = root
        self.path = relpath
        if source is None:
            with open(os.path.join(root, relpath)) as f:
                source = f.read()
        self.source = source
        self.lines = source.splitlines()
        self._tree: "ast.AST | None" = None
        self._tree_parsed = False
        self._noqa: "dict[int, set[str]] | None" = None
        self._symbols: "list[tuple[int, int, str]] | None" = None

    @property
    def tree(self) -> "ast.AST | None":
        """Parent-linked AST, or None when the file does not parse (a
        syntax error is pytest/import-time territory, not lint's).  The
        parse failure is cached too — without the flag every access
        re-parsed a broken file."""
        if not self._tree_parsed:
            self._tree_parsed = True
            try:
                self._tree = link_parents(ast.parse(self.source))
            except SyntaxError:
                self._tree = None
        return self._tree

    def noqa_rules(self, line: int) -> set:
        """Rule ids suppressed on ``line`` (``{'*'}`` = all rules)."""
        if self._noqa is None:
            self._noqa = {}
            for i, text in enumerate(self.lines, 1):
                m = _NOQA_RE.search(text)
                if m:
                    if m.group(1):
                        self._noqa[i] = {
                            r.strip() for r in m.group(1).split(",") if r.strip()
                        }
                    else:
                        self._noqa[i] = {ALL_RULES}
        return self._noqa.get(line, set())

    def symbol_at(self, line: int) -> str:
        """The innermost enclosing ``Class.method``/function qualname
        containing ``line``, or ``""`` at module level.  This is the
        line-number-independent key baselines use: renaming or moving a
        function invalidates its entries (the code changed), but edits
        elsewhere in the file do not."""
        if self._symbols is None:
            self._symbols = []
            tree = self.tree
            if tree is not None:
                def visit(node, prefix: str) -> None:
                    for child in ast.iter_child_nodes(node):
                        if isinstance(
                            child,
                            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                        ):
                            qname = (
                                f"{prefix}.{child.name}" if prefix else child.name
                            )
                            end = getattr(child, "end_lineno", child.lineno)
                            self._symbols.append((child.lineno, end, qname))
                            visit(child, qname)
                        else:
                            visit(child, prefix)

                visit(tree, "")
        best = ""
        best_span = None
        for start, end, qname in self._symbols:
            if start <= line <= end:
                span = end - start
                if best_span is None or span <= best_span:
                    best, best_span = qname, span
        return best

    def suppressed(self, finding: Finding) -> bool:
        """Inline suppression: a ``# lt: noqa[...]`` on the finding's own
        line, or anywhere in the comment-only block immediately above it
        (so a suppression can carry a multi-line justification without
        stretching the code line)."""
        rules = set(self.noqa_rules(finding.line))
        i = finding.line - 1
        while i >= 1 and self.lines[i - 1].lstrip().startswith("#"):
            rules |= self.noqa_rules(i)
            i -= 1
        return ALL_RULES in rules or finding.rule_id in rules


class RepoCtx:
    """The lint run's view of the repository: root + cached FileCtx's."""

    def __init__(self, root: str, files: "Iterable[str] | None" = None) -> None:
        self.root = os.path.abspath(root)
        self._files = sorted(files) if files is not None else None
        self._ctx: dict[str, FileCtx] = {}
        #: scratch shared across rules in one run (the interprocedural
        #: rules memoize their project graph here so LT006/7/8 build it
        #: once, not three times)
        self.cache: dict = {}

    @property
    def py_files(self) -> list[str]:
        if self._files is None:
            self._files = sorted(self._discover())
        return self._files

    def _discover(self) -> Iterator[str]:
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for name in filenames:
                if name.endswith(".py"):
                    yield os.path.relpath(
                        os.path.join(dirpath, name), self.root
                    )

    def file(self, relpath: str) -> FileCtx:
        if relpath not in self._ctx:
            self._ctx[relpath] = FileCtx(self.root, relpath)
        return self._ctx[relpath]

    def exists(self, relpath: str) -> bool:
        return os.path.exists(os.path.join(self.root, relpath))

    def read_text(self, relpath: str) -> str:
        with open(os.path.join(self.root, relpath)) as f:
            return f.read()


class Checker:
    """One lint rule.  Subclasses set ``rule_id``/``title`` and override
    ``check_file`` (per-file rules) or ``check`` (repo-level rules)."""

    rule_id: str = ""
    title: str = ""

    def inputs(self, repo: RepoCtx) -> "set[str] | None":
        """Files this rule reads beyond the per-file walk (repo-level
        rules return them so ``--changed`` knows when the rule applies);
        ``None`` = purely per-file."""
        return None

    def check(self, repo: RepoCtx) -> Iterator[Finding]:
        for relpath in repo.py_files:
            ctx = repo.file(relpath)
            if ctx.tree is None:
                continue
            yield from self.check_file(ctx)

    def check_file(self, ctx: FileCtx) -> Iterator[Finding]:
        return iter(())


class BaselineError(ValueError):
    """A malformed LINT_BASELINE.json (missing reason, unknown shape)."""


class Baseline:
    """Committed deliberate exceptions, each with a written reason.

    Entry shape::

        {"rule": "LT002", "file": "land_trendr_tpu/parallel/multihost.py",
         "symbol": "gather_local_rows", "contains": "np.asarray",
         "reason": "gather path: ..."}

    Entries key on content, never line numbers, so unrelated edits to
    the file do not invalidate them: ``symbol`` (optional) must equal
    the finding's enclosing ``Class.method``/function qualname, and
    ``contains`` (optional) must be a substring of the finding message.
    Every entry MUST carry a non-empty ``reason``; an exception nobody
    can explain is not an exception.
    """

    def __init__(self, entries: "list[dict] | None" = None) -> None:
        self.entries = entries or []
        for i, e in enumerate(self.entries):
            if not isinstance(e, dict) or not e.get("rule") or not e.get("file"):
                raise BaselineError(f"baseline entry {i} needs 'rule' and 'file'")
            if not str(e.get("reason", "")).strip():
                raise BaselineError(
                    f"baseline entry {i} ({e.get('rule')} {e.get('file')}) "
                    "has no reason — every deliberate exception must say why"
                )
        self._hits = [0] * len(self.entries)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict) or not isinstance(data.get("entries"), list):
            raise BaselineError(f"{path}: expected {{'entries': [...]}}")
        return cls(data["entries"])

    def match(self, finding: Finding) -> "dict | None":
        for i, e in enumerate(self.entries):
            if e["rule"] != finding.rule_id or e["file"] != finding.file:
                continue
            if e.get("symbol") and e["symbol"] != finding.symbol:
                continue
            if e.get("contains") and e["contains"] not in finding.message:
                continue
            self._hits[i] += 1
            return e
        return None

    def unused(self) -> list[dict]:
        """Entries that matched nothing — stale exceptions to clean up."""
        return [e for e, n in zip(self.entries, self._hits) if n == 0]


def run_rules(
    repo: RepoCtx,
    rules: Iterable[Checker],
    baseline: "Baseline | None" = None,
    only_files: "set[str] | None" = None,
) -> dict:
    """Run every rule; split findings into active / baselined / noqa'd.

    ``only_files`` (the ``--changed`` set) scopes per-file rules to just
    those files — they parse and walk nothing else, so a one-file
    pre-commit run costs one file, not the tree; a repo-level rule runs
    iff any of its declared ``inputs`` is in the set, and then keeps all
    its findings (coupling rules span files by nature).
    """
    active: list[Finding] = []
    baselined: list[tuple[Finding, dict]] = []
    noqa_count = 0
    scoped_repo = repo
    if only_files is not None:
        scoped_repo = RepoCtx(
            repo.root, files=[f for f in repo.py_files if f in only_files]
        )
    for rule in rules:
        inputs = rule.inputs(repo)
        if only_files is not None and inputs is not None:
            if not (inputs & only_files):
                continue
        for finding in rule.check(repo if inputs is not None else scoped_repo):
            if (
                only_files is not None
                and inputs is None
                and finding.file not in only_files
            ):
                continue
            if finding.file.endswith(".py") and repo.exists(finding.file):
                fctx = repo.file(finding.file)
                if not finding.symbol:
                    finding = dataclasses.replace(
                        finding, symbol=fctx.symbol_at(finding.line)
                    )
                if fctx.suppressed(finding):
                    noqa_count += 1
                    continue
            entry = baseline.match(finding) if baseline is not None else None
            if entry is not None:
                baselined.append((finding, entry))
            else:
                active.append(finding)
    active.sort(key=lambda f: (f.file, f.line, f.rule_id))
    return {
        "findings": active,
        "baselined": baselined,
        "noqa_suppressed": noqa_count,
        "unused_baseline": baseline.unused() if baseline is not None else [],
    }
