"""LT002 — no blocking host sync outside the fetch path.

The driver's throughput design (README §Fetch path, arXiv:1807.01751's
host-I/O-bound regime) funnels every device→host materialization through
``runtime/fetch.py``: packed transfers overlap the next tile's compute,
and the per-product fallback runs inside the writer pool.  A stray
``np.asarray`` / ``.block_until_ready()`` / ``.item()`` anywhere else in
the runtime stalls the pipeline for a full link round trip per call —
PR 3 removed exactly such a stray (a blocking ``model_valid`` fetch in a
write-timer metadata branch) that had been invisible in tests because
the artifacts stayed byte-identical.

Static typing cannot prove a value is device-resident, so the rule is
scoped instead of typed: inside the modules that handle device values
(``land_trendr_tpu/runtime/``, ``land_trendr_tpu/obs/``,
``land_trendr_tpu/parallel/``, ``land_trendr_tpu/serve/``), every
syncing call form is a finding — ``np.asarray(...)``,
``jax.device_get(...)``, ``jax.block_until_ready`` /
``.block_until_ready()``, and ``.item()``.  ``runtime/fetch.py`` and
``runtime/feed.py`` are the blessed modules (they ARE the fetch and
upload paths — each owns exactly one sanctioned wait point); the
driver's sanctioned compute-wait sites (the two pipeline waits and the
serve-mode warm-probe wait) carry inline ``# lt: noqa[LT002]``, and
host-side assembly seams live in ``LINT_BASELINE.json`` with their
reasons.

Scope decision for ``serve/`` (recorded rationale, ISSUE 7): the serve
layer composes whole :class:`~land_trendr_tpu.runtime.driver.Run`
objects and only ever touches their host-side summaries, so device
values should never surface there — it is IN scope and NOT blessed; any
sync call appearing in ``serve/`` is a design regression (device state
leaking past the run boundary), exactly what this rule exists to catch.
(`float()` on a device scalar is the same hazard but indistinguishable
from a host cast without types — ``.item()`` covers the idiom the
codebase actually uses.)
"""

from __future__ import annotations

import ast
from typing import Iterator

from land_trendr_tpu.lintkit.core import Checker, FileCtx, Finding

__all__ = ["HostSyncChecker"]

#: path prefixes where device values flow and a sync stalls the pipeline
SCOPED_PREFIXES = (
    "land_trendr_tpu/runtime/",
    "land_trendr_tpu/obs/",
    "land_trendr_tpu/parallel/",
    # serve/ composes Runs and reads their host-side summaries only:
    # in scope, NOT blessed (see the module docstring's rationale)
    "land_trendr_tpu/serve/",
)

#: the modules allowed to sync: they ARE the fetch/upload paths
BLESSED_FILES = (
    "land_trendr_tpu/runtime/fetch.py",
    "land_trendr_tpu/runtime/feed.py",
)


def _call_sync_kind(node: ast.Call) -> "str | None":
    """The sync idiom a call expresses, or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        base = fn.value.id if isinstance(fn.value, ast.Name) else None
        if fn.attr == "asarray" and base in ("np", "numpy"):
            return "np.asarray (device->host materialization)"
        if fn.attr == "device_get" and base == "jax":
            return "jax.device_get (blocking device->host fetch)"
        if fn.attr == "block_until_ready":
            return (
                "jax.block_until_ready (host blocks on device)"
                if base == "jax"
                else ".block_until_ready() (host blocks on device)"
            )
        if fn.attr == "item" and not node.args and not node.keywords:
            return ".item() (device scalar sync)"
    return None


class HostSyncChecker(Checker):
    rule_id = "LT002"
    title = "blocking host sync outside runtime/fetch.py"

    def check_file(self, ctx: FileCtx) -> Iterator[Finding]:
        path = ctx.path.replace("\\", "/")
        if path in BLESSED_FILES or not path.startswith(SCOPED_PREFIXES):
            return
        assert ctx.tree is not None
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _call_sync_kind(node)
            if kind is not None:
                yield Finding(
                    ctx.path, node.lineno, self.rule_id,
                    f"{kind} outside the fetch path — route device->host "
                    "materialization through runtime/fetch.py or bless the "
                    "site explicitly",
                )
