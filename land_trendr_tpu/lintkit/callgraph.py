"""Interprocedural analysis engine: project call graph + effect summaries.

lt-lint v1 (LT001–LT005) is statement-local by design: every rule asks a
question one AST walk can answer.  The defect classes the review logs
keep finding by hand are not — a lock-ordering hazard spans two
functions that each look fine alone, the PR-6 blockstore bug was
multi-MiB blocking work reached *through a call* made under a lock, and
the PR-7 leaks were resources created in one method and (not) closed in
another.  This module is the shared machinery the interprocedural rules
(:mod:`.lockorder` LT006, :mod:`.blocking` LT007, :mod:`.lifecycle`
LT008) stand on:

* a **project call graph** — every function/method in the linted tree,
  with call sites resolved by name within the package: direct names to
  same-module (or ``from``-imported) functions, ``self.m()`` through the
  class and its bases, ``obj.m()`` through a light receiver-type
  inference (``self.x = ClassName(...)`` in ``__init__``, local
  ``x = ClassName(...)`` bindings, module aliases), and — last resort —
  **attribute-name dispatch** against the project class index when the
  method name is distinctive (defined by at most two project classes and
  not a common container-method name);
* per-function **summaries** — locks acquired (``with <lock>`` /
  ``.acquire()``, with :class:`threading.Condition` objects aliased to
  the lock they wrap, so ``with self._cond`` and ``with self._lock``
  unify when the condition was built as ``Condition(self._lock)``),
  primitive **blocking operations** (file/socket IO, ``device_put`` /
  ``block_until_ready``, ``Future.result``, ``sleep``, subprocess,
  thread ``join``, ``Event``/``Condition`` ``wait``), and the held-lock
  context of every call site;
* **fixpoints** over the graph — the transitive lock-acquisition set of
  a function and a witness chain to the nearest blocking operation —
  plus a **construction-only** classification (functions reachable only
  from ``__init__``, where a held lock is uncontended by construction,
  mirroring LT001's ``__init__`` exemption).

Identity model: a lock is ``(file, owner, attr)`` where ``owner`` is the
defining class name ("" for module locks).  Class-level identity is the
standard approximation for ordering analysis — two instances of one
class are distinct locks at runtime, but an ordering hazard between the
*classes* is exactly what a reviewer needs to see.  ``Condition.wait``
releases (and reacquires) the wrapped lock, so a ``wait`` whose receiver
aliases a lock held at that site is *not* a blocking operation for
LT007, and nothing "acquired inside the wait" creates LT006 edges.

Everything is stdlib ``ast``; the graph for the whole tree builds in
well under a second and is memoized per :class:`RepoCtx` via
``repo.cache`` so the three rules share one build.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

from land_trendr_tpu.lintkit.core import RepoCtx

__all__ = [
    "LockId",
    "CallSite",
    "BlockingOp",
    "FuncInfo",
    "ProjectGraph",
    "get_graph",
]

#: receiver-less attribute dispatch is only attempted for method names
#: defined by at most this many project classes
_DISPATCH_FANOUT = 2

#: method names too generic for receiver-less dispatch: linking every
#: ``d.get(...)`` to a project class named method would drown the graph
#: in false edges (dict/list/set/queue/logger vocabulary)
_COMMON_METHODS = frozenset(
    {
        "get", "put", "pop", "items", "keys", "values", "update", "append",
        "add", "remove", "discard", "clear", "copy", "setdefault", "extend",
        "insert", "sort", "reverse", "close", "open", "start", "stop", "run",
        "read", "write", "emit", "set", "submit", "result", "join", "wait",
        "acquire", "release", "send", "recv", "flush", "shutdown", "cancel",
        "info", "warning", "error", "debug", "exception", "critical", "log",
        "match", "search", "split", "strip", "format", "encode", "decode",
        "tick", "check", "record", "observe", "inc", "dec", "render",
    }
)

#: os.* calls that move bytes (the PR-6 class); metadata operations
#: (unlink/replace/stat) are deliberately excluded — flagging every
#: eviction unlink under a lock would drown the multi-MiB signal
_OS_BLOCKING = frozenset({"write", "read", "fsync", "sendfile", "pread", "pwrite"})

_SUBPROCESS_CALLS = frozenset({"run", "Popen", "call", "check_call", "check_output"})

_SOCKET_METHODS = frozenset({"recv", "recv_into", "send", "sendall", "accept", "connect"})

_LOCK_CTORS = ("Lock", "RLock")


# ---------------------------------------------------------------------------
# identity / data model

#: (file, owner-class ("" = module scope), attribute/name)
LockId = tuple


@dataclasses.dataclass
class CallSite:
    """One call expression inside a function body."""

    line: int
    held: tuple  # LockIds held (syntactically) at the site, outermost first
    resolved: tuple  # qnames of candidate callees ("" when unresolved)
    label: str  # human form of the callee expression ("self.flush", "open")


@dataclasses.dataclass
class BlockingOp:
    """One primitive blocking operation inside a function body."""

    line: int
    desc: str
    held: tuple  # LockIds held at the site


@dataclasses.dataclass
class FuncInfo:
    """One function/method of the linted tree plus its effect summary."""

    qname: str  # "path.py::Class.method" / "path.py::func"
    file: str
    cls: "str | None"
    name: str
    node: ast.AST
    # -- summary (filled by _summarize) -----------------------------------
    acquires: set = dataclasses.field(default_factory=set)  # direct LockIds
    blocking: list = dataclasses.field(default_factory=list)  # [BlockingOp]
    calls: list = dataclasses.field(default_factory=list)  # [CallSite]
    lock_edges: list = dataclasses.field(default_factory=list)
    #: direct (held, inner, line) with-nesting edges

    @property
    def locked_convention(self) -> bool:
        return self.name.endswith("_locked")


def _terminal_name(expr: ast.AST) -> str:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _is_lockish_name(name: str) -> bool:
    low = name.lower()
    return "lock" in low or "mutex" in low or low.endswith("_cond") or low == "cond"


class _Module:
    """Per-file symbol tables: classes, functions, imports, locks, types."""

    def __init__(self, file: str, tree: ast.AST) -> None:
        self.file = file
        self.tree = tree
        self.classes: dict[str, ast.ClassDef] = {}
        self.functions: dict[str, str] = {}  # name -> qname
        self.imports: dict[str, tuple] = {}  # alias -> ("mod"|"sym", dotted)
        self.module_locks: dict[str, LockId] = {}
        self.lock_kind: dict[LockId, str] = {}  # "Lock"|"RLock"|"Condition"
        # (cls, attr) -> LockId for class locks; cls "" = module scope
        self.attr_locks: dict[tuple, LockId] = {}
        # (cls, attr) -> constructed class name (receiver-type inference)
        self.attr_types: dict[tuple, str] = {}

        for stmt in tree.body:
            if isinstance(stmt, (ast.Import,)):
                for a in stmt.names:
                    self.imports[a.asname or a.name.split(".")[0]] = (
                        "mod", a.name,
                    )
            elif isinstance(stmt, ast.ImportFrom) and stmt.module and not stmt.level:
                for a in stmt.names:
                    self.imports[a.asname or a.name] = (
                        "sym", f"{stmt.module}.{a.name}",
                    )
            elif isinstance(stmt, ast.ClassDef):
                self.classes[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        kind = _lock_ctor_kind(stmt.value)
                        if kind is not None:
                            lid = (self.file, "", t.id)
                            self.module_locks[t.id] = lid
                            self.lock_kind[lid] = kind


def _lock_ctor_kind(value: ast.AST) -> "str | None":
    """``threading.Lock()``/``RLock()``/``Condition(...)`` → its kind."""
    if not isinstance(value, ast.Call):
        return None
    name = _terminal_name(value.func)
    if name in _LOCK_CTORS:
        return name
    if name == "Condition":
        return "Condition"
    return None


# ---------------------------------------------------------------------------
# the graph


class ProjectGraph:
    """Call graph + summaries over every parsed file of a RepoCtx."""

    def __init__(self, repo: RepoCtx) -> None:
        self.repo = repo
        self.modules: dict[str, _Module] = {}
        self.funcs: dict[str, FuncInfo] = {}
        #: project-wide indexes
        self.class_files: dict[str, list] = {}  # class name -> [(file, node)]
        self.methods_by_name: dict[str, list] = {}  # meth -> [qname]
        self.class_methods: dict[tuple, str] = {}  # (file, cls, meth) -> qname
        self.class_bases: dict[tuple, list] = {}  # (file, cls) -> base names
        self.callers: dict[str, set] = {}  # qname -> {caller qnames}
        self.lock_kind: dict[LockId, str] = {}
        self._trans_acquires: "dict[str, set] | None" = None
        #: qname -> (terminal desc, terminal line, next-hop qname|None);
        #: a worklist fixpoint, NOT memoized recursion — a cycle-guard
        #: None cached mid-cycle would silently drop real chains
        #: depending on query order
        self._blocking_map: "dict[str, tuple] | None" = None
        self._construction_only: "set | None" = None

        for relpath in repo.py_files:
            ctx = repo.file(relpath)
            if ctx.tree is None:
                continue
            mod = _Module(relpath, ctx.tree)
            self.modules[relpath] = mod
            for cname, cnode in mod.classes.items():
                self.class_files.setdefault(cname, []).append((relpath, cnode))
                self.class_bases[(relpath, cname)] = [
                    _terminal_name(b) for b in cnode.bases
                ]
            self._index_functions(mod)

        for mod in self.modules.values():
            self._collect_class_state(mod)
        for info in self.funcs.values():
            self._summarize(info)
        for info in self.funcs.values():
            for site in info.calls:
                for q in site.resolved:
                    self.callers.setdefault(q, set()).add(info.qname)

    # -- indexing ----------------------------------------------------------
    def _index_functions(self, mod: _Module) -> None:
        def add(node, cls: "str | None") -> None:
            qname = (
                f"{mod.file}::{cls}.{node.name}" if cls else f"{mod.file}::{node.name}"
            )
            # first definition wins (overloads/conditionals are rare and
            # the first is the common branch)
            if qname in self.funcs:
                return
            info = FuncInfo(qname, mod.file, cls, node.name, node)
            self.funcs[qname] = info
            if cls is None:
                mod.functions.setdefault(node.name, qname)
            else:
                self.class_methods[(mod.file, cls, node.name)] = qname
                self.methods_by_name.setdefault(node.name, []).append(qname)

        for stmt in mod.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add(stmt, None)
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        add(sub, stmt.name)
        # nested defs participate as callees of their parent only; they
        # are walked inline by the summaries, not indexed

    def _collect_class_state(self, mod: _Module) -> None:
        """Lock attributes and receiver types per class (whole class body:
        locks are conventionally made in ``__init__`` but shared locks
        arrive through parameters anywhere)."""
        for cname, cnode in mod.classes.items():
            for node in ast.walk(cnode):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    kind = _lock_ctor_kind(node.value)
                    if kind == "Condition":
                        # Condition(self._lock) ALIASES the wrapped lock;
                        # Condition() owns its own
                        args = node.value.args  # type: ignore[union-attr]
                        target = None
                        if args:
                            wrapped = _terminal_name(args[0])
                            target = mod.attr_locks.get((cname, wrapped))
                            if target is None and wrapped:
                                target = (mod.file, cname, wrapped)
                                mod.attr_locks[(cname, wrapped)] = target
                                mod.lock_kind.setdefault(target, "Lock")
                        lid = target if target is not None else (
                            mod.file, cname, t.attr
                        )
                        mod.attr_locks[(cname, t.attr)] = lid
                        mod.lock_kind.setdefault(lid, "Condition")
                        if target is not None:
                            # remember the alias is condition-typed for
                            # the wait() exemption
                            mod.lock_kind[(mod.file, cname, t.attr)] = "Condition"
                    elif kind is not None:
                        lid = (mod.file, cname, t.attr)
                        mod.attr_locks[(cname, t.attr)] = lid
                        mod.lock_kind[lid] = kind
                    elif (
                        isinstance(node.value, ast.Name)
                        and _is_lockish_name(node.value.id)
                    ):
                        # a lock handed in by the owner (obs/metrics
                        # instruments share the registry lock)
                        lid = (mod.file, cname, t.attr)
                        mod.attr_locks[(cname, t.attr)] = lid
                        mod.lock_kind.setdefault(lid, "Lock")
                    elif isinstance(node.value, ast.Call):
                        ctor = self._resolve_class_name(mod, node.value.func)
                        if ctor is not None:
                            mod.attr_types[(cname, t.attr)] = ctor
        self.lock_kind.update(mod.lock_kind)

    def _resolve_class_name(self, mod: _Module, func: ast.AST) -> "str | None":
        """The project class a constructor expression names, if any."""
        name = _terminal_name(func)
        if name in mod.classes or name in self.class_files:
            return name
        imp = mod.imports.get(name)
        if imp is not None and imp[0] == "sym":
            tail = imp[1].rsplit(".", 1)[-1]
            if tail in self.class_files:
                return tail
        return None

    # -- per-function summaries -------------------------------------------
    def _lock_id_for(
        self, mod: _Module, cls: "str | None", expr: ast.AST,
        local_types: dict,
    ) -> "LockId | None":
        """The lock identity a ``with`` context / receiver expression
        names, or None when it is not lock-like."""
        if isinstance(expr, ast.Name):
            lid = mod.module_locks.get(expr.id)
            if lid is not None:
                return lid
            if _is_lockish_name(expr.id):
                lid = (mod.file, "", expr.id)
                mod.lock_kind.setdefault(lid, "Lock")
                self.lock_kind.setdefault(lid, "Lock")
                return lid
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                owner: "str | None" = None
                if base.id == "self" and cls is not None:
                    owner = cls
                    lid = self._class_lock(mod, cls, expr.attr)
                    if lid is not None:
                        return lid
                else:
                    owner = local_types.get(base.id)
                    if owner is not None:
                        ofile = self._class_file(mod, owner)
                        if ofile is not None:
                            omod = self.modules.get(ofile)
                            if omod is not None:
                                lid = self._class_lock(omod, owner, expr.attr)
                                if lid is not None:
                                    return lid
                if _is_lockish_name(expr.attr):
                    lid = (mod.file, owner or "?", expr.attr)
                    mod.lock_kind.setdefault(lid, "Lock")
                    self.lock_kind.setdefault(lid, "Lock")
                    return lid
        return None

    def _class_lock(self, mod: _Module, cls: str, attr: str) -> "LockId | None":
        """Lock attr of ``cls`` or (same-project) base classes."""
        seen = set()
        frontier = [(mod, cls)]
        while frontier:
            m, c = frontier.pop()
            if (m.file, c) in seen:
                continue
            seen.add((m.file, c))
            lid = m.attr_locks.get((c, attr))
            if lid is not None:
                return lid
            for base in self.class_bases.get((m.file, c), ()):
                bfile = self._class_file(m, base)
                if bfile is not None and bfile in self.modules:
                    frontier.append((self.modules[bfile], base))
        return None

    def _class_file(self, mod: _Module, cls: str) -> "str | None":
        """The file defining ``cls``, same module preferred."""
        if cls in mod.classes:
            return mod.file
        entries = self.class_files.get(cls)
        if entries and len(entries) == 1:
            return entries[0][0]
        imp = mod.imports.get(cls)
        if imp is not None and imp[0] == "sym" and entries:
            dotted_mod = imp[1].rsplit(".", 1)[0].replace(".", "/") + ".py"
            for file, _node in entries:
                if file == dotted_mod:
                    return file
        if entries:
            return entries[0][0]
        return None

    def _module_for_dotted(self, dotted: str) -> "str | None":
        file = dotted.replace(".", "/") + ".py"
        if file in self.modules:
            return file
        init = dotted.replace(".", "/") + "/__init__.py"
        if init in self.modules:
            return init
        return None

    def _resolve_call(
        self,
        mod: _Module,
        cls: "str | None",
        func: ast.AST,
        local_types: dict,
    ) -> list:
        """Candidate callee qnames for a call expression's func."""
        # plain name: local function, from-import, or class constructor
        if isinstance(func, ast.Name):
            q = mod.functions.get(func.id)
            if q is not None:
                return [q]
            ctor = self._resolve_class_name(mod, func)
            if ctor is not None:
                cfile = self._class_file(mod, ctor)
                if cfile is not None:
                    q = self.class_methods.get((cfile, ctor, "__init__"))
                    return [q] if q is not None else []
            imp = mod.imports.get(func.id)
            if imp is not None and imp[0] == "sym":
                dotted, sym = imp[1].rsplit(".", 1)
                mfile = self._module_for_dotted(dotted)
                if mfile is not None:
                    q = self.modules[mfile].functions.get(sym)
                    if q is not None:
                        return [q]
            return []
        if not isinstance(func, ast.Attribute):
            return []
        meth = func.attr
        base = func.value
        # self.m() — the class and its bases
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                q = self._method_on(mod, cls, meth)
                if q is not None:
                    return [q]
                return []
            # module alias: blockcache.configure(...)
            imp = mod.imports.get(base.id)
            if imp is not None:
                if imp[0] == "mod":
                    mfile = self._module_for_dotted(imp[1])
                elif imp[0] == "sym":
                    mfile = self._module_for_dotted(imp[1])
                else:
                    mfile = None
                if mfile is not None:
                    q = self.modules[mfile].functions.get(meth)
                    if q is not None:
                        return [q]
                    # ClassName.method(...) via from-import of a class
                    tail = imp[1].rsplit(".", 1)[-1]
                    q = self.class_methods.get((mfile, tail, meth))
                    if q is not None:
                        return [q]
            # typed local receiver: store = BlockStore(...); store.get()
            tname = local_types.get(base.id)
            if tname is not None:
                q = self._method_on(mod, tname, meth)
                return [q] if q is not None else []
            # ClassName.method(x) static-style call
            if base.id in mod.classes or base.id in self.class_files:
                q = self._method_on(mod, base.id, meth)
                if q is not None:
                    return [q]
        # typed attribute receiver: self.store.put() with
        # self.store = BlockStore(...) recorded in __init__
        if (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id == "self"
            and cls is not None
        ):
            tname = self._attr_type_on(mod, cls, base.attr)
            if tname is not None:
                q = self._method_on(mod, tname, meth)
                return [q] if q is not None else []
        # receiver-less attribute-name dispatch (the documented
        # approximation): only distinctive names, bounded fanout
        if meth in _COMMON_METHODS:
            return []
        candidates = self.methods_by_name.get(meth, ())
        if 0 < len(candidates) <= _DISPATCH_FANOUT:
            return list(candidates)
        return []

    def _method_on(self, mod: _Module, cls: str, meth: str) -> "str | None":
        """Method ``meth`` on ``cls`` or its (project) bases."""
        seen = set()
        frontier = [(mod, cls)]
        while frontier:
            m, c = frontier.pop()
            if (m.file, c) in seen:
                continue
            seen.add((m.file, c))
            cfile = self._class_file(m, c)
            if cfile is None:
                continue
            q = self.class_methods.get((cfile, c, meth))
            if q is not None:
                return q
            if cfile in self.modules:
                cm = self.modules[cfile]
                for base in self.class_bases.get((cfile, c), ()):
                    frontier.append((cm, base))
        return None

    def _attr_type_on(self, mod: _Module, cls: str, attr: str) -> "str | None":
        t = mod.attr_types.get((cls, attr))
        if t is not None:
            return t
        for base in self.class_bases.get((mod.file, cls), ()):
            bfile = self._class_file(mod, base)
            if bfile is not None and bfile in self.modules:
                t = self._attr_type_on(self.modules[bfile], base, attr)
                if t is not None:
                    return t
        return None

    def _local_types(self, fn: ast.AST, mod: _Module) -> dict:
        """Local ``x = ClassName(...)`` bindings (last write wins is
        ignored: first binding is the common case)."""
        out: dict[str, str] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                ctor = self._resolve_class_name(mod, node.value.func)
                if ctor is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.setdefault(t.id, ctor)
        return out

    def _summarize(self, info: FuncInfo) -> None:
        mod = self.modules[info.file]
        local_types = self._local_types(info.node, mod)
        open_aliases = {
            item.optional_vars.id
            for node in ast.walk(info.node)
            if isinstance(node, ast.With)
            for item in node.items
            if isinstance(item.context_expr, ast.Call)
            and _terminal_name(item.context_expr.func) == "open"
            and isinstance(item.optional_vars, ast.Name)
        }

        def held_at(node: ast.AST) -> tuple:
            """Locks syntactically held at ``node``, outermost first.
            Stops at the nearest enclosing function definition: a nested
            def's body runs when the closure is CALLED, not where it is
            defined, so an outer ``with lock`` does not cover it."""
            held = []
            cur = getattr(node, "parent", None)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break
                if isinstance(cur, ast.With):
                    for item in cur.items:
                        lid = self._lock_id_for(
                            mod, info.cls, item.context_expr, local_types
                        )
                        if lid is not None:
                            held.append(lid)
                cur = getattr(cur, "parent", None)
            held.reverse()  # outermost first
            return tuple(held)

        for node in ast.walk(info.node):
            if isinstance(node, ast.With):
                # `with A, B:` acquires in item order — B is taken while
                # A is held, exactly like the nested form, so earlier
                # items of the SAME statement are held context too
                stmt_held: list = []
                for item in node.items:
                    lid = self._lock_id_for(
                        mod, info.cls, item.context_expr, local_types
                    )
                    if lid is not None:
                        info.acquires.add(lid)
                        for outer in tuple(held_at(node)) + tuple(stmt_held):
                            if outer != lid:
                                info.lock_edges.append(
                                    (outer, lid, node.lineno)
                                )
                        stmt_held.append(lid)
            elif isinstance(node, ast.Call):
                held = held_at(node)
                resolved = self._resolve_call(
                    mod, info.cls, node.func, local_types
                )
                if resolved:
                    info.calls.append(
                        CallSite(
                            node.lineno, held, tuple(resolved),
                            ast.unparse(node.func) if hasattr(ast, "unparse")
                            else _terminal_name(node.func),
                        )
                    )
                    continue
                if _terminal_name(node.func) == "acquire":
                    recv = (
                        node.func.value
                        if isinstance(node.func, ast.Attribute)
                        else None
                    )
                    lid = (
                        self._lock_id_for(mod, info.cls, recv, local_types)
                        if recv is not None
                        else None
                    )
                    if lid is not None:
                        info.acquires.add(lid)
                        for outer in held:
                            if outer != lid:
                                info.lock_edges.append(
                                    (outer, lid, node.lineno)
                                )
                    continue
                desc = self._blocking_kind(
                    mod, info.cls, node, local_types, open_aliases, held
                )
                if desc is not None:
                    info.blocking.append(BlockingOp(node.lineno, desc, held))

    def _blocking_kind(
        self,
        mod: _Module,
        cls: "str | None",
        node: ast.Call,
        local_types: dict,
        open_aliases: set,
        held: tuple,
    ) -> "str | None":
        """The primitive blocking idiom an *unresolved* call expresses."""
        fn = node.func
        if isinstance(fn, ast.Name):
            if fn.id == "open":
                return "open() file IO"
            if fn.id == "sleep":
                return "sleep()"
            if fn.id == "device_put":
                return "device_put (host->device transfer)"
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        base = fn.value.id if isinstance(fn.value, ast.Name) else None
        meth = fn.attr
        if base == "os" and meth in _OS_BLOCKING:
            return f"os.{meth}() file IO"
        if base == "time" and meth == "sleep":
            return "time.sleep()"
        if base == "subprocess" and meth in _SUBPROCESS_CALLS:
            return f"subprocess.{meth}()"
        if base == "mmap" and meth == "mmap":
            return "mmap.mmap() file mapping"
        if base == "jax" and meth in ("device_put", "device_get"):
            return f"jax.{meth}() (device transfer)"
        if meth == "block_until_ready":
            return "block_until_ready() device wait"
        if meth in _SOCKET_METHODS:
            return f".{meth}() socket IO"
        if meth in ("read", "write") and base in open_aliases:
            return f"file .{meth}() on '{base}'"
        if meth == "result" and not _kw(node, "timeout"):
            return ".result() future wait"
        if meth == "get" and "queue" in _terminal_name(fn.value).lower():
            # queue.Queue.get() blocks indefinitely by default; typing is
            # name-based (a receiver CALLED a queue — `q.get()`,
            # `self._job_queue.get()`) — the idiom the codebase uses
            b = _kw(node, "block")
            if not (isinstance(b, ast.Constant) and b.value is False):
                return (
                    f".get() on queue '{_terminal_name(fn.value)}' "
                    "(blocking wait)"
                )
        if meth == "join":
            # thread/process join: no positional args, or timeout only —
            # ``sep.join(parts)`` always has exactly one positional arg
            if not node.args:
                return ".join() thread wait"
            return None
        if meth in ("wait", "wait_for"):
            recv_lid = (
                self._lock_id_for(mod, cls, fn.value, local_types)
                if isinstance(fn.value, (ast.Name, ast.Attribute))
                else None
            )
            if recv_lid is not None and recv_lid in held:
                # Condition.wait on the HELD lock releases it for the
                # duration of the wait — the sanctioned dispatcher
                # pattern, not blocking-under-lock
                return None
            return f".{meth}() blocking wait"
        if meth == "shutdown":
            w = _kw(node, "wait")
            if w is not None and isinstance(w, ast.Constant) and w.value is False:
                return None
            return ".shutdown() pool/server drain"
        return None

    # -- fixpoints ---------------------------------------------------------
    def trans_acquires(self, qname: str) -> set:
        """Every lock a call to ``qname`` may acquire, transitively."""
        if self._trans_acquires is None:
            acq = {q: set(f.acquires) for q, f in self.funcs.items()}
            changed = True
            while changed:
                changed = False
                for q, f in self.funcs.items():
                    mine = acq[q]
                    before = len(mine)
                    for site in f.calls:
                        for callee in site.resolved:
                            if callee in acq:
                                mine |= acq[callee]
                    if len(mine) != before:
                        changed = True
            self._trans_acquires = acq
        return self._trans_acquires.get(qname, set())

    def blocking_chain(self, qname: str) -> "tuple | None":
        """``(desc, line, chain)`` witnessing the nearest blocking op
        reachable from ``qname`` (chain = list of qnames walked, the
        last one containing the op), or None.  Blocking ops that sit
        under a lock acquired INSIDE the callee are still reported: the
        caller's lock is held around the whole call either way."""
        if self._blocking_map is None:
            blocks: dict[str, tuple] = {}
            for q, f in self.funcs.items():
                if f.blocking:
                    op = f.blocking[0]
                    blocks[q] = (op.desc, op.line, None)
            changed = True
            while changed:
                changed = False
                for q, f in self.funcs.items():
                    if q in blocks:
                        continue
                    hit = next(
                        (
                            c
                            for site in f.calls
                            for c in site.resolved
                            if c != q and c in blocks
                        ),
                        None,
                    )
                    if hit is not None:
                        sub = blocks[hit]
                        blocks[q] = (sub[0], sub[1], hit)
                        changed = True
            self._blocking_map = blocks
        ent = self._blocking_map.get(qname)
        if ent is None:
            return None
        chain = [qname]
        seen = {qname}
        cur = ent[2]
        while cur is not None and cur not in seen and len(chain) < 32:
            chain.append(cur)
            seen.add(cur)
            cur = self._blocking_map[cur][2]
        return (ent[0], ent[1], chain)

    def construction_only(self, qname: str) -> bool:
        """True when every (resolved) caller chain roots in ``__init__``
        — the lock is uncontended by construction (LT001's ``__init__``
        exemption, carried through the call graph)."""
        if self._construction_only is None:
            # start optimistic for everything with callers, then strip
            inits = {
                q for q, f in self.funcs.items() if f.name == "__init__"
            }
            candidates = {
                q for q in self.funcs if q in self.callers and q not in inits
            }
            changed = True
            while changed:
                changed = False
                for q in list(candidates):
                    ok = all(
                        c in inits or c in candidates
                        for c in self.callers.get(q, ())
                    )
                    if not ok:
                        candidates.discard(q)
                        changed = True
            self._construction_only = inits | candidates
        return qname in self._construction_only

    # -- iteration helpers -------------------------------------------------
    def functions(self) -> Iterator[FuncInfo]:
        return iter(self.funcs.values())

    def lock_name(self, lid: LockId) -> str:
        file, owner, attr = lid
        if owner and owner not in ("?",):
            return f"{owner}.{attr}"
        return attr

    def kind(self, lid: LockId) -> str:
        return self.lock_kind.get(lid, "Lock")


def _kw(node: ast.Call, name: str) -> "ast.AST | None":
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def get_graph(repo: RepoCtx) -> ProjectGraph:
    """The memoized project graph for this lint run (built once, shared
    by LT006/LT007/LT008)."""
    g = repo.cache.get("callgraph")
    if g is None:
        g = repo.cache["callgraph"] = ProjectGraph(repo)
    return g
