"""LT001 — lock discipline for shared mutable state.

The concurrent subsystems (``io/blockcache.py``'s process-wide cache,
``runtime/fetch.py``'s handle/stat objects, the ``obs/`` writers) keep
their invariants by construction: state shared across threads is only
touched under the owning ``threading.Lock``/``RLock``.  A violation is a
data race that no tier-1 run reproduces deterministically — exactly the
class of bug static analysis must own.

The rule is evidence-based, not name-based: a name is **guarded** when
the module/class demonstrably uses its lock for it — i.e. at least one
mutation of that name happens inside ``with <lock>``.  Then:

* any *mutation* of a guarded name outside the lock is a finding
  (assignment, augmented assignment, subscript/attribute store, or a
  mutating method call — ``pop``/``clear``/``append``/``update``/...);
* any *read* of a guarded name inside a ``return`` expression outside
  the lock is a finding — the "stats path" pattern, where an accessor
  hands out a torn or mid-update view (``dict(self._acc)`` while a
  writer thread mutates it raises ``RuntimeError: dictionary changed
  size``; multi-field snapshots interleave).
  Reads in other positions are deliberately NOT flagged: flow-sensitive
  read analysis drowns the signal in false positives.

Two scopes share the machinery:

* **module scope** — a module-level ``_lock = threading.Lock()`` guards
  module globals (``io/blockcache.py``'s design).  Mutations count when
  the name is ``global``-declared, or a subscript/attribute/mutating
  call on a module-level name.
* **class scope** — a ``self.<x> = threading.Lock()`` in ``__init__``
  guards ``self`` attributes.  ``__init__`` itself is exempt
  (construction happens-before sharing).

Convention: a function whose name ends in ``_locked`` is exempt — it
documents "caller holds the lock" (``_evict_to_budget_locked``), and
flagging it would force noqa noise on a pattern the repo already names.
"""

from __future__ import annotations

import ast
from typing import Iterator

from land_trendr_tpu.lintkit.core import (
    Checker,
    FileCtx,
    Finding,
    ancestors,
    enclosing_function,
    in_with_lock,
)

__all__ = ["LockDisciplineChecker"]

#: method calls that mutate their receiver (list/dict/set/OrderedDict)
_MUTATORS = frozenset(
    {
        "append", "extend", "insert", "remove", "pop", "popitem", "clear",
        "update", "setdefault", "add", "discard", "move_to_end", "sort",
        "reverse", "appendleft", "popleft",
    }
)


def _is_lock_ctor(node: ast.AST) -> bool:
    """``threading.Lock()`` / ``threading.RLock()`` (or bare ``Lock()``)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else ""
    )
    return name in ("Lock", "RLock")


def _locked_exempt(node: ast.AST) -> bool:
    """Inside a ``*_locked``-suffixed function (caller-holds-lock)."""
    fn = enclosing_function(node)
    while fn is not None:
        if fn.name.endswith("_locked"):
            return True
        fn = enclosing_function(fn)
    return False


def _global_names(fn: ast.AST) -> set:
    return {
        n
        for stmt in ast.walk(fn)
        if isinstance(stmt, ast.Global)
        for n in stmt.names
    }


class _Scope:
    """One lock domain (a module or a class) under analysis."""

    def __init__(self, owner, lock_names: set, is_module: bool) -> None:
        self.owner = owner
        self.lock_names = lock_names
        self.is_module = is_module

    def is_lock_expr(self, expr: ast.AST) -> bool:
        if self.is_module:
            return isinstance(expr, ast.Name) and expr.id in self.lock_names
        return (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in self.lock_names
        )

    def state_name(self, expr: ast.AST) -> "str | None":
        """The guarded-candidate name an expression refers to, if any."""
        if self.is_module:
            return expr.id if isinstance(expr, ast.Name) else None
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            return expr.attr
        return None


def _iter_mutations(scope: _Scope, body: ast.AST) -> Iterator[tuple]:
    """Yield ``(node, name, kind)`` for every mutation of scope state.

    ``kind`` is a short human label for the message.  Module scope
    requires plain-name assigns to be ``global``-declared (otherwise the
    target is a function local, not shared state).
    """
    for node in ast.walk(body):
        targets: list[ast.AST] = []
        if isinstance(node, (ast.Assign,)):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target] if node.target is not None else []
        for t in targets:
            name = scope.state_name(t)
            if name is not None:
                if scope.is_module and isinstance(t, ast.Name):
                    fn = enclosing_function(node)
                    if fn is None or name not in _global_names(fn):
                        continue
                yield node, name, "assignment"
            # container stores: _entries[key] = ..., self._counts[i] += ...
            if isinstance(t, ast.Subscript):
                name = scope.state_name(t.value)
                if name is not None:
                    yield node, name, "item assignment"
            # attribute stores on a guarded object: _tl.readahead = ...
            # (module scope) and self._stats.hits = ... (class scope both
            # resolve through the store target's value expression)
            if isinstance(t, ast.Attribute):
                name = scope.state_name(t.value)
                if name is not None:
                    yield node, name, "attribute assignment"
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATORS:
                name = scope.state_name(node.func.value)
                if name is not None:
                    yield node, name, f".{node.func.attr}() call"


def _iter_return_reads(scope: _Scope, body: ast.AST, guarded: set) -> Iterator[tuple]:
    """Yield ``(node, name)`` for guarded-state reads inside ``return``."""
    for node in ast.walk(body):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        for sub in ast.walk(node.value):
            name = scope.state_name(sub)
            if name in guarded:
                # reading self._x where _x is guarded; for module scope a
                # bare Name load suffices (Store contexts were already
                # yielded as mutations above — returns only Load)
                if isinstance(sub, (ast.Name, ast.Attribute)) and isinstance(
                    getattr(sub, "ctx", ast.Load()), ast.Load
                ):
                    yield node, name


class LockDisciplineChecker(Checker):
    rule_id = "LT001"
    title = "shared state mutated or snapshot-read outside its lock"

    def check_file(self, ctx: FileCtx) -> Iterator[Finding]:
        tree = ctx.tree
        assert tree is not None
        yield from self._check_module_scope(ctx, tree)
        classes = {
            node.name: node
            for node in ast.walk(tree)
            if isinstance(node, ast.ClassDef)
        }
        for node in classes.values():
            yield from self._check_class_scope(ctx, node, classes)

    # -- module-level locks (io/blockcache.py design) ----------------------
    def _check_module_scope(self, ctx: FileCtx, tree) -> Iterator[Finding]:
        lock_names = set()
        module_names = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        module_names.add(t.id)
                        if _is_lock_ctor(stmt.value):
                            lock_names.add(t.id)
            elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                module_names.add(stmt.target.id)
        if not lock_names:
            return
        scope = _Scope(tree, lock_names, is_module=True)

        # pass 1: evidence — names mutated under the lock are "guarded"
        guarded = set()
        mutations = []
        for node, name, kind in _iter_mutations(scope, tree):
            if name not in module_names or enclosing_function(node) is None:
                continue  # module top-level init is construction, not sharing
            mutations.append((node, name, kind))
            if in_with_lock(node, scope.is_lock_expr):
                guarded.add(name)
        # pass 2: violations
        for node, name, kind in mutations:
            if name not in guarded:
                continue
            if in_with_lock(node, scope.is_lock_expr) or _locked_exempt(node):
                continue
            yield Finding(
                ctx.path, node.lineno, self.rule_id,
                f"{kind} to lock-guarded module state '{name}' outside "
                f"'with {sorted(lock_names)[0]}'",
            )
        for node, name in _iter_return_reads(scope, tree, guarded):
            if enclosing_function(node) is None:
                continue
            if in_with_lock(node, scope.is_lock_expr) or _locked_exempt(node):
                continue
            yield Finding(
                ctx.path, node.lineno, self.rule_id,
                f"return reads lock-guarded module state '{name}' outside "
                f"'with {sorted(lock_names)[0]}' (torn snapshot)",
            )

    # -- class-held locks (obs/, runtime/fetch.py design) ------------------
    def _own_lock_attrs(self, cls: ast.ClassDef) -> set:
        """Lock attributes ``cls``'s own ``__init__`` assigns."""
        init = next(
            (
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "__init__"
            ),
            None,
        )
        locks: set = set()
        if init is None:
            return locks
        for node in ast.walk(init):
            if isinstance(node, ast.Assign) and (
                _is_lock_ctor(node.value)
                # a lock handed in by the owner (obs/metrics.py shares the
                # registry lock with its instruments): self._lock = lock
                or (
                    isinstance(node.value, ast.Name)
                    and "lock" in node.value.id.lower()
                )
            ):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        locks.add(t.attr)
        return locks

    def _lock_attrs(self, cls: ast.ClassDef, classes: dict, depth: int = 0) -> set:
        """Own lock attributes plus same-module base classes' (so
        subclasses of a lock-holding base — the obs/metrics instrument
        hierarchy — are analysed under the inherited lock)."""
        locks = self._own_lock_attrs(cls)
        if depth < 4:
            for base in cls.bases:
                if isinstance(base, ast.Name) and base.id in classes:
                    locks |= self._lock_attrs(classes[base.id], classes, depth + 1)
        return locks

    def _check_class_scope(
        self, ctx: FileCtx, cls: ast.ClassDef, classes: dict
    ) -> Iterator[Finding]:
        init = next(
            (
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "__init__"
            ),
            None,
        )
        lock_names = self._lock_attrs(cls, classes)
        if not lock_names:
            return
        scope = _Scope(cls, lock_names, is_module=False)

        def exempt(node: ast.AST) -> bool:
            fn = enclosing_function(node)
            return fn is init or _locked_exempt(node)

        guarded = set()
        mutations = []
        for node, name, kind in _iter_mutations(scope, cls):
            if name in lock_names:
                continue
            mutations.append((node, name, kind))
            if in_with_lock(node, scope.is_lock_expr) and not (
                enclosing_function(node) is init
            ):
                guarded.add(name)
        for node, name, kind in mutations:
            if name not in guarded or exempt(node):
                continue
            if in_with_lock(node, scope.is_lock_expr):
                continue
            yield Finding(
                ctx.path, node.lineno, self.rule_id,
                f"{cls.name}: {kind} to lock-guarded attribute "
                f"'self.{name}' outside 'with self.{sorted(lock_names)[0]}'",
            )
        for node, name in _iter_return_reads(scope, cls, guarded):
            if exempt(node) or in_with_lock(node, scope.is_lock_expr):
                continue
            yield Finding(
                ctx.path, node.lineno, self.rule_id,
                f"{cls.name}: return reads lock-guarded attribute "
                f"'self.{name}' outside 'with self.{sorted(lock_names)[0]}' "
                "(torn snapshot)",
            )
