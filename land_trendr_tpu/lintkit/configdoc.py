"""LT004 — RunConfig / CLI / README coupling.

``RunConfig`` (runtime/driver.py) is the run's one configuration
surface; ``cli.py``'s ``segment`` flags and README's §Run configuration
table are its two public projections.  They drift independently: a
field added for a new subsystem (PR 2's ``feed_cache_mb``, PR 3's
``fetch_depth``) is easy to wire into one projection and forget in the
other, leaving a knob that exists but cannot be set from the command
line, or documentation describing a field that no longer exists.

The rule parses all three sources and checks the triangle:

* every ``RunConfig`` dataclass field has a ``segment`` CLI flag —
  ``foo_bar`` ↔ ``--foo-bar`` by convention, with an explicit alias
  table for the negated/composite flags (``resume`` ↔ ``--no-resume``,
  ``change_filt`` ↔ ``--change``/``--change-*``, ``params`` ↔
  ``--params-json`` + the per-parameter flags, ...);
* every field has a row in README.md's ``## Run configuration`` table
  (a row is ``| `field` | ... |``);
* every README table row names a real field (the reverse direction —
  catches renames/removals whose doc row survived).

CLI flags with no field are deliberately NOT checked: many segment
flags are not run configuration (``--lazy``, ``--mesh``, ``--trace``,
``--composite`` act before/around ``RunConfig``), and enumerating them
here would just create a second alias table to drift.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from land_trendr_tpu.lintkit.core import Checker, Finding, RepoCtx

__all__ = ["ConfigDocChecker"]

DRIVER = "land_trendr_tpu/runtime/driver.py"
SERVE = "land_trendr_tpu/serve/config.py"
CLI = "land_trendr_tpu/cli.py"
README = "README.md"

#: RunConfig fields whose CLI projection is not the mechanical
#: --dashed-name
FLAG_ALIASES: dict[str, tuple[str, ...]] = {
    "resume": ("no-resume",),
    "feed_readahead": ("no-feed-readahead",),
    "fetch_packed": ("packed-fetch", "no-packed-fetch"),
    "upload_packed": ("packed-upload", "no-packed-upload"),
    "ftv_indices": ("ftv",),
    "change_filt": ("change",),
    "params": ("params-json",),
}

#: the ServeConfig alias table (the serve triangle's exceptions)
SERVE_FLAG_ALIASES: dict[str, tuple[str, ...]] = {
    "telemetry": ("no-telemetry",),
    "debug_endpoints": ("no-debug-endpoints",),
}

ROUTE = "land_trendr_tpu/fleet/config.py"

#: the RouterConfig alias table (the fleet triangle's exceptions)
ROUTE_FLAG_ALIASES: dict[str, tuple[str, ...]] = {
    "telemetry": ("no-telemetry",),
    "replicas": ("replica",),
    "affinity": ("no-affinity",),
    "journal": ("no-journal",),
}

LOADGEN = "land_trendr_tpu/loadgen/config.py"

#: the LoadConfig alias table — every field projects mechanically
LOAD_FLAG_ALIASES: dict[str, tuple[str, ...]] = {}

#: the coupling triangles this rule checks: each names a config
#: dataclass, the CLI subcommand projecting it, the README section
#: documenting it, and the alias table for non-mechanical flags.  A new
#: config surface (ServeConfig was the first) adds a row here and gets
#: the same drift protection RunConfig has.
TRIANGLES: tuple[dict, ...] = (
    {
        "file": DRIVER,
        "cls": "RunConfig",
        "subcommand": "segment",
        "section": "## run configuration",
        "aliases": FLAG_ALIASES,
    },
    {
        "file": SERVE,
        "cls": "ServeConfig",
        "subcommand": "serve",
        "section": "## serve configuration",
        "aliases": SERVE_FLAG_ALIASES,
    },
    {
        "file": ROUTE,
        "cls": "RouterConfig",
        "subcommand": "route",
        "section": "## fleet configuration",
        "aliases": ROUTE_FLAG_ALIASES,
    },
    {
        "file": LOADGEN,
        "cls": "LoadConfig",
        "subcommand": "load",
        "section": "## load configuration",
        "aliases": LOAD_FLAG_ALIASES,
    },
)

_ROW_RE = re.compile(r"^\|\s*`([A-Za-z_][A-Za-z0-9_]*)`")


def _dataclass_fields(
    repo: RepoCtx, path: str, cls_name: str
) -> "list[tuple[str, int]]":
    """(field, line) for every dataclass field of ``cls_name``."""
    tree = repo.file(path).tree
    if tree is None:
        return []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            return [
                (stmt.target.id, stmt.lineno)
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
                and not stmt.target.id.startswith("_")
            ]
    return []


def _flag_strings(node: ast.Call) -> Iterator[str]:
    for a in node.args:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            if a.value.startswith("--"):
                yield a.value[2:]


def _cli_flags(repo: RepoCtx, subcommand: str) -> set:
    """``--flag`` strings reachable from ONE subcommand's subparser.

    Scoped, not global: several subcommands define same-named flags
    (``--scale``/``--index`` exist on ``pixel`` too; ``--workdir`` on
    both ``segment`` and ``serve``), so a flag dropped from the checked
    subcommand must not stay green via another one.  The scope is the
    variable assigned from ``add_parser(subcommand)``, plus its
    ``add_argument_group``/mutually-exclusive-group variables, plus
    every ``add_argument`` inside a module function the parser is
    passed to (the ``_add_param_flags(seg)`` pattern).  If no such
    subparser exists (a restructured cli.py), every flag counts — a
    conservative fallback rather than a wall of false positives.
    """
    tree = repo.file(CLI).tree
    flags: set = set()
    if tree is None:
        return flags

    seg_vars: set = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Attribute)
            and node.value.func.attr == "add_parser"
            and node.value.args
            and isinstance(node.value.args[0], ast.Constant)
            and node.value.args[0].value == subcommand
        ):
            seg_vars.update(
                t.id for t in node.targets if isinstance(t, ast.Name)
            )
    if not seg_vars:
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
            ):
                flags.update(_flag_strings(node))
        return flags

    # argument-group variables of the segment parser count as the parser
    group_vars = set(seg_vars)
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Attribute)
            and node.value.func.attr
            in ("add_argument_group", "add_mutually_exclusive_group")
            and isinstance(node.value.func.value, ast.Name)
            and node.value.func.value.id in group_vars
        ):
            group_vars.update(
                t.id for t in node.targets if isinstance(t, ast.Name)
            )

    helper_names: set = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and any(
                isinstance(a, ast.Name) and a.id in seg_vars
                for a in node.args
            )
        ):
            helper_names.add(node.func.id)

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name in helper_names:
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "add_argument"
                ):
                    flags.update(_flag_strings(sub))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in group_vars
        ):
            flags.update(_flag_strings(node))
    return flags


def _readme_config_rows(
    repo: RepoCtx, section: str
) -> "list[tuple[str, int]]":
    """(field, line) for each table row of one README ``##`` section."""
    rows: list[tuple[str, int]] = []
    in_section = False
    for i, line in enumerate(repo.read_text(README).splitlines(), 1):
        if line.startswith("## "):
            in_section = line.strip().lower() == section
            continue
        if in_section:
            m = _ROW_RE.match(line)
            if m and m.group(1) not in ("field",):  # skip the header row
                rows.append((m.group(1), i))
    return rows


class ConfigDocChecker(Checker):
    rule_id = "LT004"
    title = "config field without CLI flag / README row (or vice versa)"

    def inputs(self, repo: RepoCtx) -> set:
        return {t["file"] for t in TRIANGLES} | {CLI, README}

    def check(self, repo: RepoCtx) -> Iterator[Finding]:
        if not repo.exists(CLI):
            return
        for tri in TRIANGLES:
            if not repo.exists(tri["file"]):
                continue
            yield from self._check_triangle(repo, tri)

    def _check_triangle(self, repo: RepoCtx, tri: dict) -> Iterator[Finding]:
        cls, path = tri["cls"], tri["file"]
        fields = _dataclass_fields(repo, path, cls)
        field_names = {f for f, _ in fields}
        flags = _cli_flags(repo, tri["subcommand"])
        rows = (
            _readme_config_rows(repo, tri["section"])
            if repo.exists(README)
            else []
        )
        row_names = {r for r, _ in rows}
        section_title = tri["section"][3:].capitalize()

        for field, line in fields:
            expected = tri["aliases"].get(
                field, (field.replace("_", "-"),)
            )
            if not any(f in flags for f in expected):
                yield Finding(
                    path, line, self.rule_id,
                    f"{cls}.{field} has no CLI flag on the "
                    f"'{tri['subcommand']}' subcommand (expected one of "
                    f"{', '.join('--' + f for f in expected)}) — the "
                    "knob cannot be set from the command line",
                )
            if field not in row_names:
                yield Finding(
                    path, line, self.rule_id,
                    f"{cls}.{field} has no row in README.md's "
                    f"'## {section_title}' table",
                )
        for row, line in rows:
            if row not in field_names:
                yield Finding(
                    README, line, self.rule_id,
                    f"README {section_title} row '{row}' names no "
                    f"{cls} field (renamed or removed?)",
                )
