"""LT012 — durable artifacts land atomically (tmp + rename) or not at all.

The repo's crash-consistency story is one idiom applied everywhere:
write the bytes to a sibling ``*.tmp`` path, ``os.replace`` onto the
final name — rename *is* the commit (manifest artifacts, tune store,
snapshot publish, history compaction, block-store segments, the lint
baseline itself).  A direct ``open(final, "w")`` into an artifact tree
re-introduces the torn-file window those helpers exist to close: a
SIGKILL mid-``json.dump`` leaves a half-written manifest/report that a
resume or the perf gate then *reads*.

A write is a finding when it is **non-atomic** — plain ``open(path,
"w"/"wb"/"x")`` or ``Path.write_text``/``write_bytes`` — AND it targets
a durable artifact, recognized two ways through :mod:`.dataflow` string
flow:

* a constant path fragment naming the artifact trees: ``manifest``,
  ``snapshot``/``.snap``, ``store``, ``result``, ``profile``,
  ``decisions``, ``baseline``, committed ``CAPACITY_*``/``PERF_*``/
  ``FAULTSOAK_*``-style reports;
* a report-output sink by name: ``args.out`` / ``out_path`` / ``out``
  — the benchmark ``--out`` artifacts the perf gate and the committed
  baselines consume.

Blessed, i.e. never a finding:

* the path carries a scratch fragment (``tmp``/``.part``) or flows from
  ``tempfile`` (``mkstemp``/``mkdtemp``/``NamedTemporaryFile``);
* the written path flows into an ``os.replace``/``os.rename`` *source*
  argument in the same function (the write IS the tmp leg of the
  idiom);
* append mode (``"a"``) — the O_APPEND line-atomic log discipline is a
  different, also-sanctioned contract;
* ``tests/`` (fixtures model torn files on purpose).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from land_trendr_tpu.lintkit.core import (
    Checker,
    FileCtx,
    Finding,
    enclosing_function,
)
from land_trendr_tpu.lintkit.dataflow import (
    EMPTY,
    FunctionFlow,
    dotted_call,
)

__all__ = ["DurableWriteChecker"]

_ARTIFACT_RE = re.compile(
    r"manifest|snapshot|\.snap|store|result|profile|decision|baseline"
    r"|capacity|faultsoak|perf_|ident",
    re.IGNORECASE,
)

_SCRATCH_RE = re.compile(r"tmp|\.part", re.IGNORECASE)

_TMP_LABEL = "<tempfile>"

_TEMPFILE_CALLS = {
    "mkstemp", "mkdtemp", "mktemp", "NamedTemporaryFile",
    "TemporaryDirectory", "TemporaryFile",
}

#: path expressions that ARE the report-output sink by name
_OUT_NAME_RE = re.compile(r"(^|_)(out|output)(_path|_file|_json)?$")


def _seeds(node: ast.AST) -> frozenset:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset((node.value,))
    if isinstance(node, ast.Call):
        name = dotted_call(node)
        if name.rsplit(".", 1)[-1] in _TEMPFILE_CALLS:
            return frozenset((_TMP_LABEL,))
    return EMPTY


def _write_mode(call: ast.Call) -> "str | None":
    """The constant mode of an ``open()`` call, or None when absent or
    non-constant (non-constant modes are not this rule's business)."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _terminal_ident(expr: ast.AST) -> str:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


class DurableWriteChecker(Checker):
    rule_id = "LT012"
    title = "non-atomic write into a durable artifact tree"

    def check_file(self, ctx: FileCtx) -> Iterator[Finding]:
        if ctx.path.startswith("tests/"):
            return
        tree = ctx.tree
        if tree is None:
            return
        flows: dict[int, FunctionFlow] = {}

        def flow_for(node: ast.AST) -> FunctionFlow:
            scope = enclosing_function(node) or tree
            key = id(scope)
            if key not in flows:
                flows[key] = FunctionFlow(scope, _seeds)
            return flows[key]

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            path_expr = self._written_path(node)
            if path_expr is None:
                continue
            flow = flow_for(node)
            frags = flow.labels(path_expr)
            if _TMP_LABEL in frags:
                continue
            if any(_SCRATCH_RE.search(f) for f in frags):
                continue
            artifact = [
                f for f in frags
                if f != _TMP_LABEL and _ARTIFACT_RE.search(f)
            ]
            sink = _OUT_NAME_RE.search(_terminal_ident(path_expr) or "")
            if not artifact and sink is None:
                continue
            if self._flows_into_replace(node, path_expr, flow):
                continue
            what = (
                f"artifact path fragment {artifact[0]!r}"
                if artifact
                else f"report output sink '{_terminal_ident(path_expr)}'"
            )
            yield Finding(
                file=ctx.path,
                line=node.lineno,
                rule_id=self.rule_id,
                message=(
                    f"non-atomic write into a durable artifact tree "
                    f"({what}) — write a sibling .tmp and os.replace() "
                    "onto the final name (rename is the commit)"
                ),
            )

    # -- write-site recognition -------------------------------------------
    def _written_path(self, call: ast.Call) -> "ast.AST | None":
        """The path expression this call writes non-atomically, if any."""
        name = dotted_call(call)
        if name == "open" and call.args:
            mode = _write_mode(call)
            if mode is not None and any(c in mode for c in "wx"):
                return call.args[0]
            return None
        # keyed on the attribute, not the dotted name: the receiver is
        # often not a name chain at all — ``(root / "x.json").write_text``
        if isinstance(call.func, ast.Attribute) and call.func.attr in (
            "write_text", "write_bytes"
        ):
            return call.func.value
        return None

    def _flows_into_replace(
        self, write: ast.Call, path_expr: ast.AST, flow: FunctionFlow
    ) -> bool:
        """True when the written path is the SOURCE of an ``os.replace``
        / ``os.rename`` in the same function — the blessed tmp leg."""
        scope = enclosing_function(write)
        if scope is None:
            return False
        path_frags = flow.labels(path_expr)
        path_name = _terminal_ident(path_expr)
        for node in ast.walk(scope):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if dotted_call(node) not in ("os.replace", "os.rename"):
                continue
            src = node.args[0]
            if path_name and _terminal_ident(src) == path_name:
                return True
            src_frags = flow.labels(src)
            if path_frags and path_frags & src_frags:
                return True
        return False
