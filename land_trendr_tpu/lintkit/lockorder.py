"""LT006 — lock-order cycles (deadlock candidates) across the call graph.

Two threads that acquire the same two locks in opposite orders will,
eventually, do so at the same time — and the continent-scale runs this
system targets (arXiv:1807.01751) turn "eventually" into "this week".
The hazard is invisible statement-locally: each ``with`` looks fine; the
cycle only exists in the *acquired-while-held* relation, and after PR 7
that relation spans modules (a server callback holding the serve lock
can reach the metrics registry lock through three calls).

The rule computes, over :mod:`.callgraph`'s project graph:

* the **acquired-while-held edge set**: lock ``A`` → lock ``B`` when
  some function acquires ``B`` (a nested ``with``/``.acquire()``) while
  ``A`` is held — directly, or transitively through resolved call edges
  (the callee's transitive acquisition set);
* **cycles** in that digraph (Tarjan SCC): each strongly-connected
  component with more than one lock is a deadlock candidate, reported
  once with every witness edge (file:line and the call it rides);
* **same-instance re-acquisition**: a function holding non-reentrant
  ``threading.Lock`` ``A`` whose direct ``self.``/same-module callee
  acquires ``A`` again — not a cycle, a certain deadlock on first
  execution.

``Condition.wait`` gets its documented caveat for free: a condition
built as ``Condition(self._lock)`` *aliases* the wrapped lock in the
identity model, so ``with self._cond`` and ``with self._lock`` are one
node (no false A→B edge between them), and the wait itself acquires
nothing.  Lock identity is class-level — instance-level ordering
(``a._lock`` before ``b._lock`` of one class, sorted by some key) is
indistinguishable statically and would be flagged; such deliberate
protocols belong in the baseline with the ordering rule written down.

Scope: ``tests/`` is excluded (fixtures model violations on purpose).
"""

from __future__ import annotations

from typing import Iterator

from land_trendr_tpu.lintkit.callgraph import get_graph
from land_trendr_tpu.lintkit.core import Checker, Finding, RepoCtx

__all__ = ["LockOrderChecker"]


def _sccs(nodes: set, edges: dict) -> list:
    """Tarjan strongly-connected components (iterative)."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    out: list = []
    counter = [0]

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(sorted(edges.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(edges.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


class LockOrderChecker(Checker):
    rule_id = "LT006"
    title = "lock-order cycle (deadlock candidate) in the acquired-while-held graph"

    def inputs(self, repo: RepoCtx) -> "set[str] | None":
        # interprocedural: any package/tool file can add an edge
        return {f for f in repo.py_files if not f.startswith("tests/")}

    def check(self, repo: RepoCtx) -> Iterator[Finding]:
        graph = get_graph(repo)
        # edge -> (file, line, symbol, via); first witness wins
        edges: dict = {}
        reacq: list = []
        for info in graph.functions():
            if info.file.startswith("tests/"):
                continue
            symbol = f"{info.cls}.{info.name}" if info.cls else info.name
            for held, inner, line in info.lock_edges:
                edges.setdefault(
                    (held, inner),
                    (info.file, line, symbol, "nested with"),
                )
            for site in info.calls:
                if not site.held:
                    continue
                same_instance = site.label.startswith("self.") or "." not in site.label
                for callee in site.resolved:
                    acquired = graph.trans_acquires(callee)
                    direct = (
                        graph.funcs[callee].acquires
                        if callee in graph.funcs
                        else set()
                    )
                    for held in site.held:
                        for lid in acquired:
                            if lid == held:
                                continue
                            edges.setdefault(
                                (held, lid),
                                (
                                    info.file, site.line, symbol,
                                    f"call to {site.label}()",
                                ),
                            )
                        if (
                            same_instance
                            and held in direct
                            and graph.kind(held) == "Lock"
                            and callee in graph.funcs
                            and not graph.funcs[callee].locked_convention
                        ):
                            reacq.append(
                                (info.file, site.line, symbol, held, site.label)
                            )

        for file, line, symbol, held, label in reacq:
            yield Finding(
                file, line, self.rule_id,
                f"re-acquisition deadlock: '{label}()' acquires non-"
                f"reentrant lock '{graph.lock_name(held)}' already held at "
                "the call site — threading.Lock is not reentrant; this "
                "blocks forever on first execution",
                symbol=symbol,
            )

        adj: dict = {}
        nodes: set = set()
        for (a, b), _w in edges.items():
            adj.setdefault(a, set()).add(b)
            nodes.add(a)
            nodes.add(b)
        for comp in _sccs(nodes, adj):
            if len(comp) < 2:
                continue
            comp_set = set(comp)
            witnesses = sorted(
                (w for (a, b), w in edges.items()
                 if a in comp_set and b in comp_set),
                key=lambda w: (w[0], w[1]),
            )
            names = " <-> ".join(
                sorted(graph.lock_name(lid) for lid in comp)
            )
            detail = "; ".join(
                f"{w[2]} at {w[0]}:{w[1]} ({w[3]})" for w in witnesses[:4]
            )
            first = witnesses[0]
            yield Finding(
                first[0], first[1], self.rule_id,
                f"lock-order cycle between {{{names}}} — two threads "
                "taking these locks in opposite orders deadlock; order "
                f"them consistently or split the critical sections "
                f"[witnesses: {detail}]",
                symbol=first[2],
            )
