"""LT007 — blocking work reachable while a known lock is held.

The exact PR-6 bug class: :class:`~land_trendr_tpu.io.blockstore.
BlockStore`'s segment flush originally wrote multi-MiB data files while
holding the instance lock, stalling every decode thread's ``get``/``put``
behind a disk write — invisible to tests (artifacts identical), paid in
tail latency on every tiered machine.  The fix pattern (detach the batch,
write outside the lock, commit under it) is a design idiom this rule
makes mandatory: **no blocking operation while a lintkit-known lock is
held**, where "reachable" includes resolved calls — a lock-held call into
a function whose transitive summary blocks is the same bug wearing a
function boundary.

Blocking operations (see :mod:`.callgraph`'s primitive table): file and
socket IO (``open``, ``os.write``/``read``/``fsync``, ``mmap.mmap``,
``.recv``/``.send``/…, file-handle ``.read``/``.write``), device
transfers and waits (``device_put``, ``device_get``,
``block_until_ready``), ``Future.result()``, ``sleep``, ``subprocess``,
thread ``.join()``, ``Event``/``Condition`` ``.wait()``, and executor /
server ``.shutdown()`` (unless ``wait=False``).

Exemptions, each load-bearing:

* **Condition.wait on the held lock** — ``Condition(self._lock)``
  aliases the wrapped lock, and ``wait`` *releases* it for the
  duration: the sanctioned dispatcher pattern
  (``serve/server.py::_next_job``) is not a finding.  A ``wait`` on a
  condition wrapping some *other* lock still is.
* **construction-only functions** — a function reachable only from
  ``__init__`` holds its lock uncontended (nothing else can see the
  object yet); ``BlockStore._load``'s under-lock recovery scan is the
  canonical example.  This is LT001's ``__init__`` exemption carried
  through the call graph.
* **``*_locked`` convention** — the suffix documents "caller holds the
  lock", so the body is checked as if a lock were held even when no
  ``with`` is visible: blocking work inside ``_foo_locked`` is a finding
  at the operation, not at every caller.

Deliberate serialization locks (a lock whose entire purpose is to order
IO, like the event log's append lock or the store's one-flush-at-a-time
lock) are baselined with their rationale, not exempted structurally —
the next reader should find the justification written down.

Scope: ``tests/`` is excluded (fixtures model violations on purpose).
"""

from __future__ import annotations

from typing import Iterator

from land_trendr_tpu.lintkit.callgraph import get_graph
from land_trendr_tpu.lintkit.core import Checker, Finding, RepoCtx

__all__ = ["BlockingUnderLockChecker"]


class BlockingUnderLockChecker(Checker):
    rule_id = "LT007"
    title = "blocking operation reachable while a lock is held"

    def inputs(self, repo: RepoCtx) -> "set[str] | None":
        return {f for f in repo.py_files if not f.startswith("tests/")}

    def check(self, repo: RepoCtx) -> Iterator[Finding]:
        graph = get_graph(repo)
        seen: set = set()
        for info in graph.functions():
            if info.file.startswith("tests/"):
                continue
            symbol = f"{info.cls}.{info.name}" if info.cls else info.name
            convention = info.locked_convention
            if graph.construction_only(info.qname):
                continue
            for op in info.blocking:
                if not op.held and not convention:
                    continue
                lock = (
                    graph.lock_name(op.held[-1])
                    if op.held
                    else "the caller's lock (*_locked convention)"
                )
                key = (info.file, op.line, op.desc)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    info.file, op.line, self.rule_id,
                    f"{op.desc} while holding '{lock}' — blocking work "
                    "under a lock stalls every thread contending for it; "
                    "move the IO/wait outside the critical section "
                    "(detach-then-commit) or record the serialization "
                    "rationale in the baseline",
                    symbol=symbol,
                )
            for site in info.calls:
                if not site.held and not convention:
                    continue
                for callee in site.resolved:
                    if callee == info.qname:
                        continue
                    cinfo = graph.funcs.get(callee)
                    if cinfo is not None and graph.construction_only(callee):
                        continue
                    chain = graph.blocking_chain(callee)
                    if chain is None:
                        continue
                    desc, line, path = chain
                    lock = (
                        graph.lock_name(site.held[-1])
                        if site.held
                        else "the caller's lock (*_locked convention)"
                    )
                    via = " -> ".join(
                        q.split("::", 1)[-1] for q in path
                    )
                    key = (info.file, site.line, callee)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Finding(
                        info.file, site.line, self.rule_id,
                        f"call to {site.label}() blocks ({desc} at "
                        f"{path[-1].split('::', 1)[0]}:{line} via {via}) "
                        f"while holding '{lock}' — the lock is held "
                        "across the whole call; restructure so the "
                        "blocking step runs outside the critical section",
                        symbol=symbol,
                    )
                    break  # one finding per call site, not per candidate
