"""LT003 — no side effects inside (or reachable from) jitted code.

A ``jax.jit``/``pjit`` function's Python body runs once per compilation,
then never again: a ``print``, file write, telemetry emit, lock
acquisition, or global mutation inside it fires at trace time only (or
worse, at every retrace, on no schedule the author controls).  The
massively-parallel hot loop stays fast precisely because the jitted
tile program is pure (ROADMAP north star; the pack program in
``runtime/fetch.py`` is the canonical example — one traced bitcast
pipeline, zero host effects).

Detection: a function is **jitted** when decorated with ``jax.jit`` /
``pjit`` / bare ``jit``, directly or through
``functools.partial(jax.jit, ...)`` / ``jax.jit(...)`` calls.  The rule
then walks the jitted function AND every same-module function reachable
from it by direct name calls (one static call graph per module — the
cross-module closure would mostly re-traverse jax itself).  Flagged
effects, per the invariant's list:

* ``print(...)`` calls;
* file I/O — ``open(...)`` and any ``os.*`` call;
* telemetry — any ``*.emit(...)`` call;
* lock acquisition — ``with <lock>`` (a ``threading`` primitive named
  ``*lock*``) or ``.acquire()``/``.release()`` calls;
* global mutation — assignment to a ``global``-declared name.

``jax.debug.print``/``jax.debug.callback`` are the sanctioned traced
side-channels and are not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from land_trendr_tpu.lintkit.core import Checker, FileCtx, Finding

__all__ = ["JitPurityChecker"]

_JIT_NAMES = ("jit", "pjit")


def _names_jit(expr: ast.AST) -> bool:
    """Does this decorator (sub)expression name a jit transform?"""
    if isinstance(expr, ast.Name):
        return expr.id in _JIT_NAMES
    if isinstance(expr, ast.Attribute):
        return expr.attr in _JIT_NAMES
    if isinstance(expr, ast.Call):
        # functools.partial(jax.jit, ...) or jax.jit(static_argnames=...)
        if _names_jit(expr.func):
            return True
        return any(_names_jit(a) for a in expr.args)
    return False


def _is_jitted(fn: ast.FunctionDef) -> bool:
    return any(_names_jit(d) for d in fn.decorator_list)


def _is_debug_attr(fn: ast.AST) -> bool:
    """``jax.debug.print`` / ``jax.debug.callback`` — sanctioned."""
    return (
        isinstance(fn, ast.Attribute)
        and isinstance(fn.value, ast.Attribute)
        and fn.value.attr == "debug"
    )


def _impurities(fn: ast.FunctionDef) -> Iterator[tuple[int, str]]:
    """Yield ``(line, description)`` for each side effect in ``fn``."""
    global_names = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            global_names.update(node.names)
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                if f.id == "print":
                    yield node.lineno, "print() call"
                elif f.id == "open":
                    yield node.lineno, "open() file I/O"
            elif isinstance(f, ast.Attribute):
                base = f.value.id if isinstance(f.value, ast.Name) else None
                if base == "os":
                    yield node.lineno, f"os.{f.attr}() file/process effect"
                elif f.attr == "emit" and not _is_debug_attr(f):
                    yield node.lineno, ".emit() telemetry call"
                elif f.attr in ("acquire", "release"):
                    yield node.lineno, f".{f.attr}() lock operation"
        elif isinstance(node, ast.With):
            for item in node.items:
                ce = item.context_expr
                name = (
                    ce.attr if isinstance(ce, ast.Attribute)
                    else ce.id if isinstance(ce, ast.Name) else ""
                )
                if "lock" in name.lower():
                    yield node.lineno, f"'with {name}' lock acquisition"
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id in global_names:
                    yield node.lineno, f"mutation of global '{t.id}'"


class JitPurityChecker(Checker):
    rule_id = "LT003"
    title = "side effect inside (or reachable from) a jitted function"

    def check_file(self, ctx: FileCtx) -> Iterator[Finding]:
        assert ctx.tree is not None
        # module-level function table for the reachability closure
        functions: dict[str, ast.FunctionDef] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef):
                functions.setdefault(node.name, node)

        def callees(fn: ast.FunctionDef) -> set:
            return {
                n.func.id
                for n in ast.walk(fn)
                if isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
            }

        reported: set = set()  # (line, what): one finding per site, not per root
        for fn in functions.values():
            if not _is_jitted(fn):
                continue
            # reachable same-module functions, jitted root first
            seen = {fn.name}
            frontier = [fn]
            chain: list[ast.FunctionDef] = []
            while frontier:
                cur = frontier.pop()
                chain.append(cur)
                for name in callees(cur):
                    if name in functions and name not in seen:
                        seen.add(name)
                        frontier.append(functions[name])
            for reached in chain:
                via = (
                    "" if reached is fn
                    else f" (in '{reached.name}', reachable from it)"
                )
                for line, what in _impurities(reached):
                    if (line, what) in reported:
                        continue
                    reported.add((line, what))
                    yield Finding(
                        ctx.path, line, self.rule_id,
                        f"{what} inside jitted function '{fn.name}'{via} — "
                        "jitted bodies run at trace time only; side effects "
                        "fire never or on every retrace",
                    )
