"""LT010 — wall-clock and monotonic-clock values must not mix.

The repo's clock convention (README §Clock domains, the PR-10/PR-16
principle): **monotonic** for spans and intervals measured within one
process, **wall** for anything that crosses hosts or lands in a durable
record, and the only sanctioned way between them is an ``(anchor_wall,
anchor_mono)`` pair — ``wall = anchor_wall + (t_mono - anchor_mono)``.
PR 16 fixed, by hand, a decision record that stored a monotonic ``now``
where the replay expected wall time; this rule is that bug class made
un-reintroducible.

Mechanics (:mod:`.dataflow`): ``time.time()`` seeds the ``wall`` label,
``time.monotonic()`` / ``perf_counter()`` seed ``mono``, and identifier
convention (``*_wall*`` / ``*mono*`` names) seeds both across function
boundaries the graph cannot resolve.  Labels flow through assignments,
arithmetic, tuple/dict stores and returns (resolved calls contribute
their callees' return labels via :class:`.dataflow.ReturnLabels`).  The
subtraction algebra is what makes the anchor idiom *naturally* clean:
``mono - mono`` and ``wall - wall`` are durations and drop both labels,
so ``anchor_wall + (t_mono - anchor_mono)`` never trips the rule —
only a genuine cross-domain ``-``/``+``/comparison does.

Findings:

* arithmetic or comparison between a pure-wall and a pure-mono value;
* the same record field (constant dict key / subscript / keyword /
  attribute) stored with pure-wall at one site and pure-mono at
  another, within a file — the "taint crosses a dict store" case;
* a field whose *name* declares a domain (``*_wall*`` / ``*mono*``)
  stored with a value from the other domain.

Values that carry BOTH labels (an anchor pair travelling as a tuple)
are ambiguous, not mixed — they never flag, so precision is lost toward
silence, never toward noise.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from land_trendr_tpu.lintkit.callgraph import get_graph
from land_trendr_tpu.lintkit.core import Checker, Finding, RepoCtx
from land_trendr_tpu.lintkit.dataflow import (
    EMPTY,
    FunctionFlow,
    ReturnLabels,
    dotted_call,
)

__all__ = ["ClockDomainChecker"]

WALL = "wall"
MONO = "mono"

_WALL_CALLS = {"time.time", "time.time_ns"}
_MONO_CALLS = {
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
}

_WALL_NAME = re.compile(r"(^|_)wall(_|$|s$)")
_MONO_NAME = re.compile(r"(^|_)mono(tonic)?(_|$|s$)|(^|_)perf(_|$)")

#: predicate/flag identifiers are ABOUT a clock, not OF one: ``has_wall``
#: / ``is_mono`` / ``use_wall`` hold booleans and must not seed a domain
_PREDICATE_NAME = re.compile(r"^(has|is|use|want|need|with)_")


def _name_domain(ident: str) -> frozenset:
    low = ident.lower()
    if _PREDICATE_NAME.match(low):
        return EMPTY
    if _MONO_NAME.search(low):
        return frozenset((MONO,))
    if _WALL_NAME.search(low):
        return frozenset((WALL,))
    return EMPTY


def _seeds(node: ast.AST) -> frozenset:
    if isinstance(node, ast.Call):
        name = dotted_call(node)
        if name in _WALL_CALLS:
            return frozenset((WALL,))
        if name in _MONO_CALLS:
            return frozenset((MONO,))
        return EMPTY
    if isinstance(node, ast.Name):
        return _name_domain(node.id)
    if isinstance(node, ast.Attribute):
        return _name_domain(node.attr)
    return EMPTY


def _pure(labels: frozenset) -> "str | None":
    """The one domain ``labels`` carries, or None (empty or ambiguous)."""
    if labels & {WALL, MONO} == {WALL}:
        return WALL
    if labels & {WALL, MONO} == {MONO}:
        return MONO
    return None


def _combine(node: ast.AST, left: frozenset, right: frozenset) -> frozenset:
    """BinOp label algebra: same-domain subtraction yields a duration
    (labels drop), everything else unions (a cross-domain op stays
    poisoned so the *site* flags, see :meth:`ClockDomainChecker`)."""
    if isinstance(node, (ast.BinOp, ast.AugAssign)) and isinstance(
        node.op, ast.Sub
    ):
        lp, rp = _pure(left), _pure(right)
        if lp is not None and lp == rp:
            return (left | right) - {WALL, MONO}
    return left | right


def _src(node: ast.AST) -> str:
    try:
        text = ast.unparse(node)
    except Exception:
        return "<expr>"
    return text if len(text) <= 60 else text[:57] + "..."


def _own_nodes(func: ast.AST) -> Iterator[ast.AST]:
    """Walk ``func``'s body without descending into nested defs (those
    are graph functions of their own)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class ClockDomainChecker(Checker):
    rule_id = "LT010"
    title = "wall/monotonic clock domains mixed"

    def inputs(self, repo: RepoCtx) -> "set[str] | None":
        return {f for f in repo.py_files if not f.startswith("tests/")}

    def check(self, repo: RepoCtx) -> Iterator[Finding]:
        graph = get_graph(repo)
        returns = ReturnLabels(graph, _seeds, _combine)
        # field -> domain -> first (file, line, src) witness, per file
        file_fields: dict[str, dict] = {}
        for info in graph.functions():
            if info.file.startswith("tests/"):
                continue
            flow = FunctionFlow(
                info.node, _seeds, combine=_combine,
                calls=lambda c, _i=info: returns.call_labels(_i, c),
            )
            symbol = f"{info.cls}.{info.name}" if info.cls else info.name
            yield from self._check_arith(info, flow, symbol)
            fields = file_fields.setdefault(info.file, {})
            yield from self._check_stores(info, flow, symbol, fields)
        yield from self._cross_function(file_fields)

    # -- arithmetic / comparison sites -------------------------------------
    def _check_arith(self, info, flow, symbol) -> Iterator[Finding]:
        for n in _own_nodes(info.node):
            if isinstance(n, ast.BinOp) and isinstance(
                n.op, (ast.Add, ast.Sub)
            ):
                pairs = [(n.left, n.right)]
            elif isinstance(n, ast.Compare):
                operands = [n.left, *n.comparators]
                pairs = list(zip(operands, operands[1:]))
            else:
                continue
            for left, right in pairs:
                lp = _pure(flow.labels(left))
                rp = _pure(flow.labels(right))
                if lp is None or rp is None or lp == rp:
                    continue
                op = (
                    "compared with"
                    if isinstance(n, ast.Compare)
                    else "combined with"
                )
                yield Finding(
                    file=info.file,
                    line=n.lineno,
                    rule_id=self.rule_id,
                    message=(
                        f"{lp}-clock value '{_src(left)}' {op} "
                        f"{rp}-clock value '{_src(right)}' — convert "
                        "through an (anchor_wall, anchor_mono) pair "
                        "instead"
                    ),
                    symbol=symbol,
                )

    # -- record-field stores ----------------------------------------------
    def _check_stores(self, info, flow, symbol, fields) -> Iterator[Finding]:
        local: dict[str, dict] = {}
        for store, labels in flow.field_stores():
            dom = _pure(labels)
            if dom is None:
                continue
            witness = (info.file, store.node.lineno, _src(store.node),
                       symbol)
            declared = _pure(_name_domain(store.field))
            if declared is not None and declared != dom:
                yield Finding(
                    file=info.file,
                    line=store.node.lineno,
                    rule_id=self.rule_id,
                    message=(
                        f"field '{store.field}' declares the {declared} "
                        f"domain but is stored a {dom}-clock value "
                        f"'{_src(store.node)}'"
                    ),
                    symbol=symbol,
                )
                continue
            key = store.field
            local.setdefault(key, {}).setdefault(dom, witness)
            fields.setdefault(key, {}).setdefault(dom, witness)
        for field, doms in local.items():
            if WALL in doms and MONO in doms:
                wfile, wline, wsrc, _ = doms[WALL]
                _, mline, msrc, _ = doms[MONO]
                yield Finding(
                    file=wfile,
                    line=max(wline, mline),
                    rule_id=self.rule_id,
                    message=(
                        f"record field '{field}' stores wall-clock "
                        f"'{wsrc}' (line {wline}) and monotonic "
                        f"'{msrc}' (line {mline}) — one field, one "
                        "clock domain"
                    ),
                    symbol=symbol,
                )
                # reported locally; do not re-report at file level
                doms.pop(MONO, None)
                if field in fields:
                    fields[field].pop(MONO, None)

    def _cross_function(self, file_fields) -> Iterator[Finding]:
        for file, fields in sorted(file_fields.items()):
            for field, doms in sorted(fields.items()):
                if WALL not in doms or MONO not in doms:
                    continue
                wfile, wline, wsrc, wsym = doms[WALL]
                _, mline, msrc, msym = doms[MONO]
                if (wsym, wline) == (msym, mline):
                    continue
                yield Finding(
                    file=file,
                    line=max(wline, mline),
                    rule_id=self.rule_id,
                    message=(
                        f"record field '{field}' stores wall-clock "
                        f"'{wsrc}' in {wsym} (line {wline}) but "
                        f"monotonic '{msrc}' in {msym} (line {mline}) "
                        "— readers cannot tell which clock they got"
                    ),
                    symbol=msym if mline >= wline else wsym,
                )
