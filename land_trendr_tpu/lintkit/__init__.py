"""lt-lint: AST-based invariant checks for the concurrent subsystems.

Five repo-specific rules over a small parent-linked-AST framework
(:mod:`.core`); the CLI is ``tools/lt_lint.py`` (``--json``,
``--changed``, exit 1 on any finding not suppressed by an inline
``# lt: noqa[rule]`` or a reasoned ``LINT_BASELINE.json`` entry):

========  ==========================================================
LT001     shared state mutated / snapshot-read outside its lock
LT002     blocking host sync outside ``runtime/fetch.py``
LT003     side effects inside (or reachable from) jitted functions
LT004     RunConfig ↔ CLI flag ↔ README-table coupling
LT005     Telemetry emit-site fields vs the event schema
========  ==========================================================

See README.md §Static analysis for the rule table with rationale and
example findings.
"""

from land_trendr_tpu.lintkit.configdoc import ConfigDocChecker
from land_trendr_tpu.lintkit.core import (
    Baseline,
    BaselineError,
    Checker,
    FileCtx,
    Finding,
    RepoCtx,
    run_rules,
)
from land_trendr_tpu.lintkit.eventschema import EventSchemaChecker
from land_trendr_tpu.lintkit.hostsync import HostSyncChecker
from land_trendr_tpu.lintkit.jitpurity import JitPurityChecker
from land_trendr_tpu.lintkit.locks import LockDisciplineChecker

__all__ = [
    "ALL_CHECKERS",
    "Baseline",
    "BaselineError",
    "Checker",
    "ConfigDocChecker",
    "EventSchemaChecker",
    "FileCtx",
    "Finding",
    "HostSyncChecker",
    "JitPurityChecker",
    "LockDisciplineChecker",
    "RepoCtx",
    "default_checkers",
    "run_rules",
]

#: rule classes in rule-id order — the CLI's default set
ALL_CHECKERS = (
    LockDisciplineChecker,
    HostSyncChecker,
    JitPurityChecker,
    ConfigDocChecker,
    EventSchemaChecker,
)


def default_checkers() -> list:
    """Fresh instances of every rule (some cache schema state)."""
    return [cls() for cls in ALL_CHECKERS]
