"""lt-lint: AST-based invariant checks for the concurrent subsystems.

Twelve repo-specific rules over a parent-linked-AST framework
(:mod:`.core`), an interprocedural call-graph engine
(:mod:`.callgraph`) and an intra-procedural taint/value-flow engine
(:mod:`.dataflow`); the CLI is ``tools/lt_lint.py`` (``--json``,
``--sarif``, ``--changed``, ``--prune-baseline``, exit 1 on any finding
not suppressed by an inline ``# lt: noqa[rule]`` or a reasoned
``LINT_BASELINE.json`` entry):

========  ==========================================================
LT001     shared state mutated / snapshot-read outside its lock
LT002     blocking host sync outside ``runtime/fetch.py``
LT003     side effects inside (or reachable from) jitted functions
LT004     RunConfig ↔ CLI flag ↔ README-table coupling
LT005     Telemetry emit-site fields vs the event schema
LT006     lock-order cycles in the acquired-while-held graph
LT007     blocking operation reachable while a lock is held
LT008     resource not discharged (close/stop/shutdown) on every path
LT009     registered pure decision machine reaches an impure primitive
LT010     wall/monotonic clock domains mixed (taint through dataflow)
LT011     fault-seam registry / fire-site / soak-coverage drift
LT012     non-atomic write into a durable artifact tree
========  ==========================================================

LT001–LT005 are statement-local; LT006–LT008 share one project call
graph per run (resolved within the package, method dispatch approximated
by receiver-type inference + attribute-name/class-index matching);
LT009–LT012 are the distributed-determinism generation, driven by the
:mod:`.dataflow` value-flow engine composed with the same call graph
and the data registries the checked modules export (``PURE_MACHINES``,
``SEAMS``, ``SOAK_COVERED_SEAMS``).  See README.md §Static analysis for
the rule table with rationale and example findings.
"""

from land_trendr_tpu.lintkit.blocking import BlockingUnderLockChecker
from land_trendr_tpu.lintkit.clockdomain import ClockDomainChecker
from land_trendr_tpu.lintkit.configdoc import ConfigDocChecker
from land_trendr_tpu.lintkit.core import (
    Baseline,
    BaselineError,
    Checker,
    FileCtx,
    Finding,
    RepoCtx,
    run_rules,
)
from land_trendr_tpu.lintkit.durablewrite import DurableWriteChecker
from land_trendr_tpu.lintkit.eventschema import EventSchemaChecker
from land_trendr_tpu.lintkit.hostsync import HostSyncChecker
from land_trendr_tpu.lintkit.jitpurity import JitPurityChecker
from land_trendr_tpu.lintkit.lifecycle import ResourceLifecycleChecker
from land_trendr_tpu.lintkit.lockorder import LockOrderChecker
from land_trendr_tpu.lintkit.locks import LockDisciplineChecker
from land_trendr_tpu.lintkit.replaypurity import ReplayPurityChecker
from land_trendr_tpu.lintkit.seamcover import SeamCoverageChecker

__all__ = [
    "ALL_CHECKERS",
    "Baseline",
    "BaselineError",
    "BlockingUnderLockChecker",
    "Checker",
    "ClockDomainChecker",
    "ConfigDocChecker",
    "DurableWriteChecker",
    "EventSchemaChecker",
    "FileCtx",
    "Finding",
    "HostSyncChecker",
    "JitPurityChecker",
    "LockDisciplineChecker",
    "LockOrderChecker",
    "RepoCtx",
    "ReplayPurityChecker",
    "ResourceLifecycleChecker",
    "SeamCoverageChecker",
    "default_checkers",
    "run_rules",
]

#: rule classes in rule-id order — the CLI's default set
ALL_CHECKERS = (
    LockDisciplineChecker,
    HostSyncChecker,
    JitPurityChecker,
    ConfigDocChecker,
    EventSchemaChecker,
    LockOrderChecker,
    BlockingUnderLockChecker,
    ResourceLifecycleChecker,
    ReplayPurityChecker,
    ClockDomainChecker,
    SeamCoverageChecker,
    DurableWriteChecker,
)


def default_checkers() -> list:
    """Fresh instances of every rule (some cache schema state)."""
    return [cls() for cls in ALL_CHECKERS]
