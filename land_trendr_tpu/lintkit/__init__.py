"""lt-lint: AST-based invariant checks for the concurrent subsystems.

Eight repo-specific rules over a parent-linked-AST framework
(:mod:`.core`) and an interprocedural call-graph engine
(:mod:`.callgraph`); the CLI is ``tools/lt_lint.py`` (``--json``,
``--sarif``, ``--changed``, ``--prune-baseline``, exit 1 on any finding
not suppressed by an inline ``# lt: noqa[rule]`` or a reasoned
``LINT_BASELINE.json`` entry):

========  ==========================================================
LT001     shared state mutated / snapshot-read outside its lock
LT002     blocking host sync outside ``runtime/fetch.py``
LT003     side effects inside (or reachable from) jitted functions
LT004     RunConfig ↔ CLI flag ↔ README-table coupling
LT005     Telemetry emit-site fields vs the event schema
LT006     lock-order cycles in the acquired-while-held graph
LT007     blocking operation reachable while a lock is held
LT008     resource not discharged (close/stop/shutdown) on every path
========  ==========================================================

LT001–LT005 are statement-local; LT006–LT008 share one project call
graph per run (resolved within the package, method dispatch approximated
by receiver-type inference + attribute-name/class-index matching).  See
README.md §Static analysis for the rule table with rationale and
example findings.
"""

from land_trendr_tpu.lintkit.blocking import BlockingUnderLockChecker
from land_trendr_tpu.lintkit.configdoc import ConfigDocChecker
from land_trendr_tpu.lintkit.core import (
    Baseline,
    BaselineError,
    Checker,
    FileCtx,
    Finding,
    RepoCtx,
    run_rules,
)
from land_trendr_tpu.lintkit.eventschema import EventSchemaChecker
from land_trendr_tpu.lintkit.hostsync import HostSyncChecker
from land_trendr_tpu.lintkit.jitpurity import JitPurityChecker
from land_trendr_tpu.lintkit.lifecycle import ResourceLifecycleChecker
from land_trendr_tpu.lintkit.lockorder import LockOrderChecker
from land_trendr_tpu.lintkit.locks import LockDisciplineChecker

__all__ = [
    "ALL_CHECKERS",
    "Baseline",
    "BaselineError",
    "BlockingUnderLockChecker",
    "Checker",
    "ConfigDocChecker",
    "EventSchemaChecker",
    "FileCtx",
    "Finding",
    "HostSyncChecker",
    "JitPurityChecker",
    "LockDisciplineChecker",
    "LockOrderChecker",
    "RepoCtx",
    "ResourceLifecycleChecker",
    "default_checkers",
    "run_rules",
]

#: rule classes in rule-id order — the CLI's default set
ALL_CHECKERS = (
    LockDisciplineChecker,
    HostSyncChecker,
    JitPurityChecker,
    ConfigDocChecker,
    EventSchemaChecker,
    LockOrderChecker,
    BlockingUnderLockChecker,
    ResourceLifecycleChecker,
)


def default_checkers() -> list:
    """Fresh instances of every rule (some cache schema state)."""
    return [cls() for cls in ALL_CHECKERS]
