"""LT011 — the fault-seam registry, the tree, and the soak must agree.

A fault seam is only as real as three facts staying true at once:

1. every seam string *fired* in the tree (``faults.check("dispatch")``,
   ``fault_check("feed.decode")``, ``plan.fired(...)``) is registered in
   ``runtime/faults.py``'s ``SEAMS`` — an unregistered name is a
   silently dead injection (``FaultPlan`` validates *schedules*, but a
   host-side typo just never fires);
2. every registered seam is fired somewhere in ``land_trendr_tpu/`` —
   a seam nobody fires is documentation, not coverage;
3. every registered seam is exercised by a ``tools/fault_soak.py`` case
   — cross-checked against the tool's exported
   ``SOAK_COVERED_SEAMS`` data table (the ``NONNEG_FIELDS`` pattern;
   the linter literal-evals it rather than importing a numpy-loading
   tool) — or carries a baselined reason.  Zero silent gaps.

The soak table is itself checked both ways: a ``SOAK_COVERED_SEAMS``
entry naming an unregistered seam is stale and flagged
(``tests/test_faults.py`` pins the table against the soak's actual case
schedules from the other side).

PAPERS.md's *Massively-Parallel Break Detection* is the
ROADMAP-item-2 algorithm about to multiply emit sites and seams; this
rule exists so each new one arrives registered, fired and soaked.
"""

from __future__ import annotations

import ast
from typing import Iterator

from land_trendr_tpu.lintkit.core import Checker, Finding, RepoCtx
from land_trendr_tpu.lintkit.dataflow import dotted_call, module_literal

__all__ = ["SeamCoverageChecker"]

REGISTRY_FILE = "land_trendr_tpu/runtime/faults.py"
SOAK_FILE = "tools/fault_soak.py"

#: call forms that fire a seam with a constant first argument: the
#: module-level / plan-method APIs and the io-layer hook names
#: (``blockcache.fault_check`` / ``fault_corrupt``)
_FIRE_TERMINALS = {"check", "fired", "corrupt", "fault_check",
                   "fault_corrupt"}

#: receivers trusted to be a faults module / plan when the terminal is
#: the generic check/fired/corrupt (a bare ``check(...)`` in some tool
#: is NOT a seam fire)
_FIRE_RECEIVERS = ("faults", "plan", "_plan", "fault")


def _fire_site(call: ast.Call) -> "str | None":
    """The seam string this call fires, or None when it is not a
    seam-firing form."""
    if not call.args:
        return None
    arg = call.args[0]
    if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
        return None
    name = dotted_call(call)
    if not name:
        return None
    parts = name.split(".")
    terminal = parts[-1]
    if terminal not in _FIRE_TERMINALS:
        return None
    if terminal in ("fault_check", "fault_corrupt"):
        return arg.value
    receiver = parts[-2] if len(parts) >= 2 else ""
    if any(r in receiver for r in _FIRE_RECEIVERS) or receiver == "self":
        return arg.value
    return None


def _assign_line(tree: "ast.AST | None", name: str) -> int:
    if tree is not None:
        for stmt in tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, ast.AnnAssign):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return stmt.lineno
    return 1


class SeamCoverageChecker(Checker):
    rule_id = "LT011"
    title = "fault-seam registry / fire-site / soak-coverage drift"

    def inputs(self, repo: RepoCtx) -> "set[str] | None":
        return {
            f for f in repo.py_files
            if f.startswith("land_trendr_tpu/") or f == SOAK_FILE
        }

    def check(self, repo: RepoCtx) -> Iterator[Finding]:
        if not repo.exists(REGISTRY_FILE):
            return
        reg_tree = repo.file(REGISTRY_FILE).tree
        seams = module_literal(reg_tree, "SEAMS")
        if not seams:
            yield Finding(
                file=REGISTRY_FILE, line=1, rule_id=self.rule_id,
                message="SEAMS registry missing or not a literal tuple",
                symbol="<registry>",
            )
            return
        seams = tuple(seams)
        reg_line = _assign_line(reg_tree, "SEAMS")

        # -- 1. every fire site names a registered seam --------------------
        fired: dict[str, list] = {}
        for relpath in repo.py_files:
            if not relpath.startswith("land_trendr_tpu/"):
                continue
            if relpath == REGISTRY_FILE:
                continue  # the registry's own APIs take the seam as a param
            ctx = repo.file(relpath)
            if ctx.tree is None:
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                seam = _fire_site(node)
                if seam is None:
                    continue
                fired.setdefault(seam, []).append((relpath, node.lineno))
                if seam not in seams:
                    yield Finding(
                        file=relpath,
                        line=node.lineno,
                        rule_id=self.rule_id,
                        message=(
                            f"fires unregistered fault seam {seam!r} — "
                            "add it to runtime/faults.py SEAMS or fix "
                            "the typo (an unregistered seam is a "
                            "silently dead injection)"
                        ),
                    )

        # -- 2. every registered seam is fired somewhere -------------------
        for seam in seams:
            if seam not in fired:
                yield Finding(
                    file=REGISTRY_FILE,
                    line=reg_line,
                    rule_id=self.rule_id,
                    message=(
                        f"registered seam {seam!r} is never fired in "
                        "land_trendr_tpu/ — dead registry entry"
                    ),
                    symbol="<registry>",
                )

        # -- 3. soak coverage ---------------------------------------------
        if not repo.exists(SOAK_FILE):
            return
        soak_tree = repo.file(SOAK_FILE).tree
        covered = module_literal(soak_tree, "SOAK_COVERED_SEAMS")
        soak_line = _assign_line(soak_tree, "SOAK_COVERED_SEAMS")
        if covered is None:
            yield Finding(
                file=SOAK_FILE, line=1, rule_id=self.rule_id,
                message=(
                    "SOAK_COVERED_SEAMS data table missing — LT011 "
                    "cannot cross-check soak coverage"
                ),
                symbol="<registry>",
            )
            return
        covered = tuple(covered)
        for seam in seams:
            if seam not in covered:
                yield Finding(
                    file=SOAK_FILE,
                    line=soak_line,
                    rule_id=self.rule_id,
                    message=(
                        f"registered seam {seam!r} has no fault_soak "
                        "case (not in SOAK_COVERED_SEAMS) — back-fill "
                        "a case or baseline this with the reason"
                    ),
                    symbol="<registry>",
                )
        for seam in covered:
            if seam not in seams:
                yield Finding(
                    file=SOAK_FILE,
                    line=soak_line,
                    rule_id=self.rule_id,
                    message=(
                        f"SOAK_COVERED_SEAMS names {seam!r} which is "
                        "not a registered seam — stale table entry"
                    ),
                    symbol="<registry>",
                )
